"""Bench trajectory differ: align committed BENCH rounds, flag regressions.

The repo commits its bench history as ``BENCH_*.json`` rounds (the
driver's ``BENCH_rNN.json`` capture files and hand-promoted hardware
rounds like ``BENCH_HW_r4.json``), but nothing reads them back — a perf
regression only surfaces when someone eyeballs two JSON files. This tool
makes the history executable, the way CI perf gates diff benchmark
archives:

- **Load + align.** Every round file is parsed into ``{metric: row}``
  regardless of shape: the capture shape (``{"n", "cmd", "rc", "tail",
  "parsed"}`` — every ``{"metric": …}`` JSON line in the tail is
  extracted, later lines superseding earlier ones) and the flat hardware
  shape (one metric dict + an ``extras`` list). Metrics align by name
  across rounds in natural round order (r01 < r02 < … < r10).
- **Trajectory.** One line per metric: the value at every round that
  measured it, the delta of the newest comparable pair, and a verdict.
- **Regression flagging.** Direction is inferred from the unit (``ms`` /
  ``s`` / ``%`` → lower is better; ``…/s`` throughput → higher is
  better; unknown units are reported but never judged). The newest
  comparable sample is checked against the previous one; beyond
  ``--tolerance`` (default 10%) the metric is REGRESSED and the exit
  code is 2 — CI-usable. Probe rows whose value is ``null`` (a dead
  relay, a hung probe) are skipped, not judged.
- **Backend hygiene.** Rows labeled ``"backend": "cpu-fallback"``
  (FORMATS §12.2 — the same bench run on a machine with no accelerator)
  never enter a hardware comparison: a TPU round followed by a CPU
  round is a fleet change, not a regression. They still print, marked.

``bench.py --compare`` wraps this against the repo root; the module CLI
(``python -m celestia_app_tpu.tools.benchdiff``) takes any directory of
rounds. Exit codes: 0 clean, 2 regressions found, 1 usage error.
"""

from __future__ import annotations

import glob as globmod
import json
import os
import re

TOLERANCE = 0.10

_NUM_CHUNK = re.compile(r"(\d+)")

#: units where a larger value is an improvement
_HIGHER_UNITS = ("/s", "per_sec", "blocks/s", "proofs/s", "txs/s")
#: units where a smaller value is an improvement
_LOWER_UNITS = ("ms", "s", "%")


def _natural_key(label: str):
    return [int(c) if c.isdigit() else c
            for c in _NUM_CHUNK.split(label)]


def round_label(path: str) -> str:
    stem = os.path.splitext(os.path.basename(path))[0]
    return stem[len("BENCH_"):] if stem.startswith("BENCH_") else stem


def _metric_rows(doc) -> list[dict]:
    """Every metric row a round document carries, in document order."""
    rows: list[dict] = []
    if not isinstance(doc, dict):
        return rows
    if "metric" in doc:
        # flat hardware shape: the primary row + its extras list
        rows.append({k: v for k, v in doc.items() if k != "extras"})
        for extra in doc.get("extras") or []:
            if isinstance(extra, dict) and "metric" in extra:
                rows.append(extra)
        return rows
    # capture shape: JSON lines inside the tail, `parsed` as fallback
    for line in str(doc.get("tail", "")).splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            row = json.loads(line)
        except ValueError:
            continue
        if isinstance(row, dict) and "metric" in row:
            rows.append(row)
    if not rows and isinstance(doc.get("parsed"), dict) \
            and "metric" in doc["parsed"]:
        rows.append(doc["parsed"])
    return rows


def load_round(path: str) -> dict:
    """{metric: row} for one round file; later rows supersede earlier
    ones (the capture tail repeats a metric as probes retry)."""
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    out: dict[str, dict] = {}
    for row in _metric_rows(doc):
        out[str(row["metric"])] = row
    return out


def load_rounds(paths: list[str]) -> list[tuple[str, dict]]:
    """[(label, {metric: row})] in natural round order."""
    rounds = [(round_label(p), load_round(p)) for p in paths]
    rounds.sort(key=lambda lr: _natural_key(lr[0]))
    return rounds


def direction_of(metric: str, unit: str | None) -> str | None:
    """'lower' | 'higher' | None (unknown — never judged)."""
    u = (unit or "").strip()
    if any(h in u for h in _HIGHER_UNITS) or "per_sec" in metric:
        return "higher"
    if u in _LOWER_UNITS:
        return "lower"
    return None


def _comparable(row: dict) -> bool:
    """A sample that may enter a hardware comparison: numeric value,
    not flagged as the CPU fallback of a hardware bench."""
    v = row.get("value")
    if not isinstance(v, (int, float)) or isinstance(v, bool):
        return False
    return row.get("backend") != "cpu-fallback"


def diff(rounds: list[tuple[str, dict]],
         tolerance: float = TOLERANCE) -> dict:
    """Align metrics across rounds and judge the newest comparable pair
    of each. Returns the machine report (also what --json prints):

      {"rounds": [labels], "tolerance": f,
       "metrics": {name: {"unit", "direction", "samples":
                          [{"round", "value", "backend"?, "skipped"?}],
                          "delta_pct", "status"}},
       "regressions": [names]}

    status: "ok" | "regressed" | "improved" | "n/a" (fewer than two
    comparable samples, or unknown direction)."""
    metrics: dict[str, dict] = {}
    for label, rows in rounds:
        for name, row in rows.items():
            m = metrics.setdefault(name, {"unit": None, "samples": []})
            if m["unit"] is None and row.get("unit"):
                m["unit"] = row["unit"]
            sample = {"round": label, "value": row.get("value")}
            if "backend" in row:
                sample["backend"] = row["backend"]
            if not _comparable(row):
                sample["skipped"] = True
            m["samples"].append(sample)
    regressions = []
    for name in sorted(metrics):
        m = metrics[name]
        m["direction"] = direction_of(name, m["unit"])
        usable = [s for s in m["samples"] if not s.get("skipped")]
        if len(usable) < 2 or m["direction"] is None:
            m["delta_pct"] = None
            m["status"] = "n/a"
            continue
        # judge only like against like: the newest sample vs the newest
        # PRIOR sample from the same backend class (a TPU round followed
        # by an unlabeled/axon round is a fleet change, not a perf move)
        newest = usable[-1]
        prior = next((s for s in reversed(usable[:-1])
                      if s.get("backend") == newest.get("backend")), None)
        if prior is None:
            m["delta_pct"] = None
            m["status"] = "n/a"
            continue
        prev, last = prior["value"], newest["value"]
        if prev == 0:
            m["delta_pct"] = None
            m["status"] = "n/a"
            continue
        delta = (last - prev) / abs(prev)
        m["delta_pct"] = round(delta * 100.0, 2)
        worse = delta > tolerance if m["direction"] == "lower" \
            else delta < -tolerance
        better = delta < -tolerance if m["direction"] == "lower" \
            else delta > tolerance
        m["status"] = ("regressed" if worse
                       else "improved" if better else "ok")
        if worse:
            regressions.append(name)
    return {
        "rounds": [label for label, _rows in rounds],
        "tolerance": tolerance,
        "metrics": {k: metrics[k] for k in sorted(metrics)},
        "regressions": regressions,
    }


def report_text(report: dict) -> str:
    lines = [f"rounds: {' '.join(report['rounds'])}   "
             f"tolerance: {report['tolerance'] * 100:.0f}%"]
    for name, m in report["metrics"].items():
        traj = " -> ".join(
            f"{s['value']}" + ("[cpu]" if s.get("backend") == "cpu-fallback"
                               else "" if not s.get("skipped") else "[skip]")
            for s in m["samples"])
        delta = (f"{m['delta_pct']:+.1f}%" if m["delta_pct"] is not None
                 else "  --")
        unit = m["unit"] or "?"
        lines.append(f"{m['status']:>9}  {delta:>8}  {name} [{unit}]: "
                     f"{traj}")
    if report["regressions"]:
        lines.append("REGRESSED: " + ", ".join(report["regressions"]))
    return "\n".join(lines)


def main(argv=None) -> int:
    import argparse
    import sys

    ap = argparse.ArgumentParser(prog="benchdiff")
    ap.add_argument("--dir", default=".",
                    help="directory holding the BENCH_*.json rounds")
    ap.add_argument("--glob", default="BENCH_*.json")
    ap.add_argument("--tolerance", type=float, default=TOLERANCE,
                    help="fractional regression tolerance (0.10 = 10%%)")
    ap.add_argument("--metric", default=None,
                    help="only this metric")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    paths = sorted(globmod.glob(os.path.join(args.dir, args.glob)))
    if not paths:
        print(f"ERROR: no rounds match {args.glob} in {args.dir}",
              file=sys.stderr)
        return 1
    try:
        report = diff(load_rounds(paths), tolerance=args.tolerance)
    except (OSError, ValueError) as e:
        print(f"ERROR: unreadable round file: {e}", file=sys.stderr)
        return 1
    if args.metric:
        report["metrics"] = {k: v for k, v in report["metrics"].items()
                             if k == args.metric}
        report["regressions"] = [r for r in report["regressions"]
                                 if r == args.metric]
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(report_text(report))
    return 2 if report["regressions"] else 0


if __name__ == "__main__":
    raise SystemExit(main())
