"""Per-file incremental result cache for ``run_analysis``.

One JSON document (default: ``.analyze_cache.json`` next to the
analyzed package root, gitignored — FORMATS §11.4), holding up to
``MAX_RUNS`` key-namespaced run slots so alternating run shapes (the
full pre-commit sweep vs a ``--rule``-filtered dev loop, or two sibling
roots sharing the parent directory) stay warm side by side instead of
evicting each other:

    {
      "version": 2,
      "runs": {
        "<key sha256 hex>": {
          "chain/app.py": {
            "sha": "<sha256 of the file bytes>",
            "violations": {"det-wallclock": [[line, col, msg], ...]},
            "fragment": {...callgraph.build_fragment...} | null
          }, ...
        }, ...
      }
    }

The ``key`` folds together everything that can change a per-file
result besides the file itself: the analyzed root's absolute path, the
config (its committed bytes, or a canonical dump for in-memory
configs), the rule-set *source* (sha256 over every
``tools/analyze/*.py``, so editing any rule auto-invalidates — no
hand-bumped version constant to forget), the fragment schema version,
the set of rules being run, and the Python minor version (AST shapes
differ). Per-file entries store violations post-pragma and
post-symbol-scope (both are functions of the keyed inputs) but
pre-waiver — waivers are applied at assembly so staleness accounting
stays global. Interprocedural rules are never cached: they re-link and
re-run from the (cached) fragments on every run, which is what keeps a
one-file edit's warm run both fast and byte-identical to cold.

Within a run slot, entries not touched in that run (deleted/renamed
files) are dropped on save; the current run's slot is re-inserted
last, and the oldest slots are trimmed past ``MAX_RUNS``. The write is
tmp+rename so a killed run never leaves a torn cache.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys

CACHE_VERSION = 2
MAX_RUNS = 4


def default_cache_path(root: str) -> str:
    """Next to (not inside) the analyzed package: for the installed
    package root that is the repo root's ``.analyze_cache.json``."""
    parent = os.path.dirname(os.path.abspath(root))
    return os.path.join(parent, ".analyze_cache.json")


def rules_source_hash() -> str:
    """sha256 over the analyze framework's own sources — editing any
    rule, the engine, or this module invalidates every cached result."""
    pkg_dir = os.path.dirname(os.path.abspath(__file__))
    h = hashlib.sha256()
    for name in sorted(os.listdir(pkg_dir)):
        if not name.endswith(".py"):
            continue
        h.update(name.encode())
        with open(os.path.join(pkg_dir, name), "rb") as f:
            h.update(hashlib.sha256(f.read()).digest())
    return h.hexdigest()


def config_hash(config) -> str:
    """Committed configs hash by file bytes; in-memory configs by a
    canonical dump of their dataclass fields."""
    if config.source_path and os.path.exists(config.source_path):
        with open(config.source_path, "rb") as f:
            return hashlib.sha256(f.read()).hexdigest()
    doc = {
        "exclude": list(config.exclude),
        "rules": {
            rid: {
                "severity": rc.severity,
                "include": list(rc.include),
                "exclude": list(rc.exclude),
                "allow": list(rc.allow),
                "options": rc.options,
            }
            for rid, rc in sorted(config.rules.items())
        },
        "waivers": [[w.rule, w.path, w.reason] for w in config.waivers],
    }
    return hashlib.sha256(
        json.dumps(doc, sort_keys=True).encode()).hexdigest()


def cache_key(config, rules_run: list[str], root: str) -> str:
    from celestia_app_tpu.tools.analyze.callgraph import FRAGMENT_VERSION

    h = hashlib.sha256()
    h.update(f"v{CACHE_VERSION}/frag{FRAGMENT_VERSION}/"
             f"py{sys.version_info[0]}.{sys.version_info[1]}/".encode())
    h.update(os.path.abspath(root).encode() + b"/")
    h.update(rules_source_hash().encode())
    h.update(config_hash(config).encode())
    h.update(",".join(sorted(rules_run)).encode())
    return h.hexdigest()


class ResultCache:
    def __init__(self, path: str, key: str, runs: dict):
        self.path = path
        self.key = key
        self._runs = runs                      # other keys' slots
        self._files = runs.pop(key, {})        # this run's slot
        self._touched: dict[str, dict] = {}
        self._dirty = False

    @classmethod
    def open(cls, path: str, key: str) -> "ResultCache":
        runs: dict = {}
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
            if (doc.get("version") == CACHE_VERSION
                    and isinstance(doc.get("runs"), dict)):
                runs = doc["runs"]
        except (OSError, ValueError):
            pass  # missing/torn cache = cold start, never an error
        return cls(path, key, runs)

    def lookup(self, rel: str, sha: str) -> dict | None:
        entry = self._files.get(rel)
        if entry is None or entry.get("sha") != sha:
            return None
        self._touched[rel] = entry
        return entry

    def put(self, rel: str, sha: str, violations: dict,
            fragment: dict | None) -> None:
        self._touched[rel] = {
            "sha": sha, "violations": violations, "fragment": fragment,
        }
        self._dirty = True

    def save(self) -> None:
        if not self._dirty and set(self._touched) == set(self._files):
            return
        runs = dict(self._runs)
        runs[self.key] = self._touched  # current run last (newest)
        while len(runs) > MAX_RUNS:
            runs.pop(next(iter(runs)))  # trim oldest slot
        doc = {"version": CACHE_VERSION, "runs": runs}
        tmp = self.path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(doc, f, separators=(",", ":"))
            os.replace(tmp, self.path)
        except OSError:
            # an unwritable cache dir degrades to cold runs, silently
            # by design: the cache is a pure accelerator
            try:
                os.unlink(tmp)
            except OSError:
                pass
