"""Static lock discipline: the ``# guarded-by:`` convention.

A field annotated at its initialization site —

    self._tables = {}  # guarded-by: _lock

— must only be touched inside a ``with self._lock:`` block. The rule is
**lexical**: it checks that every ``self.<attr>`` access in the class
(reads and writes, including inside closures defined in methods) has a
``with self.<lockname>`` ancestor in the same function. Three escapes:

- ``__init__`` is exempt (construction happens-before publication);
- methods named ``*_locked`` are exempt — the documented convention
  for internal helpers whose CALLER holds the lock;
- an inline ``# lint: disable=lock-guard`` pragma, for the rare
  benign race that is cheaper to document than to lock.

The runtime half of lock discipline — acquisition-order tracking and
lock-order-inversion detection under ``CELESTIA_RACE=1`` — lives in
``racecheck.py``.
"""

from __future__ import annotations

import ast
import re

from celestia_app_tpu.tools.analyze.engine import (
    FileContext,
    Rule,
    register,
)
from celestia_app_tpu.tools.analyze.config import RuleConfig

_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_]\w*)")


def _guarded_attrs(cls: ast.ClassDef, ctx: FileContext) -> dict[str, str]:
    """attr name -> lock attr name, from ``# guarded-by:`` comments on
    ``self.X = ...`` lines anywhere in the class (conventionally
    ``__init__``)."""
    guarded: dict[str, str] = {}
    for node in ast.walk(cls):
        targets: list[ast.expr] = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            targets = [node.target]
        for t in targets:
            if (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                    and t.lineno <= len(ctx.lines)):
                m = _GUARDED_RE.search(ctx.lines[t.lineno - 1])
                if m:
                    guarded[t.attr] = m.group(1)
    return guarded


def _holds_lock(node: ast.AST, lockname: str, ctx: FileContext) -> bool:
    """True when `node` is lexically inside ``with self.<lockname>``
    (also accepts a bare ``with <lockname>`` for module-style locks)."""
    for parent in ctx.parents(node):
        if not isinstance(parent, (ast.With, ast.AsyncWith)):
            continue
        for item in parent.items:
            e = item.context_expr
            if (isinstance(e, ast.Attribute) and e.attr == lockname
                    and isinstance(e.value, ast.Name)
                    and e.value.id == "self"):
                return True
            if isinstance(e, ast.Name) and e.id == lockname:
                return True
    return False


def _enclosing_method(node: ast.AST, cls: ast.ClassDef,
                      ctx: FileContext) -> ast.FunctionDef | None:
    """The class-level method whose body (possibly via nested closures)
    contains `node`."""
    method = None
    for parent in ctx.parents(node):
        if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef)):
            method = parent
        if parent is cls:
            return method
        if isinstance(parent, ast.ClassDef):
            return None  # a nested class: out of this rule's scope
    return None


@register
class LockGuardRule(Rule):
    id = "lock-guard"
    help = ("fields annotated '# guarded-by: <lock>' may only be "
            "touched inside 'with self.<lock>:' (or from *_locked "
            "helpers whose caller holds it)")

    def check(self, ctx: FileContext, cfg: RuleConfig):
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            guarded = _guarded_attrs(cls, ctx)
            if not guarded:
                continue
            for node in ast.walk(cls):
                if not (isinstance(node, ast.Attribute)
                        and isinstance(node.value, ast.Name)
                        and node.value.id == "self"
                        and node.attr in guarded):
                    continue
                method = _enclosing_method(node, cls, ctx)
                if method is None:
                    continue
                outer = method
                for p in ctx.parents(method):
                    if isinstance(p, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                        outer = p
                    elif p is cls:
                        break
                if outer.name == "__init__" or \
                        outer.name.endswith("_locked") or \
                        method.name.endswith("_locked"):
                    continue
                lockname = guarded[node.attr]
                if not _holds_lock(node, lockname, ctx):
                    yield (node.lineno, node.col_offset,
                           f"self.{node.attr} is guarded-by "
                           f"{lockname} but accessed outside "
                           f"'with self.{lockname}' in "
                           f"{cls.name}.{method.name}()")
