"""``analyze.toml`` loader.

The container's Python (3.10) predates ``tomllib``, so this module
carries a deliberately small TOML-subset reader covering exactly what
the committed config uses: ``[table]`` / ``[[array-of-tables]]``
headers with dotted bare keys, string / integer / boolean scalars, and
(possibly multiline) arrays of strings. Anything outside the subset is
a hard parse error — the config is committed, so failing loudly beats
guessing.

Config shape (normative — docs/FORMATS.md §11):

    [analyze]
    exclude = ["__pycache__"]            # path prefixes skipped entirely

    [rules.det-wallclock]
    severity = "error"                   # error | warning | off
    include = ["wire/", "chain/app.py",  # path prefixes; a
               "chain/consensus.py::apply"]  # ``path::symbol`` entry
    exclude = []                         # scopes to one function/method
    allow = []                           # rule-specific allowlist paths

    [[waivers]]
    rule = "det-float"
    path = "da/sampling.py"
    reason = "confidence reporting, not consensus state"   # REQUIRED

A waiver downgrades every violation of ``rule`` in ``path`` (prefix
match) to "waived" — still reported, never fatal. Waivers without a
reason, and waivers that match nothing (stale), are themselves errors:
the waiver ledger must stay honest.
"""

from __future__ import annotations

import dataclasses


class ConfigError(ValueError):
    pass


# ---------------------------------------------------------------------------
# TOML-subset parsing
# ---------------------------------------------------------------------------


def _parse_scalar(tok: str, where: str):
    tok = tok.strip()
    if tok.startswith('"') and tok.endswith('"') and len(tok) >= 2:
        return tok[1:-1]
    if tok in ("true", "false"):
        return tok == "true"
    try:
        return int(tok)
    except ValueError:
        raise ConfigError(f"{where}: unsupported TOML value {tok!r}")


def _split_array_items(body: str, where: str) -> list[str]:
    items, cur, in_str = [], "", False
    for ch in body:
        if ch == '"':
            in_str = not in_str
            cur += ch
        elif ch == "," and not in_str:
            items.append(cur)
            cur = ""
        else:
            cur += ch
    if in_str:
        raise ConfigError(f"{where}: unterminated string in array")
    if cur.strip():
        items.append(cur)
    return [i for i in (s.strip() for s in items) if i]


def parse_toml_subset(text: str) -> dict:
    """Parse the TOML subset described in the module docstring into
    nested dicts; ``[[name]]`` tables become lists of dicts."""
    root: dict = {}
    target: dict = root
    lines = text.splitlines()
    i = 0
    while i < len(lines):
        raw = lines[i]
        line = raw.strip()
        i += 1
        if not line or line.startswith("#"):
            continue
        if line.startswith("[[") and line.endswith("]]"):
            path = line[2:-2].strip()
            parent = root
            parts = path.split(".")
            for p in parts[:-1]:
                parent = parent.setdefault(p, {})
            arr = parent.setdefault(parts[-1], [])
            if not isinstance(arr, list):
                raise ConfigError(f"line {i}: {path} is not a table array")
            target = {}
            arr.append(target)
            continue
        if line.startswith("[") and line.endswith("]"):
            path = line[1:-1].strip()
            parent = root
            for p in path.split("."):
                parent = parent.setdefault(p, {})
                if not isinstance(parent, dict):
                    raise ConfigError(f"line {i}: {path} is not a table")
            target = parent
            continue
        if "=" not in line:
            raise ConfigError(f"line {i}: expected key = value, got {raw!r}")
        key, _, value = line.partition("=")
        key = key.strip().strip('"')
        value = value.strip()
        # strip a trailing comment outside strings
        value = _strip_comment(value)
        if value.startswith("["):
            # gather lines until the closing bracket (multiline arrays)
            while "]" not in value:
                if i >= len(lines):
                    raise ConfigError(f"line {i}: unterminated array")
                value += " " + _strip_comment(lines[i].strip())
                i += 1
            body = value[value.index("[") + 1:value.rindex("]")]
            target[key] = [
                _parse_scalar(tok, f"line {i}")
                for tok in _split_array_items(body, f"line {i}")
            ]
        else:
            target[key] = _parse_scalar(value, f"line {i}")
    return root


def _strip_comment(value: str) -> str:
    out, in_str = "", False
    for ch in value:
        if ch == '"':
            in_str = not in_str
        if ch == "#" and not in_str:
            break
        out += ch
    return out.rstrip()


# ---------------------------------------------------------------------------
# config model
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class RuleConfig:
    severity: str = "error"          # error | warning | off
    include: list[str] = dataclasses.field(default_factory=list)
    # scanned exactly like `include`, but DECLARED extra scope: files a
    # rule covers on purpose beyond the consensus-reachable set (e.g.
    # the scenario plane's sim/ + utils/clock.py under det-wallclock),
    # so the scope audit does not flag them as dead include entries. If
    # one ever becomes consensus-reachable, scope-drift still demands
    # its promotion into `include`.
    include_extra: list[str] = dataclasses.field(default_factory=list)
    exclude: list[str] = dataclasses.field(default_factory=list)
    allow: list[str] = dataclasses.field(default_factory=list)
    options: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class Waiver:
    rule: str
    path: str
    reason: str
    used: int = 0


@dataclasses.dataclass
class AnalyzeConfig:
    exclude: list[str] = dataclasses.field(default_factory=list)
    rules: dict[str, RuleConfig] = dataclasses.field(default_factory=dict)
    waivers: list[Waiver] = dataclasses.field(default_factory=list)
    source_path: str | None = None

    def rule(self, rule_id: str) -> RuleConfig:
        cfg = self.rules.get(rule_id)
        if cfg is None:
            cfg = self.rules[rule_id] = RuleConfig()
        return cfg


_KNOWN_RULE_KEYS = {"severity", "include", "include_extra", "exclude",
                    "allow"}


def config_from_dict(doc: dict, source_path: str | None = None,
                     ) -> AnalyzeConfig:
    cfg = AnalyzeConfig(source_path=source_path)
    top = doc.get("analyze", {})
    cfg.exclude = list(top.get("exclude", ["__pycache__"]))
    for rule_id, body in doc.get("rules", {}).items():
        if not isinstance(body, dict):
            raise ConfigError(f"[rules.{rule_id}] is not a table")
        sev = body.get("severity", "error")
        if sev not in ("error", "warning", "off"):
            raise ConfigError(
                f"[rules.{rule_id}] severity must be error|warning|off, "
                f"got {sev!r}"
            )
        cfg.rules[rule_id] = RuleConfig(
            severity=sev,
            include=list(body.get("include", [])),
            include_extra=list(body.get("include_extra", [])),
            exclude=list(body.get("exclude", [])),
            allow=list(body.get("allow", [])),
            options={k: v for k, v in body.items()
                     if k not in _KNOWN_RULE_KEYS},
        )
    for w in doc.get("waivers", []):
        if "reason" not in w or not str(w["reason"]).strip():
            raise ConfigError(
                f"waiver for {w.get('rule')}:{w.get('path')} has no reason "
                "(every waiver must say why)"
            )
        if "rule" not in w or "path" not in w:
            raise ConfigError(f"waiver missing rule/path: {w}")
        cfg.waivers.append(
            Waiver(rule=str(w["rule"]), path=str(w["path"]),
                   reason=str(w["reason"]))
        )
    return cfg


def load_config(path: str | None = None) -> AnalyzeConfig:
    if path is None:
        from celestia_app_tpu.tools.analyze import default_config_path

        path = default_config_path()
    with open(path) as f:
        return config_from_dict(parse_toml_subset(f.read()),
                                source_path=path)
