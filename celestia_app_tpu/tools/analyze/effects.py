"""The interprocedural effect system: whole-program residency,
lock-order, and guarded-by proofs over the call graph.

`callgraph.build_fragment` (v4) records per-function effect FACTS —
host-materialization sites (`xfer`), lock with-frames with lexical
spans (`frames`), guarded-attribute accesses (`guarded`), raise sites
(`raises`) and try-shield spans (`shielded`). This module turns those
facts into per-function effect SUMMARIES propagated bottom-up through
a fixpoint over the SCC condensation of the linked call graph
(`tarjan_sccs` emits components callees-first, so each summary is
computed after everything it calls — cycles iterate to a fixpoint
inside their component). The lattice per function:

- ``host``  — the nearest UNLEDGERED host-materialization sink this
  function can reach (hop distance + next-hop pointer, so the full
  sink path reconstructs by chasing summaries). Sites routed through
  ``obs.xfer`` (or carrying a ``# xfer: ledger`` marker) are exempt;
  ``np.asarray``-family and ``.item()`` sinks only count in files that
  import jax — elsewhere they cannot touch a device value.
- ``acquires`` — every lock this function may take, directly or
  transitively, with the first call hop toward the acquiring frame.
- ``required`` — locks a callee path *assumes held*: a ``*_locked``
  helper touching a ``# guarded-by:`` attribute without holding the
  lock pushes the obligation to its callers; exempt (``*_locked``)
  callers propagate it further, ``__init__`` clears it (happens-before
  publication), anyone else must hold the lock at the call site.
- ``escapes`` — exception type names that can escape, own raises plus
  callee escapes, minus spans shielded by matching/broad handlers.

Three rules consume the summaries (and the BFS the taint engine
already provides):

- **xfer-reach** — the static twin of the runtime transfer ledger:
  any unledgered host-materialization sink reachable from the warmed
  produce/serve roots in analyze.toml is an error with the full call
  path. Allow entries are traversal BARRIERS (same semantics as
  det-reach), so every entry is load-bearing and deletion-testable.
- **lock-order** — the static ABBA detector: lock-acquisition edges
  from lexical frame nesting and from held-frame × callee-acquires
  summaries; every 2-lock cycle reports BOTH acquisition paths, and
  larger strongly-connected lock sets report the whole component.
  Known inversions live in the ``ledger`` option (shared with the
  runtime racecheck waiver surface); unmatched ledger entries are
  stale errors.
- **guarded-by-flow** — interprocedural guarded-by: a call into a
  path that assumes a lock held, from a function that cannot hold it,
  is an error at the call line (the lexical rules_locks check only
  sees the syntactic enclosing function).

``analyze --effects <qualified-name>`` prints one symbol's computed
summary (`describe_symbol`) with the reconstructed sink path.
"""

from __future__ import annotations

import re

from celestia_app_tpu.tools.analyze.config import AnalyzeConfig, RuleConfig
from celestia_app_tpu.tools.analyze.engine import (
    ProgramRule,
    Violation,
    _in_scope,
    register,
)
from celestia_app_tpu.tools.analyze.taint import _barrier, _resolve_roots

_SINK_KINDS = {
    "d2h-raw": "raw jax.device_get",
    "h2d-raw": "raw jax.device_put",
    "asarray": "np.asarray-family host materialization",
    "item": ".item() host sync",
}
# asarray/.item only materialize device bytes when the file can hold a
# jax.Array at all — gating on the import kills the numpy-only noise
_JAX_ONLY_KINDS = {"asarray", "item"}


# ---------------------------------------------------------------------------
# SCC condensation
# ---------------------------------------------------------------------------


def tarjan_sccs(program):
    """Strongly connected components of the call graph, emitted in
    REVERSE topological order (every component after all components it
    calls into) — the natural order for bottom-up summary computation.
    Iterative: the call graph is deeper than CPython's recursion
    limit wants to be responsible for."""
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    onstack: set[str] = set()
    stack: list[str] = []
    sccs: list[list[str]] = []
    counter = 0
    for start in sorted(program.nodes):
        if start in index:
            continue
        work: list[tuple[str, int]] = [(start, 0)]
        while work:
            nid, ei = work[-1]
            if ei == 0:
                index[nid] = low[nid] = counter
                counter += 1
                stack.append(nid)
                onstack.add(nid)
            edges = program.edges.get(nid, [])
            advanced = False
            while ei < len(edges):
                tgt = edges[ei][0]
                ei += 1
                if tgt not in program.nodes:
                    continue
                if tgt not in index:
                    work[-1] = (nid, ei)
                    work.append((tgt, 0))
                    advanced = True
                    break
                if tgt in onstack:
                    low[nid] = min(low[nid], index[tgt])
            if advanced:
                continue
            work.pop()
            if low[nid] == index[nid]:
                comp = []
                while True:
                    w = stack.pop()
                    onstack.discard(w)
                    comp.append(w)
                    if w == nid:
                        break
                sccs.append(sorted(comp))
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[nid])
    return sccs


# ---------------------------------------------------------------------------
# summaries
# ---------------------------------------------------------------------------


class EffectSummary:
    """One function's computed effects. ``host`` is the nearest
    unledgered sink as (dist, sink_nid, sink_line, kind, what, via)
    where ``via`` is the next-hop node id (None when the sink is an
    own site); ``acquires`` maps lock id -> (dist, line, via);
    ``required`` maps lock id -> (attr, line, via); ``escapes`` is the
    set of escaping exception type names."""

    __slots__ = ("host", "acquires", "required", "escapes")

    def __init__(self):
        self.host = None
        self.acquires: dict[str, tuple] = {}
        self.required: dict[str, tuple] = {}
        self.escapes: set[str] = set()


def _lock_id(node, lockname: str, is_self: int) -> str:
    """Stable lock identity. ``self.<attr>`` locks key on the owning
    class (two classes' ``_lock`` attributes are different locks);
    bare-name locks are file-scoped."""
    if is_self:
        return f"{node.path}::{node.cls or 'self'}.{lockname}"
    return f"{node.path}::{lockname}"


def _is_exempt(qual: str) -> bool:
    """Mirrors the lexical lock-guard exemption: any ``*_locked``
    qualname component means 'my caller holds the lock'."""
    return any(part.endswith("_locked") for part in qual.split("."))


def _held_at(node, line: int, lock: str) -> bool:
    """Does `node` lexically hold `lock` at `line`? Exact lock-id
    match, plus a same-attribute relaxation for self-locks: a held
    ``self._lock`` satisfies a required ``<AnyClass>._lock`` — through
    inheritance it IS the same object, and the fragment cannot see
    subclass relationships across files."""
    want_attr = lock.rsplit(".", 1)[-1] if "." in lock.split("::")[-1] else None
    for lockname, is_self, start, end in node.frames:
        if not (start <= line <= end):
            continue
        if _lock_id(node, lockname, is_self) == lock:
            return True
        if is_self and want_attr is not None and lockname == want_attr:
            return True
    return False


def _shielded(node, line: int, name: str) -> bool:
    tail = name.rsplit(".", 1)[-1]
    for lo, hi, t in node.shielded:
        if lo <= line <= hi and (t == "*" or t.rsplit(".", 1)[-1] == tail):
            return True
    return False


def _own_host_sinks(program, node):
    jaxy = program.imports_jax.get(node.path, False)
    out = []
    for kind, line, what in node.xfer:
        if kind == "ledgered":
            continue
        if kind in _JAX_ONLY_KINDS and not jaxy:
            continue
        out.append((0, node.id, int(line), kind, what, None))
    return out


def _host_key(cand):
    return (cand[0], cand[1], cand[2], cand[3], cand[4], cand[5] or "")


def _seed(program, summaries, nid) -> None:
    node = program.nodes[nid]
    s = summaries[nid] = EffectSummary()
    own = _own_host_sinks(program, node)
    if own:
        s.host = min(own, key=_host_key)
    for lockname, is_self, start, end in node.frames:
        lock = _lock_id(node, lockname, is_self)
        cand = (0, int(start), None)
        cur = s.acquires.get(lock)
        if cur is None or cand < (cur[0], cur[1], cur[2] or ""):
            s.acquires[lock] = cand
    if _is_exempt(node.qual):
        for attr, lockname, line, held in node.guarded:
            if held:
                continue
            lock = f"{node.path}::{node.cls or 'self'}.{lockname}"
            cand = (attr, int(line), None)
            cur = s.required.get(lock)
            if cur is None or (cand[1], cand[0]) < (cur[1], cur[0]):
                s.required[lock] = cand
    for excname, line in node.raises_:
        if not _shielded(node, int(line), excname):
            s.escapes.add(excname.rsplit(".", 1)[-1])


def _flow(program, summaries, nid) -> bool:
    """Fold current callee summaries into `nid`'s; True if changed."""
    node = program.nodes[nid]
    s = summaries[nid]
    changed = False
    exempt = _is_exempt(node.qual)
    is_init = node.qual.split(".")[-1] == "__init__"
    for tgt, line in program.edges.get(nid, []):
        t = summaries.get(tgt)
        if t is None:
            continue
        if t.host is not None:
            cand = (t.host[0] + 1, t.host[1], t.host[2],
                    t.host[3], t.host[4], tgt)
            if s.host is None or _host_key(cand) < _host_key(s.host):
                s.host = cand
                changed = True
        for lock, (d, _bl, _via) in t.acquires.items():
            cand = (d + 1, int(line), tgt)
            cur = s.acquires.get(lock)
            if cur is None or (cand[0], cand[1], cand[2] or "") < (
                    cur[0], cur[1], cur[2] or ""):
                s.acquires[lock] = cand
                changed = True
        if exempt and not is_init:
            for lock, (attr, _al, _via) in t.required.items():
                if _held_at(node, int(line), lock):
                    continue
                cand = (attr, int(line), tgt)
                cur = s.required.get(lock)
                if cur is None or (cand[1], cand[0]) < (cur[1], cur[0]):
                    s.required[lock] = cand
                    changed = True
        for name in t.escapes:
            if name not in s.escapes and not _shielded(
                    node, int(line), name):
                s.escapes.add(name)
                changed = True
    return changed


def _required_chain(summaries, nid, lock) -> list[str]:
    chain = [nid]
    cur = nid
    while True:
        ent = summaries[cur].required.get(lock)
        if ent is None or ent[2] is None:
            break
        cur = ent[2]
        chain.append(cur)
    return chain


def _acquire_chain(summaries, nid, lock) -> list[str]:
    chain = [nid]
    cur = nid
    while True:
        ent = summaries[cur].acquires.get(lock)
        if ent is None or ent[2] is None:
            break
        cur = ent[2]
        chain.append(cur)
    return chain


def _host_chain(summaries, nid) -> list[str]:
    chain = [nid]
    cur = nid
    while True:
        h = summaries[cur].host
        if h is None or h[5] is None:
            break
        cur = h[5]
        chain.append(cur)
    return chain


def compute_summaries(program):
    """(summaries, guarded_reports) — memoized on the program object so
    the three effect rules and ``--effects`` share one fixpoint."""
    cached = getattr(program, "_effect_summaries", None)
    if cached is not None:
        return cached
    summaries: dict[str, EffectSummary] = {}
    for nid in program.nodes:
        _seed(program, summaries, nid)
    for scc in tarjan_sccs(program):
        while True:
            changed = False
            for nid in scc:
                changed |= _flow(program, summaries, nid)
            if not changed:
                break
    # the guarded-by obligation surfaces at the FIRST caller that can
    # neither hold the lock nor push the obligation further up
    reports = []
    seen: set[tuple[str, str, str]] = set()
    for nid in sorted(program.nodes):
        node = program.nodes[nid]
        if _is_exempt(node.qual) or node.qual.split(".")[-1] == "__init__":
            continue
        for tgt, line in sorted(program.edges.get(nid, []),
                                key=lambda e: (e[1], e[0])):
            t = summaries.get(tgt)
            if t is None:
                continue
            for lock in sorted(t.required):
                attr, _al, _via = t.required[lock]
                if _held_at(node, int(line), lock):
                    continue
                key = (nid, lock, attr)
                if key in seen:
                    continue
                seen.add(key)
                reports.append({
                    "path": node.path, "line": int(line),
                    "caller": nid, "callee": tgt,
                    "lock": lock, "attr": attr,
                    "chain": [nid] + _required_chain(summaries, tgt, lock),
                })
    program._effect_summaries = (summaries, reports)
    return summaries, reports


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------


@register
class XferReachRule(ProgramRule):
    id = "xfer-reach"
    help = ("host-materialization sinks (raw device_get/device_put, "
            "np.asarray-family, .item()) reachable from the warmed "
            "produce/serve roots must route through the counted "
            "obs.xfer helpers — the static twin of the runtime "
            "transfer ledger")

    def check_program(self, program, config: AnalyzeConfig,
                      rcfg: RuleConfig):
        if not rcfg.options.get("roots"):
            yield Violation(
                rule=self.id, severity="error", path="analyze.toml",
                line=0, col=0,
                message=("xfer-reach is enabled but [rules.xfer-reach] "
                         "configures no roots — an empty root set makes "
                         "the residency proof a silent no-op"),
            )
            return
        roots, missing = _resolve_roots(program, rcfg, self.id)
        for v in missing:
            yield v
        visited, parents = program.reachable(roots, _barrier(rcfg.allow))
        for nid in sorted(visited):
            node = program.nodes[nid]
            jaxy = program.imports_jax.get(node.path, False)
            for kind, line, what in node.xfer:
                if kind == "ledgered":
                    continue
                if kind in _JAX_ONLY_KINDS and not jaxy:
                    continue
                chain = program.call_path(parents, nid)
                root_qual = program.nodes[chain[0]].qual
                yield Violation(
                    rule=self.id, severity="error", path=node.path,
                    line=int(line), col=0,
                    message=(f"{_SINK_KINDS.get(kind, kind)} ({what}) "
                             f"in {node.qual}() is reachable from "
                             f"warmed root {root_qual}() — route it "
                             "through obs.xfer (to_device/to_host/"
                             "ensure_host) so the transfer ledger "
                             "counts it, or add a reasoned allow "
                             "entry"),
                    call_path=chain,
                    effect={"kind": kind, "what": what,
                            "sink": nid, "root": chain[0]},
                )


_LEDGER_RE = re.compile(
    r"^\s*(?P<a>\S+)\s*<->\s*(?P<b>\S+)\s*:\s*(?P<reason>.+?)\s*$")


def _lock_graph(program, summaries, rcfg):
    """Lock-acquisition edges: (A, B) -> provenance of the first
    deterministic witness that B can be acquired while A is held."""
    edges: dict[tuple[str, str], dict] = {}

    def record(a, b, prov):
        if a != b and (a, b) not in edges:
            edges[(a, b)] = prov

    for nid in sorted(program.nodes):
        node = program.nodes[nid]
        if not node.frames or not _in_scope(node.path, rcfg):
            continue
        for lockname, is_self, start, end in node.frames:
            a = _lock_id(node, lockname, is_self)
            for ln2, is2, s2, e2 in node.frames:
                if start < s2 and e2 <= end:
                    record(a, _lock_id(node, ln2, is2),
                           {"holder": nid, "frame_line": int(start),
                            "line": int(s2), "callee": None})
            for tgt, line in program.edges.get(nid, []):
                if not (start <= line <= end):
                    continue
                t = summaries.get(tgt)
                if t is None:
                    continue
                for b in t.acquires:
                    record(a, b,
                           {"holder": nid, "frame_line": int(start),
                            "line": int(line), "callee": tgt})
    return edges


def _graph_sccs(nodes, adj):
    """Tarjan over a small generic digraph (the lock graph)."""
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    onstack: set[str] = set()
    stack: list[str] = []
    sccs: list[list[str]] = []
    counter = 0
    for start in sorted(nodes):
        if start in index:
            continue
        work = [(start, 0)]
        while work:
            n, ei = work[-1]
            if ei == 0:
                index[n] = low[n] = counter
                counter += 1
                stack.append(n)
                onstack.add(n)
            succ = adj.get(n, [])
            advanced = False
            while ei < len(succ):
                m = succ[ei]
                ei += 1
                if m not in index:
                    work[-1] = (n, ei)
                    work.append((m, 0))
                    advanced = True
                    break
                if m in onstack:
                    low[n] = min(low[n], index[m])
            if advanced:
                continue
            work.pop()
            if low[n] == index[n]:
                comp = []
                while True:
                    w = stack.pop()
                    onstack.discard(w)
                    comp.append(w)
                    if w == n:
                        break
                sccs.append(sorted(comp))
            if work:
                p = work[-1][0]
                low[p] = min(low[p], low[n])
    return sccs


@register
class LockOrderRule(ProgramRule):
    id = "lock-order"
    help = ("static ABBA detection: lock-acquisition edges from held "
            "with-frames × call edges; every cycle reports both full "
            "acquisition paths. Known inversions are waived in the "
            "rule's ledger (shared with the runtime racecheck waiver "
            "surface); unmatched ledger entries are stale errors")

    def check_program(self, program, config: AnalyzeConfig,
                      rcfg: RuleConfig):
        summaries, _ = compute_summaries(program)
        edges = _lock_graph(program, summaries, rcfg)
        adj: dict[str, list[str]] = {}
        locks: set[str] = set()
        for (a, b) in edges:
            adj.setdefault(a, []).append(b)
            locks.update((a, b))
        for succ in adj.values():
            succ.sort()

        ledger: dict[frozenset, str] = {}
        bad_entries: list[str] = []
        for raw in rcfg.options.get("ledger", []):
            m = _LEDGER_RE.match(str(raw))
            if m is None:
                bad_entries.append(str(raw))
                continue
            ledger[frozenset((m.group("a"), m.group("b")))] = (
                m.group("reason"))
        for raw in bad_entries:
            yield Violation(
                rule=self.id, severity="error", path="analyze.toml",
                line=0, col=0,
                message=(f"unparseable lock-order ledger entry {raw!r} "
                         "— format is '<lockA> <-> <lockB> : <reason>'"),
            )

        def describe(prov, lock):
            chain = [prov["holder"]]
            if prov["callee"] is not None:
                chain += _acquire_chain(summaries, prov["callee"], lock)
            quals = " -> ".join(
                program.nodes[n].qual for n in chain
                if n in program.nodes)
            return chain, quals

        matched: set[frozenset] = set()
        for scc in _graph_sccs(locks, adj):
            if len(scc) < 2:
                continue
            if len(scc) == 2:
                a, b = scc
                ab, ba = edges[(a, b)], edges[(b, a)]
                ab_chain, ab_q = describe(ab, b)
                ba_chain, ba_q = describe(ba, a)
                pair = frozenset((a, b))
                reason = ledger.get(pair)
                if reason is not None:
                    matched.add(pair)
                yield Violation(
                    rule=self.id, severity="error",
                    path=ab["holder"].split("::")[0],
                    line=ab["line"], col=0,
                    message=(f"lock-order inversion: {a} then {b} "
                             f"(via {ab_q}) but {b} then {a} "
                             f"(via {ba_q}) — two threads interleaving "
                             "these acquisition paths deadlock"),
                    waived=reason is not None,
                    waiver_reason=reason,
                    call_path=ab_chain,
                    effect={"cycle": [a, b],
                            "ab": {"line": ab["line"],
                                   "chain": ab_chain},
                            "ba": {"line": ba["line"],
                                   "chain": ba_chain}},
                )
            else:
                first = edges[(scc[0], next(
                    m for m in adj.get(scc[0], []) if m in scc))]
                yield Violation(
                    rule=self.id, severity="error",
                    path=first["holder"].split("::")[0],
                    line=first["line"], col=0,
                    message=("lock-order cycle over "
                             f"{len(scc)} locks: {', '.join(scc)} — "
                             "no consistent acquisition order exists"),
                    effect={"cycle": list(scc)},
                )
        for pair in sorted(ledger, key=sorted):
            if pair not in matched:
                a, b = sorted(pair)
                yield Violation(
                    rule=self.id, severity="error", path="analyze.toml",
                    line=0, col=0,
                    message=(f"stale lock-order ledger entry "
                             f"'{a} <-> {b}' — the static cycle it "
                             "waives no longer exists; the inversion "
                             "ledger must track the code"),
                )


@register
class GuardedByFlowRule(ProgramRule):
    id = "guarded-by-flow"
    help = ("interprocedural guarded-by: calling a *_locked path that "
            "touches a '# guarded-by:' attribute, from a function that "
            "neither holds the lock nor is itself *_locked, mutates "
            "the field lock-free on every interleaving")

    def check_program(self, program, config: AnalyzeConfig,
                      rcfg: RuleConfig):
        _summaries, reports = compute_summaries(program)
        for rep in reports:
            if not _in_scope(rep["path"], rcfg):
                continue
            callee = program.nodes[rep["callee"]]
            caller = program.nodes[rep["caller"]]
            yield Violation(
                rule=self.id, severity="error", path=rep["path"],
                line=rep["line"], col=0,
                message=(f"call to {callee.qual}() reaches an access "
                         f"of self.{rep['attr']} (guarded-by "
                         f"{rep['lock']}) but {caller.qual}() holds no "
                         "lock here and is not *_locked — acquire the "
                         "lock around the call or rename the caller "
                         "*_locked to push the obligation up"),
                call_path=rep["chain"],
                effect={"lock": rep["lock"], "attr": rep["attr"],
                        "chain": rep["chain"]},
            )


# ---------------------------------------------------------------------------
# --effects
# ---------------------------------------------------------------------------


def describe_symbol(program, entry: str) -> str:
    """Human-readable computed summary for ``analyze --effects``."""
    nid = program.resolve_entry(entry)
    if nid is None:
        return (f"--effects: {entry!r} not found in the call graph "
                "(use path.py::Qual.name, or a unique ::symbol suffix)")
    summaries, _ = compute_summaries(program)
    s = summaries[nid]
    node = program.nodes[nid]
    out = [f"effect summary for {nid} (line {node.line})"]
    own = _own_host_sinks(program, node)
    if own:
        out.append("  own host sinks:")
        for _d, _nid, line, kind, what, _via in own:
            out.append(f"    line {line}: {_SINK_KINDS.get(kind, kind)}"
                       f" ({what})")
    if s.host is not None:
        chain = _host_chain(summaries, nid)
        sink = program.nodes.get(s.host[1])
        sink_q = sink.qual if sink else s.host[1]
        out.append(f"  nearest unledgered sink: "
                   f"{_SINK_KINDS.get(s.host[3], s.host[3])} "
                   f"({s.host[4]}) in {sink_q}() at "
                   f"{s.host[1].split('::')[0]}:{s.host[2]}, "
                   f"{s.host[0]} hop(s)")
        out.append("    sink path: " + " -> ".join(chain))
    else:
        out.append("  host: clean (no unledgered sink reachable)")
    if s.acquires:
        out.append("  acquires:")
        for lock in sorted(s.acquires):
            d, line, _via = s.acquires[lock]
            chain = _acquire_chain(summaries, nid, lock)
            out.append(f"    {lock} ({d} hop(s), line {line}): "
                       + " -> ".join(chain))
    else:
        out.append("  acquires: none")
    if s.required:
        out.append("  requires held:")
        for lock in sorted(s.required):
            attr, line, _via = s.required[lock]
            out.append(f"    {lock} (guards self.{attr}, line {line})")
    if s.escapes:
        out.append("  escapes: " + ", ".join(sorted(s.escapes)))
    else:
        out.append("  escapes: none")
    return "\n".join(out)
