"""Exception hygiene + effect discipline rules.

- ``except-swallow``: a ``bare except`` / ``except Exception`` handler
  that neither re-raises, logs through ``obs.log``, nor bumps a
  telemetry counter is a silent failure sink — ~30 of them hid real
  errors before this rule existed.
- ``jit-purity``: side effects inside ``jax.jit``-traced functions run
  once at trace time (or force a host round-trip) and then silently
  never again — logging, telemetry, ``np.asarray``, ``float()`` casts,
  and ``global`` mutation are all bugs inside device code.
- ``print-call`` / ``raw-urlopen``: the two pre-framework regex gates
  (tests/test_obs.py, tests/test_faults.py), now framework rules; the
  old tests are thin wrappers invoking these so tier-1 names persist.
"""

from __future__ import annotations

import ast

from celestia_app_tpu.tools.analyze.engine import (
    FileContext,
    Rule,
    register,
)
from celestia_app_tpu.tools.analyze.config import RuleConfig

# the logging/telemetry classifier and the jit detectors live ONCE in
# callgraph.py (the fragment builder needs them too); re-exported here
# so the rule file keeps reading naturally
from celestia_app_tpu.tools.analyze.callgraph import (  # noqa: E402
    _is_logging_call,
    impure_findings,
    jitted_fn_nodes,
)


def _handler_is_swallowing(handler: ast.ExceptHandler,
                           ctx: FileContext) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return False
        if isinstance(node, ast.Call) and _is_logging_call(node, ctx):
            return False
    return True


def _broad_types(handler: ast.ExceptHandler, ctx: FileContext) -> bool:
    if handler.type is None:
        return True  # bare except
    types = (handler.type.elts if isinstance(handler.type, ast.Tuple)
             else [handler.type])
    for t in types:
        if ctx.resolve(t) in ("Exception", "BaseException",
                              "builtins.Exception",
                              "builtins.BaseException"):
            return True
    return False


@register
class ExceptSwallowRule(Rule):
    id = "except-swallow"
    help = ("broad exception handlers must log via obs.log or bump a "
            "telemetry counter — silent failure sinks hide real bugs")

    def check(self, ctx: FileContext, cfg: RuleConfig):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _broad_types(node, ctx):
                continue
            if _handler_is_swallowing(node, ctx):
                what = ("bare except" if node.type is None
                        else "except Exception")
                yield (node.lineno, node.col_offset,
                       f"{what} swallows errors silently — log via "
                       "obs.log or bump a telemetry counter (or narrow "
                       "the exception type)")


# ---------------------------------------------------------------------------
# jit purity
# ---------------------------------------------------------------------------


@register
class JitPurityRule(Rule):
    id = "jit-purity"
    help = ("side effects inside jitted functions run once at trace "
            "time or force host round-trips — keep device code pure; "
            "checked transitively through the call graph")

    def check(self, ctx: FileContext, cfg: RuleConfig):
        for fn in sorted(jitted_fn_nodes(ctx), key=lambda n: n.lineno):
            for line, col, msg in impure_findings(
                    fn, ctx, f"{fn.name}()"):
                yield (line, col, msg)

    def check_program(self, program, config, cfg: RuleConfig):
        """The transitive closure (ISSUE 12): helpers *called from*
        jitted program bodies are held to the same purity bar. The
        per-file pass above already covers everything lexically inside
        a jitted function (nested defs included), so this pass reports
        only reached functions outside every jitted span."""
        from celestia_app_tpu.tools.analyze.engine import (
            Violation,
            _in_scope,
        )
        from celestia_app_tpu.tools.analyze.taint import _barrier

        jit_spans: dict[str, list[tuple[int, int]]] = {}
        for node in program.nodes.values():
            if node.jitted:
                jit_spans.setdefault(node.path, []).append(
                    (node.line, node.end))
        stop = _barrier(cfg.allow)
        seen: set[tuple[str, int, int]] = set()
        for nid in sorted(program.nodes):
            j = program.nodes[nid]
            if not j.jitted or not _in_scope(j.path, cfg):
                continue
            visited, parents = program.reachable([nid], stop)
            for tid in sorted(visited):
                n = program.nodes[tid]
                if tid == nid or n.jitted:
                    continue
                if any(lo <= n.line <= hi
                       for lo, hi in jit_spans.get(n.path, [])):
                    continue  # lexically inside a jitted fn: per-file
                for line, col, msg in n.impure:
                    key = (n.path, line, col)
                    if key in seen:
                        continue
                    seen.add(key)
                    yield Violation(
                        rule=self.id, severity=cfg.severity,
                        path=n.path, line=line, col=col,
                        message=(msg + f" [transitively reached from "
                                 f"jitted {j.qual}()]"),
                        call_path=program.call_path(parents, tid),
                    )


# ---------------------------------------------------------------------------
# the migrated regex gates
# ---------------------------------------------------------------------------


@register
class PrintRule(Rule):
    id = "print-call"
    help = ("library code logs through celestia_app_tpu.obs.log; print "
            "is reserved for the CLI and operator tools (rule allow "
            "list in analyze.toml)")

    def check(self, ctx: FileContext, cfg: RuleConfig):
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "print"):
                yield (node.lineno, node.col_offset,
                       "print call in a library module (use "
                       "celestia_app_tpu.obs.log, or allowlist with a "
                       "reason)")


@register
class UrlopenRule(Rule):
    id = "raw-urlopen"
    help = ("peer I/O goes through the hardened net/transport.py "
            "PeerClient (timeouts, retries, circuit breaker); raw "
            "urlopen bypasses all of it")

    def check(self, ctx: FileContext, cfg: RuleConfig):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = ctx.resolve(node.func) or ""
            if name == "urlopen" or name.endswith(".urlopen"):
                yield (node.lineno, node.col_offset,
                       "direct urlopen outside net/transport.py (route "
                       "peer I/O through the hardened PeerClient, or "
                       "allowlist with a reason)")
