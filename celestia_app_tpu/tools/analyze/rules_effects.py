"""Exception hygiene + effect discipline rules.

- ``except-swallow``: a ``bare except`` / ``except Exception`` handler
  that neither re-raises, logs through ``obs.log``, nor bumps a
  telemetry counter is a silent failure sink — ~30 of them hid real
  errors before this rule existed.
- ``jit-purity``: side effects inside ``jax.jit``-traced functions run
  once at trace time (or force a host round-trip) and then silently
  never again — logging, telemetry, ``np.asarray``, ``float()`` casts,
  and ``global`` mutation are all bugs inside device code.
- ``print-call`` / ``raw-urlopen``: the two pre-framework regex gates
  (tests/test_obs.py, tests/test_faults.py), now framework rules; the
  old tests are thin wrappers invoking these so tier-1 names persist.
"""

from __future__ import annotations

import ast

from celestia_app_tpu.tools.analyze.engine import (
    FileContext,
    Rule,
    register,
)
from celestia_app_tpu.tools.analyze.config import RuleConfig

_LOG_METHODS = {"debug", "info", "warning", "error", "exception"}
_TELEMETRY_METHODS = {"incr", "observe", "measure_since", "gauge",
                      "counter"}


def _is_logging_call(node: ast.Call, ctx: FileContext) -> bool:
    if not isinstance(node.func, ast.Attribute):
        return False
    attr = node.func.attr
    base = ctx.resolve(node.func.value) or ""
    base_tail = base.rsplit(".", 1)[-1].lower()
    if attr in _LOG_METHODS and ("log" in base_tail or base_tail in
                                 ("lg", "obs")):
        return True
    # incr/observe/gauge/measure_since are distinctive registry verbs;
    # accept them on any receiver (telemetry module, self on Registry,
    # pool.metrics, ...) — a counter bump is a counter bump
    if attr in _TELEMETRY_METHODS:
        return True
    return False


def _handler_is_swallowing(handler: ast.ExceptHandler,
                           ctx: FileContext) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return False
        if isinstance(node, ast.Call) and _is_logging_call(node, ctx):
            return False
    return True


def _broad_types(handler: ast.ExceptHandler, ctx: FileContext) -> bool:
    if handler.type is None:
        return True  # bare except
    types = (handler.type.elts if isinstance(handler.type, ast.Tuple)
             else [handler.type])
    for t in types:
        if ctx.resolve(t) in ("Exception", "BaseException",
                              "builtins.Exception",
                              "builtins.BaseException"):
            return True
    return False


@register
class ExceptSwallowRule(Rule):
    id = "except-swallow"
    help = ("broad exception handlers must log via obs.log or bump a "
            "telemetry counter — silent failure sinks hide real bugs")

    def check(self, ctx: FileContext, cfg: RuleConfig):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _broad_types(node, ctx):
                continue
            if _handler_is_swallowing(node, ctx):
                what = ("bare except" if node.type is None
                        else "except Exception")
                yield (node.lineno, node.col_offset,
                       f"{what} swallows errors silently — log via "
                       "obs.log or bump a telemetry counter (or narrow "
                       "the exception type)")


# ---------------------------------------------------------------------------
# jit purity
# ---------------------------------------------------------------------------

_JIT_NAMES = {"jax.jit", "jit", "pl.pallas_call"}
_HOST_CALLS = {"numpy.asarray", "numpy.array", "numpy.frombuffer",
               "jax.device_get"}
_HOST_ATTRS = {"block_until_ready", "item"}


def _is_jit_decorator(dec: ast.AST, ctx: FileContext) -> bool:
    name = ctx.resolve(dec)
    if name in _JIT_NAMES:
        return True
    if isinstance(dec, ast.Call):
        fname = ctx.resolve(dec.func)
        if fname in _JIT_NAMES:
            return True
        if fname in ("functools.partial", "partial") and dec.args:
            return ctx.resolve(dec.args[0]) in _JIT_NAMES
    return False


def _jitted_functions(ctx: FileContext) -> list[ast.FunctionDef]:
    """Functions traced by jax: decorated with @jax.jit (directly or via
    partial), or defined in a scope where ``jax.jit(name, ...)`` /
    ``jax.jit(lambda ...)`` wraps them (the jitted-factory idiom used
    all over ops/ and da/)."""
    jitted: list[ast.FunctionDef] = []
    wrapped_names: set[str] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and ctx.resolve(node.func) in \
                _JIT_NAMES:
            for arg in node.args[:1]:
                if isinstance(arg, ast.Name):
                    wrapped_names.add(arg.id)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if any(_is_jit_decorator(d, ctx) for d in node.decorator_list):
            jitted.append(node)
        elif node.name in wrapped_names:
            jitted.append(node)
    return jitted


@register
class JitPurityRule(Rule):
    id = "jit-purity"
    help = ("side effects inside jitted functions run once at trace "
            "time or force host round-trips — keep device code pure")

    def check(self, ctx: FileContext, cfg: RuleConfig):
        for fn in _jitted_functions(ctx):
            for node in ast.walk(fn):
                if isinstance(node, ast.Global):
                    yield (node.lineno, node.col_offset,
                           f"global mutation inside jitted {fn.name}() "
                           "(runs once at trace time, then never again)")
                    continue
                if not isinstance(node, ast.Call):
                    continue
                name = ctx.resolve(node.func)
                attr = (node.func.attr
                        if isinstance(node.func, ast.Attribute)
                        else None)
                if name == "print":
                    yield (node.lineno, node.col_offset,
                           f"print inside jitted {fn.name}() fires at "
                           "trace time only (use jax.debug.print)")
                elif _is_logging_call(node, ctx):
                    yield (node.lineno, node.col_offset,
                           f"logging/telemetry inside jitted {fn.name}()"
                           " fires at trace time only (hoist to the "
                           "caller)")
                elif name in _HOST_CALLS:
                    yield (node.lineno, node.col_offset,
                           f"{name}() inside jitted {fn.name}() forces "
                           "a host round-trip per call")
                elif attr in _HOST_ATTRS:
                    yield (node.lineno, node.col_offset,
                           f".{attr}() inside jitted {fn.name}() forces "
                           "a host sync")
                elif name == "float" and node.args:
                    yield (node.lineno, node.col_offset,
                           f"float() cast inside jitted {fn.name}() "
                           "concretizes a tracer (host round-trip)")


# ---------------------------------------------------------------------------
# the migrated regex gates
# ---------------------------------------------------------------------------


@register
class PrintRule(Rule):
    id = "print-call"
    help = ("library code logs through celestia_app_tpu.obs.log; print "
            "is reserved for the CLI and operator tools (rule allow "
            "list in analyze.toml)")

    def check(self, ctx: FileContext, cfg: RuleConfig):
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "print"):
                yield (node.lineno, node.col_offset,
                       "print call in a library module (use "
                       "celestia_app_tpu.obs.log, or allowlist with a "
                       "reason)")


@register
class UrlopenRule(Rule):
    id = "raw-urlopen"
    help = ("peer I/O goes through the hardened net/transport.py "
            "PeerClient (timeouts, retries, circuit breaker); raw "
            "urlopen bypasses all of it")

    def check(self, ctx: FileContext, cfg: RuleConfig):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = ctx.resolve(node.func) or ""
            if name == "urlopen" or name.endswith(".urlopen"):
                yield (node.lineno, node.col_offset,
                       "direct urlopen outside net/transport.py (route "
                       "peer I/O through the hardened PeerClient, or "
                       "allowlist with a reason)")
