"""The analysis plane: an AST lint framework for consensus code.

A consensus state machine forks on *any* nondeterminism — wall-clock
reads, RNG, float arithmetic, set/dict iteration order — in the
Prepare/Process/apply path, and degrades silently when exceptions are
swallowed without a log line or a telemetry counter, when side effects
leak into jitted device code, or when a lock-guarded structure is
touched outside its lock. Each of those invariants used to be either a
one-off regex test (the print and urlopen gates) or nothing at all.
This package is the single home for all of them:

- ``engine``   — rule registry, pragma handling, file walking, scoping
- ``config``   — ``analyze.toml`` loader (waivers, per-rule scope)
- ``rules_*``  — the three rule families (determinism, effects, locks)
- ``report``   — text and JSON reporters
- ``racecheck``— the runtime lock-order detector (``CELESTIA_RACE=1``)

Run it as ``python -m celestia_app_tpu analyze [--json]``; the tier-1
test ``tests/test_analyze.py`` runs the full tree and fails on any
non-waived violation, so every rule stays green as the codebase grows.

This module keeps imports lazy so ``racecheck`` can be imported from
``celestia_app_tpu/__init__`` without pulling the whole framework in.
"""

from __future__ import annotations


def load_config(path: str | None = None):
    from celestia_app_tpu.tools.analyze.config import load_config as _lc

    return _lc(path)


def run_analysis(root: str | None = None, config=None,
                 only_rules=None, cache=None):
    from celestia_app_tpu.tools.analyze.engine import run_analysis as _ra

    return _ra(root, config, only_rules=only_rules, cache=cache)


def default_package_root() -> str:
    import os

    import celestia_app_tpu

    return os.path.dirname(os.path.abspath(celestia_app_tpu.__file__))


def default_config_path() -> str:
    import os

    return os.path.join(
        os.path.dirname(default_package_root()), "analyze.toml"
    )
