"""Rule engine: AST walk, scoping, pragmas, waivers.

Rules are small classes registered in ``REGISTRY``; each gets a
``FileContext`` (parsed AST with parent links, import-alias resolution,
enclosing-function lookup) and yields ``(line, col, message)`` tuples.
The engine owns everything rules should not re-implement:

- **scoping** — per-rule ``include``/``exclude``/``allow`` path lists
  from ``analyze.toml``; an include entry may be ``path::symbol`` to
  scope a rule to one function/method (the consensus apply path);
- **pragmas** — ``# lint: disable=<rule>[,<rule>...]`` on the flagged
  line suppresses the violation entirely (strongest precedence);
- **waivers** — ``[[waivers]]`` entries downgrade matching violations
  to non-fatal, and stale waivers (matching nothing) are errors.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re

from celestia_app_tpu.tools.analyze.config import AnalyzeConfig, RuleConfig

_PRAGMA_RE = re.compile(r"#\s*lint:\s*disable=([\w\-, ]+)")


@dataclasses.dataclass
class Violation:
    rule: str
    severity: str      # "error" | "warning"
    path: str          # package-relative, posix separators
    line: int
    col: int
    message: str
    waived: bool = False
    waiver_reason: str | None = None
    # interprocedural rules (det-reach, scope-drift, blocking-under-
    # lock, transitive jit-purity) attach the root→sink chain of
    # "path::qualname" node ids; per-file rules leave it None
    call_path: list[str] | None = None
    # effect rules (xfer-reach, lock-order, guarded-by-flow) attach a
    # structured effect-path payload (sink kind, lock cycle with both
    # acquisition chains, guarded attr + obligation chain)
    effect: dict | None = None

    def __str__(self) -> str:
        tag = "waived" if self.waived else self.severity
        base = (f"{self.path}:{self.line}:{self.col}: "
                f"{tag}[{self.rule}] {self.message}")
        if self.call_path:
            base += " | call path: " + " -> ".join(self.call_path)
        return base


class FileContext:
    """One parsed source file plus the lookups every rule needs."""

    def __init__(self, path: str, source: str):
        self.path = path                       # package-relative, posix
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                child._lint_parent = parent  # type: ignore[attr-defined]
        self.aliases = self._import_aliases()
        self._funcs = self._function_spans()
        self.pragmas: dict[int, set[str]] = {}
        for lineno, text in enumerate(self.lines, 1):
            m = _PRAGMA_RE.search(text)
            if m:
                self.pragmas[lineno] = {
                    r.strip() for r in m.group(1).split(",") if r.strip()
                }

    # -- imports ---------------------------------------------------------

    def _import_aliases(self) -> dict[str, str]:
        """local name -> canonical dotted path, from this file's imports
        (``import numpy as np`` -> np=numpy; ``from time import time as
        now`` -> now=time.time)."""
        aliases: dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    aliases[a.asname or a.name] = f"{node.module}.{a.name}"
        return aliases

    def resolve(self, node: ast.AST) -> str | None:
        """Canonical dotted name of an expression (through import
        aliases), or None for anything not a plain Name/Attribute
        chain — ``np.random.default_rng`` -> ``numpy.random.default_rng``."""
        parts: list[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(node.id)
        parts.reverse()
        head = self.aliases.get(parts[0], parts[0])
        return ".".join([head] + parts[1:])

    # -- enclosing functions ---------------------------------------------

    def _function_spans(self) -> list[tuple[int, int, str]]:
        spans: list[tuple[int, int, str]] = []

        def visit(node: ast.AST, qual: list[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    name = ".".join(qual + [child.name])
                    spans.append(
                        (child.lineno, child.end_lineno or child.lineno,
                         name)
                    )
                    visit(child, qual + [child.name])
                elif isinstance(child, ast.ClassDef):
                    visit(child, qual + [child.name])
                else:
                    visit(child, qual)

        visit(self.tree, [])
        return spans

    def enclosing_qualname(self, line: int) -> str | None:
        """Qualified name of the innermost function containing `line`
        (``ClassName.method.inner``), or None at module level."""
        best: tuple[int, int, str] | None = None
        for start, end, name in self._funcs:
            if start <= line <= end:
                if best is None or start > best[0]:
                    best = (start, end, name)
        return best[2] if best else None

    def parents(self, node: ast.AST):
        while True:
            node = getattr(node, "_lint_parent", None)
            if node is None:
                return
            yield node


class Rule:
    id = "base"
    default_severity = "error"
    help = ""

    def check(self, ctx: FileContext, cfg: RuleConfig):
        raise NotImplementedError
        yield  # pragma: no cover


class ProgramRule(Rule):
    """A whole-program rule: runs ONCE over the linked call graph
    (``callgraph.Program``) after the per-file pass, yielding complete
    ``Violation`` objects (severity is overwritten from config, pragma
    and waiver precedence apply as usual). Program rules self-scope —
    the engine's per-file include filtering does not apply, because a
    violation's path (the sink) and its scope anchor (the root holding
    the lock / the consensus root) are different files."""

    whole_program = True

    def check(self, ctx: FileContext, cfg: RuleConfig):
        return iter(())

    def check_program(self, program, config: AnalyzeConfig,
                      cfg: RuleConfig):
        raise NotImplementedError
        yield  # pragma: no cover


REGISTRY: dict[str, Rule] = {}


def register(rule_cls: type[Rule]) -> type[Rule]:
    REGISTRY[rule_cls.id] = rule_cls()
    return rule_cls


def _load_rules() -> None:
    # importing the rule modules populates REGISTRY via @register
    from celestia_app_tpu.tools.analyze import (  # noqa: F401
        effects,
        rules_determinism,
        rules_effects,
        rules_locks,
        taint,
    )


def registered_rule_ids() -> set[str]:
    """All registered rule ids (loads the rule modules)."""
    _load_rules()
    return set(REGISTRY)


# ---------------------------------------------------------------------------
# scoping
# ---------------------------------------------------------------------------


def _path_matches(path: str, entry: str) -> bool:
    """Prefix path match: ``wire/`` matches the tree, ``cli.py`` the
    file. An entry may carry a ``::symbol`` suffix (checked later)."""
    entry = entry.split("::", 1)[0]
    return path == entry or path.startswith(entry)


def _in_scope(path: str, cfg: RuleConfig) -> bool:
    if any(_path_matches(path, e) for e in cfg.exclude):
        return False
    if any(_path_matches(path, e) for e in cfg.allow):
        return False
    if cfg.include or cfg.include_extra:
        return any(_path_matches(path, e)
                   for e in (*cfg.include, *cfg.include_extra))
    return True


def _symbol_scopes(path: str, cfg: RuleConfig) -> list[str] | None:
    """The ``::symbol`` restrictions that apply to this file, or None
    when any plain include (or no include at all) covers it whole."""
    if not cfg.include and not cfg.include_extra:
        return None
    symbols: list[str] = []
    for e in (*cfg.include, *cfg.include_extra):
        base, _, sym = e.partition("::")
        if not _path_matches(path, base):
            continue
        if not sym:
            return None
        symbols.append(sym)
    return symbols


# ---------------------------------------------------------------------------
# the run
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Report:
    root: str
    violations: list[Violation]
    files_scanned: int
    rules_run: list[str]
    config_path: str | None
    wall_s: float = 0.0
    cache_hits: int = 0
    cache_misses: int = 0
    # the linked callgraph.Program when any program rule ran (the
    # --scopes audit reads it); never serialized
    program: object = None

    @property
    def errors(self) -> list[Violation]:
        return [v for v in self.violations
                if not v.waived and v.severity == "error"]

    @property
    def warnings(self) -> list[Violation]:
        return [v for v in self.violations
                if not v.waived and v.severity == "warning"]

    @property
    def waived(self) -> list[Violation]:
        return [v for v in self.violations if v.waived]


def iter_python_files(root: str, exclude: list[str]):
    for dirpath, dirnames, filenames in os.walk(root):
        rel_dir = os.path.relpath(dirpath, root)
        rel_dir = "" if rel_dir == "." else rel_dir.replace(os.sep, "/")
        dirnames[:] = sorted(
            d for d in dirnames
            if not any(_path_matches(
                (f"{rel_dir}/{d}" if rel_dir else d) + "/", e)
                or d == e.rstrip("/") for e in exclude)
        )
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            rel = f"{rel_dir}/{name}" if rel_dir else name
            if any(_path_matches(rel, e) for e in exclude):
                continue
            yield os.path.join(dirpath, name), rel


def run_analysis(root: str | None = None,
                 config: AnalyzeConfig | None = None,
                 only_rules: set[str] | None = None,
                 cache: bool | str | None = None) -> Report:
    """Analyze every ``.py`` under `root` (default: the installed
    ``celestia_app_tpu`` package) against `config` (default: the
    committed ``analyze.toml``). Stale waivers surface as synthetic
    ``stale-waiver`` errors so the ledger cannot rot.

    `cache` enables the per-file incremental result cache (True for
    the default location next to `root`, or an explicit path): files
    whose sha256 is unchanged reuse their per-file violations and
    call-graph fragment; the interprocedural rules re-link and re-run
    from fragments every time (FORMATS §11.4)."""
    import hashlib
    import time

    t0 = time.perf_counter()
    _load_rules()
    if only_rules is not None:
        unknown = sorted(set(only_rules) - set(REGISTRY))
        if unknown:
            # a silent empty run would let a renamed rule id turn the
            # tier-1 wrapper gates (print/urlopen) into no-ops
            raise ValueError(f"unknown rule id(s): {unknown}")
    if root is None:
        from celestia_app_tpu.tools.analyze import default_package_root

        root = default_package_root()
    if config is None:
        from celestia_app_tpu.tools.analyze.config import load_config

        config = load_config()
    for w in config.waivers:
        w.used = 0
    violations: list[Violation] = []
    files = 0
    rules_run = sorted(
        rid for rid in REGISTRY
        if config.rule(rid).severity != "off"
        and (only_rules is None or rid in only_rules)
    )
    program_rules = [rid for rid in rules_run
                     if hasattr(REGISTRY[rid], "check_program")]
    need_program = bool(program_rules)
    result_cache = None
    if cache:
        from celestia_app_tpu.tools.analyze import cache as cache_mod

        result_cache = cache_mod.ResultCache.open(
            cache if isinstance(cache, str)
            else cache_mod.default_cache_path(root),
            cache_mod.cache_key(config, rules_run, root),
        )
    cache_hits = cache_misses = 0
    fragments: dict[str, dict] = {}
    pragmas_by_file: dict[str, dict[int, set[str]]] = {}

    from celestia_app_tpu.tools.analyze import callgraph

    for abspath, rel in iter_python_files(root, config.exclude):
        files += 1
        with open(abspath, "rb") as f:
            data = f.read()
        sha = hashlib.sha256(data).hexdigest()
        entry = result_cache.lookup(rel, sha) if result_cache else None
        if entry is not None:
            cache_hits += 1
            for rid, rows in entry["violations"].items():
                # "parse-error" is synthetic (not a registered rule):
                # it must survive warm runs like any other result
                if rid != "parse-error" and rid not in rules_run:
                    continue
                sev = ("error" if rid == "parse-error"
                       else config.rule(rid).severity)
                for line, col, msg in rows:
                    violations.append(Violation(
                        rule=rid, severity=sev,
                        path=rel, line=line, col=col, message=msg,
                    ))
            frag = entry.get("fragment")
            if frag is not None:
                fragments[rel] = frag
                pragmas_by_file[rel] = {
                    int(k): set(v)
                    for k, v in frag.get("pragmas", {}).items()
                }
            continue
        cache_misses += 1
        source = data.decode("utf-8")
        rows_by_rule: dict[str, list] = {}
        try:
            ctx = FileContext(rel, source)
        except SyntaxError as e:
            rows_by_rule["parse-error"] = [
                [e.lineno or 0, e.offset or 0,
                 f"cannot parse: {e.msg}"]]
            violations.append(Violation(
                rule="parse-error", severity="error", path=rel,
                line=e.lineno or 0, col=e.offset or 0,
                message=f"cannot parse: {e.msg}",
            ))
            if result_cache:
                result_cache.put(rel, sha, rows_by_rule, None)
            continue
        for rid in rules_run:
            rcfg = config.rule(rid)
            if not _in_scope(rel, rcfg):
                continue
            symbols = _symbol_scopes(rel, rcfg)
            for line, col, msg in REGISTRY[rid].check(ctx, rcfg):
                if rid in ctx.pragmas.get(line, set()):
                    continue  # pragma wins over everything
                if symbols is not None:
                    qual = ctx.enclosing_qualname(line) or ""
                    parts = qual.split(".")
                    if not any(sym in parts for sym in symbols):
                        continue
                rows_by_rule.setdefault(rid, []).append(
                    [line, col, msg])
                violations.append(Violation(
                    rule=rid, severity=rcfg.severity, path=rel,
                    line=line, col=col, message=msg,
                ))
        frag = None
        if need_program or result_cache:
            frag = callgraph.build_fragment(ctx)
            fragments[rel] = frag
            pragmas_by_file[rel] = ctx.pragmas
        if result_cache:
            result_cache.put(rel, sha, rows_by_rule, frag)
    program = None
    if need_program:
        program = callgraph.Program(fragments)
        for rid in program_rules:
            rcfg = config.rule(rid)
            for v in REGISTRY[rid].check_program(program, config, rcfg):
                if v.rule in pragmas_by_file.get(v.path, {}).get(
                        v.line, set()):
                    continue  # pragma wins over everything
                v.severity = rcfg.severity
                violations.append(v)
    if result_cache:
        result_cache.save()
    # waivers: first match wins, counted for staleness
    for v in violations:
        for w in config.waivers:
            if w.rule == v.rule and _path_matches(v.path, w.path):
                v.waived, v.waiver_reason = True, w.reason
                w.used += 1
                break
    for w in config.waivers:
        # staleness is only decidable when the waiver's rule actually
        # ran (a --rule-filtered run must not condemn the others)
        if w.used == 0 and w.rule in rules_run:
            violations.append(Violation(
                rule="stale-waiver", severity="error",
                path=w.path, line=0, col=0,
                message=(f"waiver for rule {w.rule!r} matched nothing — "
                         "remove it (or it is masking a typo)"),
            ))
    violations.sort(key=lambda v: (v.path, v.line, v.col, v.rule,
                                   v.message))
    return Report(
        root=root, violations=violations, files_scanned=files,
        rules_run=rules_run, config_path=config.source_path,
        wall_s=time.perf_counter() - t0,
        cache_hits=cache_hits, cache_misses=cache_misses,
        program=program,
    )
