"""Consensus-determinism rules.

Every validator must compute bit-identical state from the same block:
a wall-clock read, an unseeded RNG draw, float rounding, or the
iteration order of an unsorted set can differ between two honest nodes
and fork the chain (the failure class arXiv:1910.01247 / 2301.08295
assume away by construction). These rules are scoped, via
``analyze.toml``, to the consensus-critical modules: ``wire/``,
``chain/app.py``, the ``chain/consensus.py`` apply path, ``da/``, and
the ``das/`` proof-serving code.
"""

from __future__ import annotations

import ast

from celestia_app_tpu.tools.analyze.engine import (
    FileContext,
    Rule,
    register,
)
from celestia_app_tpu.tools.analyze.config import RuleConfig

_WALLCLOCK = {
    "time.time", "time.time_ns",
    "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today",
}

_RNG_EXACT = {"os.urandom", "os.getrandom"}
_RNG_PREFIXES = ("random.", "numpy.random.", "uuid.", "secrets.",
                 "jax.random.PRNGKey")


@register
class WallClockRule(Rule):
    id = "det-wallclock"
    help = ("wall-clock reads in consensus-critical code fork the chain; "
            "use the block time threaded through the header")

    def check(self, ctx: FileContext, cfg: RuleConfig):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = ctx.resolve(node.func)
            if name in _WALLCLOCK:
                yield (node.lineno, node.col_offset,
                       f"wall-clock read {name}() in consensus-critical "
                       "code (use the block time from the header/config)")


@register
class RngRule(Rule):
    id = "det-rng"
    help = ("ambient randomness in consensus-critical code forks the "
            "chain; thread a seeded rng from config")

    def check(self, ctx: FileContext, cfg: RuleConfig):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = ctx.resolve(node.func)
            if name is None:
                continue
            if name in _RNG_EXACT or any(
                    name.startswith(p) or name == p.rstrip(".")
                    for p in _RNG_PREFIXES):
                yield (node.lineno, node.col_offset,
                       f"nondeterministic rng {name}() in consensus-"
                       "critical code (thread a seeded generator from "
                       "config instead)")


@register
class FloatRule(Rule):
    id = "det-float"
    help = ("float arithmetic is not bit-stable across platforms/"
            "backends; consensus math must stay integral")

    def check(self, ctx: FileContext, cfg: RuleConfig):
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.Constant)
                    and isinstance(node.value, float)):
                yield (node.lineno, node.col_offset,
                       f"float literal {node.value!r} in consensus-"
                       "critical code (use integers / fixed-point)")
            elif isinstance(node, ast.BinOp) and isinstance(node.op,
                                                            ast.Div):
                yield (node.lineno, node.col_offset,
                       "true division yields a float in consensus-"
                       "critical code (use // or integer math)")
            elif (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "float"):
                yield (node.lineno, node.col_offset,
                       "float() cast in consensus-critical code")


def _is_set_expr(node: ast.AST, ctx: FileContext) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        name = ctx.resolve(node.func)
        if name in ("set", "frozenset"):
            return True
        # set ops that return sets: a.union(b) etc. are left to review;
        # the syntactic cases are the ones that actually bite
    return False


@register
class SetIterRule(Rule):
    id = "det-set-iter"
    help = ("set iteration order depends on hash seeds and insertion "
            "history; sort before iterating in consensus-critical code")

    def check(self, ctx: FileContext, cfg: RuleConfig):
        msg = ("iteration over a set is order-nondeterministic in "
               "consensus-critical code (wrap in sorted())")
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.For) and _is_set_expr(node.iter, ctx):
                yield (node.iter.lineno, node.iter.col_offset, msg)
            elif isinstance(node, (ast.ListComp, ast.GeneratorExp,
                                   ast.DictComp, ast.SetComp)):
                for gen in node.generators:
                    if _is_set_expr(gen.iter, ctx):
                        yield (gen.iter.lineno, gen.iter.col_offset, msg)
            elif isinstance(node, ast.Call):
                name = ctx.resolve(node.func)
                if name in ("list", "tuple", "enumerate") and node.args \
                        and _is_set_expr(node.args[0], ctx):
                    yield (node.lineno, node.col_offset, msg)


_HASH_FUNCS = {"hashlib.sha256", "hashlib.sha512", "hashlib.md5",
               "hashlib.blake2b", "json.dumps"}
_HASH_ATTRS = {"update", "digest", "hexdigest"}
_DICT_ITER_ATTRS = {"keys", "values", "items"}


def _dict_iter_call(node: ast.AST, ctx: FileContext) -> ast.Call | None:
    """A ``.keys()/.values()/.items()`` call inside `node` that is NOT
    wrapped in ``sorted(...)`` somewhere on the way up (sorting restores
    determinism — that is the fix the rule prescribes)."""
    for sub in ast.walk(node):
        if not (isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr in _DICT_ITER_ATTRS
                and not sub.args):
            continue
        if any(isinstance(p, ast.Call) and ctx.resolve(p.func) == "sorted"
               for p in ctx.parents(sub)):
            continue
        return sub
    return None


@register
class DictHashRule(Rule):
    id = "det-dict-hash"
    help = ("dict iteration order is insertion order, which can differ "
            "between honest nodes; sort before hashing/serializing")

    def check(self, ctx: FileContext, cfg: RuleConfig):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = ctx.resolve(node.func)
            attr = (node.func.attr
                    if isinstance(node.func, ast.Attribute) else None)
            if not (name in _HASH_FUNCS or attr in _HASH_ATTRS
                    or (name or "").startswith("hashlib.")):
                continue
            if attr in _HASH_ATTRS and name not in _HASH_FUNCS \
                    and not (name or "").startswith("hashlib."):
                # .update() is also the dict-merge verb: only treat it
                # as a sink on a hash-shaped receiver (h, hasher,
                # *sha*, *digest*, ...) so `dst.update(src.items())`
                # stays legal
                recv = (ctx.resolve(node.func.value) or "")
                tail = recv.rsplit(".", 1)[-1].lower()
                if tail not in ("h", "hasher", "hd") and not any(
                        k in tail for k in ("hash", "sha", "md5",
                                            "blake", "digest")):
                    continue
            if name == "json.dumps" and any(
                    kw.arg == "sort_keys"
                    and isinstance(kw.value, ast.Constant)
                    and bool(kw.value.value)
                    for kw in node.keywords):
                continue  # sort_keys=True restores determinism
            for arg in node.args:
                hit = _dict_iter_call(arg, ctx)
                if hit is not None:
                    yield (hit.lineno, hit.col_offset,
                           "dict iteration feeding a hash/serialization "
                           "call (sort the items first — insertion order "
                           "is not consensus)")
                    break
