"""Whole-package call graph for the interprocedural rules.

Two stages, split so the incremental cache can persist the per-file
half and re-derive only what changed:

1. ``build_fragment(ctx)`` — a **per-file, JSON-serializable** summary:
   every function definition (qualified ``Class.method.inner`` names),
   its outgoing call *descriptors* (resolved as far as one file can —
   import aliases via ``FileContext.resolve``, ``self.`` receivers,
   ``self.<attr>.`` receivers typed from ``__init__`` assignments),
   the taint marks the engines need (wall-clock/rng/env/hash-iter
   sources, blocking operations, jit-impurity findings), the
   ``with self.<lock>`` frames with the calls made inside them, and
   the class table (methods, bases, attribute types).

   Since v4 the fragment also carries the per-function **effect
   facts** the bottom-up effect system (``effects.py``) folds into
   transitive summaries: host↔device boundary sites (raw
   ``jax.device_get``/``device_put``, the ``np.asarray`` family,
   ``.item()``, and the counted ``obs.xfer`` helpers — a ``# xfer:
   ledger`` line marker declares a raw site as ledger-internal), every
   lock ``with``-frame with its lexical span, accesses to ``#
   guarded-by:`` fields with their held/unheld verdict, raised
   exception types with broad/narrow ``except`` shield spans, and a
   file-level ``imports_jax`` flag (``np.asarray`` can only
   materialize a device value in a file that can hold one).

2. ``Program(fragments)`` — links descriptors into concrete edges
   against the global definition table. Resolution order:

   - ``dotted``  — ``celestia_app_tpu.da.eds.extend_shares`` maps the
     longest module prefix to a file (``da/eds.py``) and the remainder
     to a qualname (classes resolve to ``__init__``);
   - ``local``   — a module-level function or class in the same file;
   - ``self``    — the enclosing class's method table, then package
     base classes (linear MRO walk);
   - ``selfattr``— ``self.codec.open_sample()`` through the attribute
     type recorded from ``self.codec = SomeCodec(...)``;
   - ``attr``    — **conservative dynamic-dispatch fallback**: an
     unresolvable receiver links the call to *every* package method of
     that name (this is what keeps the codec registry's
     ``_encode_impl`` hooks and duck-typed stores in the graph).
     Names that collide with builtin container/str/file methods are
     skipped — linking every ``.get()`` to every ``get`` method would
     drown the graph in noise, and those calls never reach package
     code through a builtin receiver anyway;
   - ``closure`` / bare references — defining a nested function or
     passing ``self._loop`` as a callback edges to it (how the warmer
     threads and seed listeners stay reachable).

Sound where it can be, conservative where it cannot: missing dynamic
edges are the only false-negative channel, and the fallback policy
above is the documented trade (DESIGN, "The analysis plane").
"""

from __future__ import annotations

import ast
import dataclasses
import re

from celestia_app_tpu.tools.analyze.engine import FileContext
from celestia_app_tpu.tools.analyze.rules_determinism import (
    _RNG_EXACT,
    _RNG_PREFIXES,
    _WALLCLOCK,
    _dict_iter_call,
    _HASH_FUNCS,
)
from celestia_app_tpu.tools.analyze.rules_locks import (
    _guarded_attrs,
    _holds_lock,
)

FRAGMENT_VERSION = 4

PACKAGE = "celestia_app_tpu"

# attr-fallback names skipped because they collide with builtin
# container/str/bytes/file/threading methods — a call through one of
# these on an unknown receiver is overwhelmingly a builtin, not package
# dispatch. Package hook names (``_encode_impl``, ``open_sample``,
# ``repair`` ...) are distinctive and stay linked.
FALLBACK_SKIP = frozenset({
    "get", "items", "keys", "values", "update", "append", "extend",
    "add", "pop", "popitem", "clear", "copy", "remove", "discard",
    "sort", "reverse", "insert", "count", "index", "join", "split",
    "rsplit", "splitlines", "strip", "lstrip", "rstrip", "encode",
    "decode", "format", "replace", "startswith", "endswith", "lower",
    "upper", "title", "zfill", "read", "write", "readline",
    "readlines", "close", "flush", "seek", "tell", "fileno",
    "hexdigest", "digest", "hex", "to_bytes", "from_bytes",
    "bit_length", "acquire", "release", "wait", "notify", "notify_all",
    "set", "is_set", "put", "get_nowait", "put_nowait", "start",
    "cancel", "done", "result", "submit", "shutdown", "group",
    "groups", "match", "search", "findall", "finditer", "sub",
    "item", "tolist", "tobytes", "astype", "reshape", "flatten",
    "transpose", "setdefault", "union", "intersection", "difference",
    "issubset", "mkdir", "exists", "send", "recv", "connect", "bind",
    "name", "next", "degree", "request", "getresponse", "sync",
})

# see Program._fallback: these layers call the library, never the
# reverse, so name-match fallback must not land in them
_FALLBACK_TARGET_EXCLUDE = ("tools/", "cli.py", "testing/", "client/",
                            "service/")

# blocking-operation classification (the ``blocking-under-lock`` sinks)
_BLOCK_SLEEP = {"time.sleep"}
_BLOCK_FSYNC = {"os.fsync", "os.fdatasync"}
_BLOCK_NET_EXACT = {"socket.socket", "socket.create_connection"}
_BLOCK_NET_PREFIX = ("urllib.request.", "http.client.", "subprocess.")
# THE definition of "wraps a function for device tracing" — shared by
# the per-file jit-purity rule (rules_effects imports it), the
# transitive pass, and the blocking-under-lock jit-compile sink, so
# the passes can never disagree on which functions are jitted
JIT_WRAPPERS = {"jax.jit", "jit", "pl.pallas_call", "jax.pmap"}
_BLOCK_JIT = JIT_WRAPPERS

# host↔device boundary sites (the ``xfer-reach`` effect facts).
# d2h-raw / h2d-raw are uncounted crossings wherever they appear;
# asarray / item only *can* materialize a device value in a file that
# imports jax (the fragment's ``imports_jax`` flag — the rule checks
# it); ledgered sites are the counted obs/xfer helpers and are what
# every reachable crossing must route through.
_XFER_D2H_RAW = {"jax.device_get"}
_XFER_H2D_RAW = {"jax.device_put"}
_XFER_ASARRAY = {"numpy.asarray", "numpy.array",
                 "numpy.ascontiguousarray"}
_XFER_LEDGERED = {
    "celestia_app_tpu.obs.xfer.to_host",
    "celestia_app_tpu.obs.xfer.to_device",
    "celestia_app_tpu.obs.xfer.ensure_host",
}
# a raw site INSIDE the ledger implementation itself (obs/xfer.py's
# own device_put/device_get/np.asarray) declares itself with this line
# marker — the static twin of the runtime `_explicit()` thread flag
_XFER_MARKER_RE = re.compile(r"#\s*xfer:\s*ledger\b")


def _classify_xfer(name: str | None, attr: str | None,
                   ) -> tuple[str, str] | None:
    """(kind, what) when a resolved call is a host↔device boundary
    site: a raw crossing, an asarray-family materialization, a host
    sync, or one of the counted ledger helpers."""
    if name is not None:
        if name in _XFER_D2H_RAW:
            return ("d2h-raw", name)
        if name in _XFER_H2D_RAW:
            return ("h2d-raw", name)
        if name in _XFER_ASARRAY:
            return ("asarray", name)
        if name in _XFER_LEDGERED:
            return ("ledgered", name.rsplit(".", 1)[-1])
    if attr == "item":
        return ("item", ".item")
    return None


def _imports_jax(ctx: FileContext) -> bool:
    """True when the file imports jax anywhere (module level or inside
    a function — the engine-gated lazy-import idiom counts)."""
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Import):
            if any(a.name == "jax" or a.name.startswith("jax.")
                   for a in node.names):
                return True
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod == "jax" or mod.startswith("jax."):
                return True
    return False


def _broad_handler_types(handler: ast.ExceptHandler,
                         ctx: FileContext) -> list[str]:
    """The exception names a handler shields: ``["*"]`` for bare /
    Exception / BaseException handlers, the resolved type names
    otherwise (unresolvable entries are dropped — conservative: an
    unknown handler shields nothing)."""
    if handler.type is None:
        return ["*"]
    types = (handler.type.elts if isinstance(handler.type, ast.Tuple)
             else [handler.type])
    out: list[str] = []
    for t in types:
        name = ctx.resolve(t)
        if name in ("Exception", "BaseException", "builtins.Exception",
                    "builtins.BaseException"):
            return ["*"]
        if name is not None:
            out.append(name.rsplit(".", 1)[-1])
    return out


# jit-impurity body findings (shared with rules_effects via
# ``impure_findings`` below)
_JIT_HOST_CALLS = {"numpy.asarray", "numpy.array", "numpy.frombuffer",
                   "jax.device_get"}
_JIT_HOST_ATTRS = {"block_until_ready", "item"}
_LOG_METHODS = {"debug", "info", "warning", "error", "exception"}
_TELEMETRY_METHODS = {"incr", "observe", "measure_since", "gauge",
                      "counter"}


def _is_logging_call(node: ast.Call, ctx: FileContext) -> bool:
    if not isinstance(node.func, ast.Attribute):
        return False
    attr = node.func.attr
    base = ctx.resolve(node.func.value) or ""
    base_tail = base.rsplit(".", 1)[-1].lower()
    if attr in _LOG_METHODS and ("log" in base_tail or base_tail in
                                 ("lg", "obs")):
        return True
    return attr in _TELEMETRY_METHODS


def impure_findings(fn: ast.AST, ctx: FileContext,
                    label: str) -> list[list]:
    """The jit-purity body checks for ONE function body, shared by the
    per-file rule (rules_effects) and the transitive program pass:
    ``[line, col, message]`` rows, message framed with `label`."""
    out: list[list] = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Global):
            out.append([node.lineno, node.col_offset,
                        f"global mutation inside jitted {label} "
                        "(runs once at trace time, then never again)"])
            continue
        if not isinstance(node, ast.Call):
            continue
        name = ctx.resolve(node.func)
        attr = (node.func.attr
                if isinstance(node.func, ast.Attribute) else None)
        if name == "print":
            out.append([node.lineno, node.col_offset,
                        f"print inside jitted {label} fires at trace "
                        "time only (use jax.debug.print)"])
        elif _is_logging_call(node, ctx):
            out.append([node.lineno, node.col_offset,
                        f"logging/telemetry inside jitted {label} "
                        "fires at trace time only (hoist to the "
                        "caller)"])
        elif name in _JIT_HOST_CALLS:
            out.append([node.lineno, node.col_offset,
                        f"{name}() inside jitted {label} forces a "
                        "host round-trip per call"])
        elif attr in _JIT_HOST_ATTRS:
            out.append([node.lineno, node.col_offset,
                        f".{attr}() inside jitted {label} forces a "
                        "host sync"])
        elif name == "float" and node.args:
            out.append([node.lineno, node.col_offset,
                        f"float() cast inside jitted {label} "
                        "concretizes a tracer (host round-trip)"])
    return out


# ---------------------------------------------------------------------------
# per-file fragment
# ---------------------------------------------------------------------------


def _classify_source(name: str | None) -> tuple[str, str] | None:
    """(kind, what) when a resolved call name is a determinism taint
    source: wall-clock, ambient rng, or an environment read."""
    if name is None:
        return None
    if name in _WALLCLOCK:
        return ("wallclock", name)
    if name in _RNG_EXACT or any(
            name.startswith(p) or name == p.rstrip(".")
            for p in _RNG_PREFIXES):
        return ("rng", name)
    if name == "os.getenv" or name.startswith("os.environ"):
        return ("env", name)
    return None


def _classify_blocking(name: str | None, attr: str | None,
                       ) -> tuple[str, str] | None:
    if name is not None:
        if name in _BLOCK_SLEEP:
            return ("sleep", name)
        if name in _BLOCK_FSYNC:
            return ("fsync", name)
        if (name in _BLOCK_NET_EXACT or name == "urlopen"
                or name.endswith(".urlopen")
                or name.startswith(_BLOCK_NET_PREFIX)):
            return ("net", name)
        if name in _BLOCK_JIT:
            return ("jit-compile", name)
        tail = name.rsplit(".", 1)[-1]
        if tail.startswith("jitted_"):
            # the repo's jitted-factory naming convention: calling a
            # factory can pay an XLA compile on a cold cache
            return ("jit-compile", name)
    if attr == "block_until_ready":
        return ("jit-compile", f".{attr}")
    return None


def _qualify(ctx: FileContext) -> dict[ast.AST, str]:
    """def/class node -> dotted qualname (``Class.method.inner``)."""
    quals: dict[ast.AST, str] = {}

    def visit(node: ast.AST, qual: list[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                q = qual + [child.name]
                quals[child] = ".".join(q)
                visit(child, q)
            else:
                visit(child, qual)

    visit(ctx.tree, [])
    return quals


def _enclosing_function(node: ast.AST, ctx: FileContext,
                        quals: dict) -> ast.AST | None:
    for p in ctx.parents(node):
        if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return p
    return None


def _descriptor(func_expr: ast.AST, ctx: FileContext,
                cls_name: str | None) -> list | None:
    """Call-target descriptor for a call's func expression, or None
    when nothing package-resolvable can come of it."""
    if isinstance(func_expr, ast.Attribute):
        recv = func_expr.value
        if isinstance(recv, ast.Name) and recv.id == "self":
            return ["self", func_expr.attr]
        if (isinstance(recv, ast.Attribute)
                and isinstance(recv.value, ast.Name)
                and recv.value.id == "self"):
            return ["selfattr", recv.attr, func_expr.attr]
        name = ctx.resolve(func_expr)
        if name is not None and name.startswith(PACKAGE + "."):
            return ["dotted", name]
        if name is not None:
            # a resolvable Name-rooted chain: if the root is an import
            # alias this is an external-module call (numpy.*, jax.*) —
            # falling back by attr name would invent edges into
            # unrelated package methods
            root = func_expr
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name) and root.id in ctx.aliases:
                return None
        return ["attr", func_expr.attr]
    if isinstance(func_expr, ast.Name):
        name = ctx.resolve(func_expr)
        if name is None:
            return None
        if name.startswith(PACKAGE + "."):
            return ["dotted", name]
        if "." not in name:
            return ["local", name]
    return None


def jitted_fn_nodes(ctx: FileContext) -> set[ast.AST]:
    """Functions traced by jax: @jax.jit/@partial(jax.jit)/@jax.pmap
    decoration, or wrapped by a ``jax.jit(name)``-style call in the
    same file. The ONE detector both jit-purity passes use."""
    wrapped: set[str] = set()
    jitted: set[ast.AST] = set()
    for node in ast.walk(ctx.tree):
        if (isinstance(node, ast.Call)
                and ctx.resolve(node.func) in JIT_WRAPPERS):
            for arg in node.args[:1]:
                if isinstance(arg, ast.Name):
                    wrapped.add(arg.id)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        decs = node.decorator_list
        hit = False
        for d in decs:
            name = ctx.resolve(d)
            if name in JIT_WRAPPERS:
                hit = True
            elif isinstance(d, ast.Call):
                fname = ctx.resolve(d.func)
                if fname in JIT_WRAPPERS:
                    hit = True
                elif fname in ("functools.partial", "partial") and d.args \
                        and ctx.resolve(d.args[0]) in JIT_WRAPPERS:
                    hit = True
        if hit or node.name in wrapped:
            jitted.add(node)
    return jitted


def _lock_frame(node: ast.AST, ctx: FileContext) -> tuple | None:
    """(lockname, with_line) when `node` sits inside a ``with
    self.<lock>``/``with <lock>`` frame whose context name looks like a
    lock (contains 'lock'); innermost wins."""
    for p in ctx.parents(node):
        if not isinstance(p, (ast.With, ast.AsyncWith)):
            continue
        for item in p.items:
            e = item.context_expr
            lockname = None
            if (isinstance(e, ast.Attribute)
                    and isinstance(e.value, ast.Name)
                    and e.value.id == "self"):
                lockname = e.attr
            elif isinstance(e, ast.Name):
                lockname = e.id
            if lockname is not None and "lock" in lockname.lower():
                return (lockname, p.lineno)
    return None


def build_fragment(ctx: FileContext) -> dict:
    """The per-file, cacheable half of the call graph (module
    docstring, stage 1)."""
    quals = _qualify(ctx)
    functions: dict[str, dict] = {}
    classes: dict[str, dict] = {}

    # class table: methods, bases, self-attr types from __init__
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        qual = quals[node]
        if "." in qual:
            continue  # nested classes: out of scope
        bases = []
        for b in node.bases:
            name = ctx.resolve(b)
            if name:
                bases.append(name)
        attr_types: dict[str, str] = {}
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Assign):
                continue
            if not (isinstance(sub.value, ast.Call)):
                continue
            vname = ctx.resolve(sub.value.func)
            if not vname:
                continue
            for t in sub.targets:
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    attr_types[t.attr] = vname
        classes[node.name] = {"bases": bases, "attr_types": attr_types,
                              "guarded": _guarded_attrs(node, ctx)}

    jitted_nodes = jitted_fn_nodes(ctx)
    file_imports_jax = _imports_jax(ctx)

    fn_nodes = [n for n in quals
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    node_by_qual = {quals[n]: n for n in fn_nodes}

    for fn in fn_nodes:
        qual = quals[fn]
        # enclosing class (for self-resolution): the nearest ClassDef
        # ancestor whose qual is a prefix
        cls_name = None
        for p in ctx.parents(fn):
            if isinstance(p, ast.ClassDef) and p in quals:
                cls_name = quals[p].split(".")[0]
                break
        info = {
            "line": fn.lineno,
            "end": fn.end_lineno or fn.lineno,
            "class": cls_name,
            "calls": [],
            "refs": [],
            "sources": [],
            "blocking": [],
            "impure": impure_findings(fn, ctx, f"{qual}()"),
            "jitted": fn in jitted_nodes,
            "locks": [],
            # effect facts (fragment v4; consumed by effects.py)
            "xfer": [],      # [kind, line, what]
            "frames": [],    # [lockname, is_self, start, end]
            "guarded": [],   # [attr, lockname, line, held]
            "raises": [],    # [exc name, line]
            "shielded": [],  # [start, end, name-or-"*"]
        }
        lock_blocks: dict[tuple, dict] = {}
        cls_guarded = (classes.get(cls_name, {}).get("guarded", {})
                       if cls_name else {})

        def _lock_entry(frame):
            if frame not in lock_blocks:
                lock_blocks[frame] = {"lock": frame[0], "line": frame[1],
                                      "calls": [], "blocking": []}
            return lock_blocks[frame]

        for node in ast.walk(fn):
            # nested defs belong to the nested function, not this one —
            # but defining them is a conservative closure edge
            if node is not fn and isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if _enclosing_function(node, ctx, quals) is fn:
                    info["calls"].append(
                        ["closure", quals[node], node.lineno])
                continue
            if _enclosing_function(node, ctx, quals) is not fn:
                continue
            # guarded-field access (read or write): the guarded-by-flow
            # seed facts — held is the LEXICAL verdict; the effect
            # system supplies the interprocedural one. Checked BEFORE
            # the call/ref dispatch: `self.X` attribute nodes
            # short-circuit that chain with `continue`
            if (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"
                    and node.attr in cls_guarded):
                g_lock = cls_guarded[node.attr]
                info["guarded"].append(
                    [node.attr, g_lock, node.lineno,
                     1 if _holds_lock(node, g_lock, ctx) else 0])
            if isinstance(node, ast.Call):
                name = ctx.resolve(node.func)
                attr = (node.func.attr
                        if isinstance(node.func, ast.Attribute) else None)
                src = _classify_source(name)
                if src is not None:
                    info["sources"].append([src[0], node.lineno, src[1]])
                xf = _classify_xfer(name, attr)
                if xf is not None:
                    xkind, xwhat = xf
                    if (xkind != "ledgered"
                            and node.lineno <= len(ctx.lines)
                            and _XFER_MARKER_RE.search(
                                ctx.lines[node.lineno - 1])):
                        xkind = "ledgered"
                    info["xfer"].append([xkind, node.lineno, xwhat])
                blk = _classify_blocking(name, attr)
                frame = _lock_frame(node, ctx)
                if blk is not None:
                    info["blocking"].append([blk[0], node.lineno, blk[1]])
                    if frame is not None:
                        _lock_entry(frame)["blocking"].append(
                            [blk[0], node.lineno, blk[1]])
                desc = _descriptor(node.func, ctx, cls_name)
                if desc is not None:
                    info["calls"].append(desc + [node.lineno])
                    if frame is not None:
                        _lock_entry(frame)["calls"].append(
                            desc + [node.lineno])
                # hash-iteration source: dict/set iteration feeding a
                # hash/serialization sink (the det-dict-hash detector)
                if (name in _HASH_FUNCS
                        or (name or "").startswith("hashlib.")):
                    for arg in node.args:
                        hit = _dict_iter_call(arg, ctx)
                        if hit is not None:
                            info["sources"].append(
                                ["hash-iter", hit.lineno,
                                 f"dict/set iteration into {name}"])
                            break
            elif isinstance(node, (ast.Attribute, ast.Name)):
                # bare references to package functions (callbacks,
                # Thread targets, listener registration)
                if isinstance(node, ast.Attribute):
                    par = node._lint_parent  # type: ignore[attr-defined]
                    if (isinstance(par, ast.Call)
                            and par.func is node):
                        continue  # already handled as a call
                    if (isinstance(node.value, ast.Name)
                            and node.value.id == "self"):
                        info["refs"].append(["self", node.attr,
                                             node.lineno])
                        continue
                    name = ctx.resolve(node)
                    if name and name.startswith(PACKAGE + "."):
                        info["refs"].append(["dotted", name, node.lineno])
                elif isinstance(node, ast.Name):
                    par = node._lint_parent  # type: ignore[attr-defined]
                    if (isinstance(par, ast.Call) and par.func is node):
                        continue
                    if isinstance(par, (ast.Attribute,)):
                        continue
                    if node.id in node_by_qual and isinstance(
                            par, (ast.keyword, ast.Call, ast.Tuple,
                                  ast.List, ast.Dict, ast.Return,
                                  ast.Assign)):
                        info["refs"].append(["local", node.id,
                                             node.lineno])
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                # every lock with-frame, with its lexical span — the
                # effect system derives held-at-line sets and the
                # static lock-acquisition graph from these
                for item in node.items:
                    e = item.context_expr
                    lockname = None
                    is_self = 0
                    if (isinstance(e, ast.Attribute)
                            and isinstance(e.value, ast.Name)
                            and e.value.id == "self"):
                        lockname, is_self = e.attr, 1
                    elif isinstance(e, ast.Name):
                        lockname = e.id
                    if lockname is not None and "lock" in lockname.lower():
                        info["frames"].append(
                            [lockname, is_self, node.lineno,
                             node.end_lineno or node.lineno])
            elif isinstance(node, ast.Raise) and node.exc is not None:
                exc = node.exc
                ename = (ctx.resolve(exc.func)
                         if isinstance(exc, ast.Call)
                         else ctx.resolve(exc))
                if ename:
                    info["raises"].append(
                        [ename.rsplit(".", 1)[-1], node.lineno])
            elif isinstance(node, ast.Try):
                lo = node.body[0].lineno
                hi = node.body[-1].end_lineno or node.body[-1].lineno
                for h in node.handlers:
                    for tname in _broad_handler_types(h, ctx):
                        info["shielded"].append([lo, hi, tname])
            # os.environ read outside a call (subscript / in-test)
            if (isinstance(node, ast.Attribute)
                    and ctx.resolve(node) == "os.environ"):
                info["sources"].append(["env", node.lineno, "os.environ"])
        info["locks"] = list(lock_blocks.values())
        # one source row per (kind, line): `os.environ.get(...)` is one
        # env read, not a call hit plus an attribute hit
        dedup: dict[tuple, list] = {}
        for row in info["sources"]:
            dedup.setdefault((row[0], row[1]), row)
        info["sources"] = sorted(dedup.values(),
                                 key=lambda r: (r[1], r[0]))
        functions[qual] = info

    return {
        "version": FRAGMENT_VERSION,
        "path": ctx.path,
        "imports_jax": file_imports_jax,
        "functions": functions,
        "classes": classes,
        "pragmas": {str(k): sorted(v) for k, v in ctx.pragmas.items()},
    }


# ---------------------------------------------------------------------------
# linking
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Node:
    id: str                 # "chain/app.py::App.prepare_proposal"
    path: str
    qual: str
    line: int
    end: int
    jitted: bool
    sources: list           # [kind, line, what]
    blocking: list          # [kind, line, what]
    impure: list            # [line, col, msg]
    locks: list             # resolved at link time
    # effect facts (fragment v4; consumed by effects.py)
    cls: str | None = None      # enclosing class name (lock identity)
    xfer: list = dataclasses.field(default_factory=list)
    frames: list = dataclasses.field(default_factory=list)
    guarded: list = dataclasses.field(default_factory=list)
    raises_: list = dataclasses.field(default_factory=list)
    shielded: list = dataclasses.field(default_factory=list)


class Program:
    """The linked whole-package call graph (module docstring, stage 2)."""

    def __init__(self, fragments: dict[str, dict]):
        self.fragments = fragments
        self.nodes: dict[str, Node] = {}
        self.edges: dict[str, list[tuple[str, int]]] = {}
        # module dotted path -> file path
        self._mods: dict[str, str] = {}
        # (path, class) tables
        self._classes: dict[tuple[str, str], dict] = {}
        # method name -> [node ids] (the attr-fallback index)
        self._by_method: dict[str, list[str]] = {}
        # file path -> does it import jax (fragment v4 flag)
        self.imports_jax: dict[str, bool] = {}
        self._link()

    # -- def tables ------------------------------------------------------

    def _module_of(self, path: str) -> str:
        mod = path[:-3] if path.endswith(".py") else path
        if mod.endswith("/__init__"):
            mod = mod[: -len("/__init__")]
        dotted = mod.replace("/", ".")
        return f"{PACKAGE}.{dotted}" if dotted else PACKAGE

    def _link(self) -> None:
        for path, frag in self.fragments.items():
            self._mods[self._module_of(path)] = path
            self.imports_jax[path] = bool(frag.get("imports_jax"))
            for cname, cinfo in frag.get("classes", {}).items():
                self._classes[(path, cname)] = cinfo
            for qual, info in frag.get("functions", {}).items():
                nid = f"{path}::{qual}"
                self.nodes[nid] = Node(
                    id=nid, path=path, qual=qual,
                    line=info["line"], end=info["end"],
                    jitted=bool(info.get("jitted")),
                    sources=info.get("sources", []),
                    blocking=info.get("blocking", []),
                    impure=info.get("impure", []),
                    locks=[],
                    cls=info.get("class"),
                    xfer=info.get("xfer", []),
                    frames=info.get("frames", []),
                    guarded=info.get("guarded", []),
                    raises_=info.get("raises", []),
                    shielded=info.get("shielded", []),
                )
                parts = qual.split(".")
                if len(parts) == 2:  # Class.method — the only shape
                    # reachable through attribute dispatch; indexing
                    # module-level functions here would let every
                    # ``lax.scan``-style external call alias a package
                    # function of the same name
                    self._by_method.setdefault(parts[1], []).append(nid)
        for path, frag in self.fragments.items():
            for qual, info in frag.get("functions", {}).items():
                nid = f"{path}::{qual}"
                out: list[tuple[str, int]] = []
                for desc in info.get("calls", []):
                    out.extend(self._resolve(path, qual, info, desc))
                for desc in info.get("refs", []):
                    kind, name, line = desc
                    out.extend(self._resolve(
                        path, qual, info, [kind, name, line],
                        ref=True))
                # distinct (target, line) pairs: the effect system
                # needs EVERY call line (a second call to the same
                # helper outside a lock frame is a different fact)
                seen = set()
                uniq = []
                for tgt, line in out:
                    if (tgt, line) not in seen:
                        seen.add((tgt, line))
                        uniq.append((tgt, line))
                self.edges[nid] = uniq
                node = self.nodes[nid]
                for lk in info.get("locks", []):
                    callees: list[tuple[str, int]] = []
                    for desc in lk.get("calls", []):
                        callees.extend(
                            self._resolve(path, qual, info, desc))
                    node.locks.append({
                        "lock": lk["lock"], "line": lk["line"],
                        "callees": callees,
                        "blocking": lk.get("blocking", []),
                    })

    # -- resolution ------------------------------------------------------

    def _resolve_dotted(self, name: str, line: int) -> list:
        """celestia_app_tpu.<...>.<sym>[.<sym2>] -> node ids."""
        parts = name.split(".")
        for cut in range(len(parts) - 1, 0, -1):
            mod = ".".join(parts[:cut])
            path = self._mods.get(mod)
            if path is None:
                continue
            rest = parts[cut:]
            if not rest:
                return []
            qual = ".".join(rest)
            nid = f"{path}::{qual}"
            if nid in self.nodes:
                return [(nid, line)]
            if (path, rest[0]) in self._classes:
                if len(rest) >= 2:
                    # ClassRef.method through an import of the class
                    return self._method(path, rest[0], rest[1], line)
                # a bare class: edge its __init__ (construction runs it)
                return self._class_init(path, rest[0], line)
            return []
        return []

    def _class_init(self, path: str, cname: str, line: int) -> list:
        hit = self._method(path, cname, "__init__", line)
        return hit

    def _method(self, path: str, cname: str, mname: str,
                line: int) -> list:
        """Method lookup with a linear package-bases walk."""
        seen: set[tuple[str, str]] = set()
        stack = [(path, cname)]
        while stack:
            p, c = stack.pop()
            if (p, c) in seen:
                continue
            seen.add((p, c))
            nid = f"{p}::{c}.{mname}"
            if nid in self.nodes:
                return [(nid, line)]
            cinfo = self._classes.get((p, c))
            if cinfo is None:
                continue
            for base in cinfo.get("bases", []):
                if base.startswith(PACKAGE + "."):
                    bp = base.split(".")
                    for cut in range(len(bp) - 1, 0, -1):
                        bpath = self._mods.get(".".join(bp[:cut]))
                        if bpath is not None and cut == len(bp) - 1:
                            stack.append((bpath, bp[-1]))
                            break
                elif "." not in base and (p, base) in self._classes:
                    stack.append((p, base))
        return []

    def _fallback(self, mname: str, line: int) -> list:
        if mname in FALLBACK_SKIP or mname.startswith("__"):
            return []
        # layers that sit ABOVE the library (operator tooling, the
        # client SDK, the HTTP/gRPC adapters, test harness twins) are
        # never dynamic-dispatch TARGETS of library code — they call
        # down, not vice versa; linking into them invents
        # relayer/load-harness/duck-type-twin paths
        return [(nid, line) for nid in self._by_method.get(mname, [])
                if not nid.startswith(_FALLBACK_TARGET_EXCLUDE)]

    def _resolve(self, path: str, qual: str, info: dict,
                 desc: list, ref: bool = False) -> list:
        kind = desc[0]
        line = desc[-1]
        if kind == "dotted":
            return self._resolve_dotted(desc[1], line)
        if kind == "local":
            name = desc[1]
            nid = f"{path}::{name}"
            if nid in self.nodes:
                return [(nid, line)]
            if (path, name) in self._classes:
                return self._class_init(path, name, line)
            return []
        if kind == "closure":
            nid = f"{path}::{desc[1]}"
            return [(nid, line)] if nid in self.nodes else []
        if kind == "self":
            cls = info.get("class")
            if cls is not None:
                hit = self._method(path, cls, desc[1], line)
                if hit:
                    return hit
            if ref:
                # a bare ``self.x`` reference is almost always a data
                # attribute read — name-fallback here would link every
                # ``self.height`` to every ``height()`` in the package
                return []
            return self._fallback(desc[1], line)
        if kind == "selfattr":
            cls = info.get("class")
            attrname, mname = desc[1], desc[2]
            if cls is not None:
                cinfo = self._classes.get((path, cls), {})
                tname = cinfo.get("attr_types", {}).get(attrname)
                if tname:
                    if tname.startswith(PACKAGE + "."):
                        hit = self._resolve_dotted(
                            f"{tname}.{mname}", line)
                        if hit:
                            return hit
                    elif (path, tname) in self._classes:
                        hit = self._method(path, tname, mname, line)
                        if hit:
                            return hit
            return self._fallback(mname, line)
        if kind == "attr":
            return self._fallback(desc[1], line)
        return []

    # -- traversal -------------------------------------------------------

    def resolve_entry(self, entry: str) -> str | None:
        """``path::symbol`` config entry -> node id (exact qualname, or
        unique suffix match so ``App.commit`` finds
        ``chain/app.py::App.commit``)."""
        if entry in self.nodes:
            return entry
        path, _, sym = entry.partition("::")
        if not sym:
            return None
        cands = [nid for nid in self.nodes
                 if self.nodes[nid].path == path
                 and (self.nodes[nid].qual == sym
                      or self.nodes[nid].qual.endswith("." + sym))]
        if len(cands) == 1:
            return cands[0]
        return None

    def reachable(self, roots: list[str],
                  stop) -> tuple[set[str], dict[str, tuple[str, str]]]:
        """BFS over edges from `roots` (node ids). `stop(node)` True
        halts traversal INTO that node (it is neither visited nor
        expanded). Returns (visited, parents) where parents maps node ->
        (parent node, root) for shortest-path reconstruction."""
        visited: set[str] = set()
        parents: dict[str, tuple[str, str]] = {}
        queue: list[tuple[str, str]] = []
        for r in roots:
            if r in self.nodes and not stop(self.nodes[r]):
                if r not in visited:
                    visited.add(r)
                    parents[r] = (None, r)  # type: ignore[arg-type]
                    queue.append((r, r))
        i = 0
        while i < len(queue):
            nid, root = queue[i]
            i += 1
            for tgt, _line in self.edges.get(nid, []):
                if tgt in visited:
                    continue
                node = self.nodes.get(tgt)
                if node is None or stop(node):
                    continue
                visited.add(tgt)
                parents[tgt] = (nid, root)
                queue.append((tgt, root))
        return visited, parents

    def call_path(self, parents: dict, nid: str) -> list[str]:
        """Root-first chain of node ids ending at `nid`."""
        chain = [nid]
        cur = nid
        while True:
            ent = parents.get(cur)
            if ent is None or ent[0] is None:
                break
            cur = ent[0]
            chain.append(cur)
        chain.reverse()
        return chain
