"""Text and JSON reporters for analysis runs.

The JSON schema (normative — docs/FORMATS.md §11):

    {
      "version": 3,
      "root": "<analyzed directory>",
      "config": "<analyze.toml path or null>",
      "summary": {
        "files_scanned": N, "rules_run": [...],
        "errors": N, "warnings": N, "waived": N, "wall_s": F,
        "cache_hits": N, "cache_misses": N
      },
      "violations": [
        {"rule": str, "severity": "error"|"warning", "path": str,
         "line": int, "col": int, "message": str,
         "waived": bool, "waiver_reason": str|null,
         "call_path": ["path::qualname", ...],
         "effect": {...}|null}, ...
      ]
    }

``call_path`` is the root→sink chain of call-graph node ids for the
interprocedural rules (det-reach, scope-drift, blocking-under-lock,
transitive jit-purity, xfer-reach, lock-order, guarded-by-flow) and
``[]`` for per-file rules. ``effect`` (v3) is the effect-system
rules' structured payload — xfer-reach: ``{kind, what, sink, root}``;
lock-order: ``{cycle, ab: {line, chain}, ba: {line, chain}}`` (both
acquisition paths); guarded-by-flow: ``{lock, attr, chain}`` — and
``null`` for every other rule.
"""

from __future__ import annotations

import json

from celestia_app_tpu.tools.analyze.engine import Report

JSON_VERSION = 3


def to_json(report: Report) -> dict:
    return {
        "version": JSON_VERSION,
        "root": report.root,
        "config": report.config_path,
        "summary": {
            "files_scanned": report.files_scanned,
            "rules_run": list(report.rules_run),
            "errors": len(report.errors),
            "warnings": len(report.warnings),
            "waived": len(report.waived),
            "wall_s": round(report.wall_s, 4),
            "cache_hits": report.cache_hits,
            "cache_misses": report.cache_misses,
        },
        "violations": [
            {
                "rule": v.rule, "severity": v.severity, "path": v.path,
                "line": v.line, "col": v.col, "message": v.message,
                "waived": v.waived, "waiver_reason": v.waiver_reason,
                "call_path": list(v.call_path or ()),
                "effect": v.effect,
            }
            for v in report.violations
        ],
    }


def to_json_text(report: Report) -> str:
    return json.dumps(to_json(report), indent=2, sort_keys=False)


def to_text(report: Report, verbose: bool = False) -> str:
    lines = []
    for v in report.violations:
        if v.waived and not verbose:
            continue
        lines.append(str(v))
    lines.append(
        f"analyze: {report.files_scanned} files, "
        f"{len(report.rules_run)} rules, "
        f"{len(report.errors)} errors, {len(report.warnings)} warnings, "
        f"{len(report.waived)} waived ({report.wall_s:.2f}s)"
    )
    return "\n".join(lines)
