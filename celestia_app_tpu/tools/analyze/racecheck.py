"""Runtime lock-order detector (``CELESTIA_RACE=1``).

Static analysis proves guarded fields are touched under their lock;
it cannot prove two locks are always taken in the same ORDER. This
module wraps ``threading.Lock``/``threading.RLock`` so every lock
created after ``install()`` records, per thread, which locks were held
when it was acquired. Locks are identified by their **creation site**
(``file:line``) so all instances created at one site form one class —
two ``CATPool`` objects locked from different threads do not count as
an inversion against each other (same-site edges are skipped), but
``reactor.py:157 -> telemetry.py:292`` observed alongside
``telemetry.py:292 -> reactor.py:157`` is a real ABBA deadlock waiting
for the right interleaving, and is recorded as a violation.

Activation: ``celestia_app_tpu/__init__`` calls ``install()`` when
``CELESTIA_RACE=1`` is in the environment, so chaos/stress subprocesses
get coverage of every lock in the package from the first import. The
chaos and stress tier-1 tests run under the flag and assert
``violations() == []`` at teardown.

**Contention profiling (the boundary observatory).** The same wrapper
is the process's only chokepoint that sees every acquire, so it doubles
as the lock-contention profiler (``set_profiling(True)``, env
``CELESTIA_LOCKPROF=1``). The hot path is kept nearly free: every
outermost acquire first tries a non-blocking acquire — success means
zero wait, and only two plain dict/float updates under the module's raw
state lock happen (no telemetry, no clock beyond the hold stamp).
Only a CONTENDED acquire (the try failed) measures its wait and records
it into the per-creation-site histogram ``lock.wait{site=…}`` — so the
p50/p99 in /metrics are quantiles of the waits that actually blocked,
which is the number contention triage needs. Hold times and totals
aggregate locally per site and are published at scrape time by a
registered collector as gauges: ``lock.acquires{site=…}``,
``lock.contended{site=…}``, ``lock.hold_s{site=…}`` (last) and
``lock.hold_max_s{site=…}``. Profiling and ABBA order-tracking are
independent gates (``set_order_tracking``): a bench can profile
contention without paying the frame-walking order bookkeeping, and
vice versa. Recording routes through utils/telemetry, whose registry
lock is itself tracked when installed early — a thread-local
``in_prof`` flag breaks that recursion (the registry's own lock is
never profiled).

This module must stay dependency-free at import (it is imported from
the package root before anything else); telemetry is imported lazily,
on the first profiled acquire.
"""

from __future__ import annotations

import os
import re
import threading
import time

_orig_lock = threading.Lock
_orig_rlock = threading.RLock

# internal state is protected by a RAW (untracked) lock — the detector
# must never trace itself
_state_lock = _orig_lock()
_edges: dict[tuple[str, str], dict] = {}   # (site_a, site_b) -> evidence
_violations: list[dict] = []
_installed = False
_tls = threading.local()

# independent gates over the shared wrapper: ABBA order bookkeeping
# (the original racecheck) vs wait/hold telemetry (the observatory)
_track_order = True
_profile = False
_telemetry = None  # lazily-imported utils.telemetry module


def _tele():
    global _telemetry
    if _telemetry is None:
        from celestia_app_tpu.utils import telemetry

        _telemetry = telemetry
    return _telemetry


def set_profiling(value: bool) -> None:
    """Gate the wait/hold telemetry on tracked locks (affects existing
    wrappers immediately; pair with `install()` so locks ARE wrapped).
    Enabling also registers the scrape-time collector that publishes
    the locally-aggregated per-site stats as gauges."""
    global _profile
    _profile = bool(value)
    if _profile:
        try:
            _tele().register_collector(_publish_lock_stats)
        except Exception:
            pass  # headless installs without the registry still profile


def profiling() -> bool:
    return _profile


def set_order_tracking(value: bool) -> None:
    """Gate the ABBA order bookkeeping (frame walk per outermost
    acquire) — profiling-only installs turn it off."""
    global _track_order
    _track_order = bool(value)


def _record_wait(site: str, wait_s: float) -> None:
    # in_prof breaks recursion: telemetry's own registry lock is a
    # tracked lock when installed early, and recording through it must
    # not re-enter the profiler
    _tls.in_prof = True
    try:
        _tele().observe("lock.wait", wait_s, labels={"site": site})
    except Exception:
        pass  # profiling must never take down the locked path
    finally:
        _tls.in_prof = False


# per-site local aggregates, published only at scrape time — the hot
# path never touches the telemetry registry for these.
# site -> [acquires, contended, hold_last_s, hold_max_s]
_prof_stats: dict[str, list] = {}  # guarded-by: _state_lock


def _prof_stat(site: str) -> list:
    st = _prof_stats.get(site)
    if st is None:
        st = _prof_stats[site] = [0, 0, 0.0, 0.0]
    return st


def _publish_lock_stats() -> None:
    """Telemetry collector: fold the local per-site aggregates into the
    registry as gauges, once per scrape (not once per acquire)."""
    _tls.in_prof = True
    try:
        tele = _tele()
        with _state_lock:
            snap = {site: list(st) for site, st in _prof_stats.items()}
        for site, (acq, cont, last, mx) in snap.items():
            labels = {"site": site}
            tele.gauge("lock.acquires", acq, labels=labels)
            tele.gauge("lock.contended", cont, labels=labels)
            tele.gauge("lock.hold_s", last, labels=labels)
            tele.gauge("lock.hold_max_s", mx, labels=labels)
    except Exception:
        pass  # a broken scrape must never take down the locked path
    finally:
        _tls.in_prof = False


def prof_stats() -> dict:
    """{site: {"acquires", "contended", "hold_last_s", "hold_max_s"}} —
    the local aggregates, for tests and in-process consumers."""
    with _state_lock:
        return {site: {"acquires": st[0], "contended": st[1],
                       "hold_last_s": st[2], "hold_max_s": st[3]}
                for site, st in _prof_stats.items()}


def _site(depth_hint: int = 2) -> str:
    """file:line of the frame that called Lock()/RLock()."""
    import sys

    f = sys._getframe(depth_hint)
    # skip frames inside this module (e.g. the factory shims)
    while f is not None and f.f_globals.get("__name__") == __name__:
        f = f.f_back
    if f is None:
        return "<unknown>"
    path = f.f_code.co_filename
    parts = path.replace(os.sep, "/").rsplit("/", 3)
    return f"{'/'.join(parts[-2:])}:{f.f_lineno}"


def _held() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def _note_acquire(lock: "_TrackedLock") -> None:
    stack = _held()
    # get_ident, NOT current_thread(): the latter allocates a
    # _DummyThread during thread bootstrap whose Event goes through the
    # patched locks and recurses straight back here
    me = f"tid={threading.get_ident()}"
    # where THIS acquire happened (not where the lock was created) —
    # the triage aid ISSUE 12 asks for: the report carries both the
    # creation sites and each thread's acquisition stack
    acq_site = _site(1)
    my_stack = [f"{h._race_site}@{s}" for h, s in stack]
    my_stack.append(f"{lock._race_site}@{acq_site}")
    with _state_lock:
        for held, _held_at in stack:
            a, b = held._race_site, lock._race_site
            if a == b:
                continue  # same creation site: one lock class, no order
            if (a, b) not in _edges:
                _edges[(a, b)] = {"thread": me, "stack": my_stack}
            rev = _edges.get((b, a))
            if rev is not None and not _already_reported(a, b):
                fwd_stack = " < ".join(rev.get("stack", []))
                rev_stack = " < ".join(my_stack)
                waiver = _waiver_reason_locked(a, b)
                if waiver is None:
                    import sys

                    msg = (
                        f"RACECHECK: lock-order inversion: {b} -> {a} "
                        f"(thread {rev['thread']}; acquired {fwd_stack})"
                        f" vs {a} -> {b} (thread {me}; acquired "
                        f"{rev_stack}) | locks created at A={a}, B={b} "
                        "(site@site = lock-creation@acquisition)"
                    )
                    try:
                        # one greppable stderr line — chaos subprocess
                        # logs are asserted clean of it
                        # (tests/test_chaos.py)
                        sys.stderr.write(msg + "\n")
                    except Exception:
                        pass
                _violations.append({
                    "first": b, "then": a,
                    "thread_forward": rev["thread"],
                    "stack_forward": list(rev.get("stack", [])),
                    "first_rev": a, "then_rev": b,
                    "thread_reverse": me,
                    "stack_reverse": list(my_stack),
                    "waived": waiver is not None,
                    "waiver_reason": waiver,
                    "message": (
                        f"lock-order inversion: {b} -> {a} "
                        f"(thread {rev['thread']}; acquired {fwd_stack})"
                        f" vs {a} -> {b} (thread {me}; acquired "
                        f"{rev_stack})"
                    ),
                })
    stack.append((lock, acq_site))


def _already_reported(a: str, b: str) -> bool:
    return any(
        {v["first"], v["then"]} == {a, b} for v in _violations
    )


def _note_release(lock: "_TrackedLock") -> None:
    stack = _held()
    for i in range(len(stack) - 1, -1, -1):
        if stack[i][0] is lock:
            del stack[i]
            break


class _TrackedLock:
    """Wrapper over a real Lock/RLock that records acquisition order.
    Implements the Condition-variable integration surface
    (``_release_save``/``_acquire_restore``/``_is_owned``) so wrapped
    locks keep working inside ``threading.Condition``/``Event``."""

    def __init__(self, inner, site: str, reentrant: bool):
        self._race_inner = inner
        self._race_site = site
        self._race_reentrant = reentrant
        self._race_depth_tls = threading.local()

    # -- depth (RLock reentrancy must not re-record edges) ---------------

    def _depth(self) -> int:
        return getattr(self._race_depth_tls, "n", 0)

    def _set_depth(self, n: int) -> None:
        self._race_depth_tls.n = n

    # -- the lock protocol ----------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1):
        # profile only the OUTERMOST acquire (RLock reentry never
        # blocks), and never while recording a previous sample
        prof = (_profile and self._depth() == 0
                and not getattr(_tls, "in_prof", False))
        if prof:
            # fast path: an uncontended acquire succeeds the
            # non-blocking try — zero wait, no clock pair, no telemetry
            ok = self._race_inner.acquire(False)
            contended = not ok
            if contended and blocking:
                t0 = time.perf_counter()
                ok = self._race_inner.acquire(blocking, timeout)
                if ok:
                    _record_wait(self._race_site,
                                 time.perf_counter() - t0)
        else:
            ok = self._race_inner.acquire(blocking, timeout)
        if ok:
            d = self._depth()
            if d == 0:
                if prof:
                    self._race_depth_tls.t_acq = time.perf_counter()
                    self._race_depth_tls.contended = contended
                if _track_order:
                    _note_acquire(self)
            self._set_depth(d + 1)
        return ok

    def _flush_hold(self, t_acq: float) -> None:
        """Fold one acquire/release pair into the local per-site stats
        (one raw-lock take per pair; telemetry sees nothing here)."""
        hold_s = time.perf_counter() - t_acq
        contended = getattr(self._race_depth_tls, "contended", False)
        self._race_depth_tls.contended = False
        with _state_lock:
            st = _prof_stat(self._race_site)
            st[0] += 1
            if contended:
                st[1] += 1
            st[2] = hold_s
            if hold_s > st[3]:
                st[3] = hold_s

    def release(self) -> None:
        d = self._depth()
        t_acq = getattr(self._race_depth_tls, "t_acq", None) \
            if d <= 1 else None
        self._race_inner.release()
        self._set_depth(max(0, d - 1))
        if d <= 1:
            if _track_order:
                _note_release(self)
            if t_acq is not None:
                self._race_depth_tls.t_acq = None
                self._flush_hold(t_acq)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        fn = getattr(self._race_inner, "locked", None)
        if fn is not None:
            return fn()
        # RLock grew .locked() only in 3.13; emulate: owned by me, or
        # a non-blocking probe fails
        if self._is_owned():
            return True
        if self._race_inner.acquire(False):
            self._race_inner.release()
            return False
        return True

    def __repr__(self) -> str:
        return f"<TrackedLock {self._race_site} {self._race_inner!r}>"

    # -- Condition integration -------------------------------------------

    def _release_save(self):
        inner_save = getattr(self._race_inner, "_release_save", None)
        d = self._depth()
        # a cond.wait hands the lock back: close the hold interval here
        # (the wait itself is deliberately NOT recorded as lock.wait —
        # waiting on a condition is not mutex contention)
        t_acq = getattr(self._race_depth_tls, "t_acq", None)
        if t_acq is not None:
            self._race_depth_tls.t_acq = None
            self._flush_hold(t_acq)
        state = inner_save() if inner_save else self._race_inner.release()
        self._set_depth(0)
        if _track_order:
            _note_release(self)
        return (state, d)

    def _acquire_restore(self, saved) -> None:
        state, d = saved
        inner_restore = getattr(self._race_inner, "_acquire_restore",
                                None)
        if inner_restore:
            inner_restore(state)
        else:
            self._race_inner.acquire()
        if _profile and not getattr(_tls, "in_prof", False):
            # hold resumes when cond.wait reacquires
            self._race_depth_tls.t_acq = time.perf_counter()
        if d > 0 and _track_order:
            _note_acquire(self)
        self._set_depth(d)

    def _is_owned(self) -> bool:
        inner_owned = getattr(self._race_inner, "_is_owned", None)
        if inner_owned:
            return inner_owned()
        if self._race_inner.acquire(False):
            self._race_inner.release()
            return False
        return True

    def _at_fork_reinit(self) -> None:
        reinit = getattr(self._race_inner, "_at_fork_reinit", None)
        if reinit:
            reinit()
        self._set_depth(0)


def _make_lock():
    return _TrackedLock(_orig_lock(), _site(), reentrant=False)


def _make_rlock():
    return _TrackedLock(_orig_rlock(), _site(), reentrant=True)


def install() -> bool:
    """Patch ``threading.Lock``/``RLock`` with tracking factories.
    Idempotent; affects locks created AFTER the call, which is every
    lock in the package when installed from ``celestia_app_tpu/
    __init__`` (the env hook). Returns True when newly installed."""
    global _installed
    if _installed:
        return False
    _installed = True
    threading.Lock = _make_lock
    threading.RLock = _make_rlock
    return True


def uninstall() -> None:
    global _installed
    threading.Lock = _orig_lock
    threading.RLock = _orig_rlock
    _installed = False


def installed() -> bool:
    return _installed


def enabled_by_env() -> bool:
    return os.environ.get("CELESTIA_RACE", "").strip() == "1"


def profile_enabled_by_env() -> bool:
    return os.environ.get("CELESTIA_LOCKPROF", "").strip() == "1"


# -- the shared inversion ledger --------------------------------------------
# The static lock-order rule (tools/analyze/effects.py) and this
# runtime detector must never silently disagree about the set of KNOWN
# inversions: both read the same [rules.lock-order] ledger entries from
# analyze.toml ("<lockA> <-> <lockB> : <reason>", lock ids
# "<pkg-relative-path>::<attr>"). Runtime lock classes are creation
# sites (file:line), so matching is by the FILE pair the two lock
# classes were created in — the finest unit both detectors share.

_LEDGER_RE = re.compile(
    r"^\s*(?P<a>\S+)\s*<->\s*(?P<b>\S+)\s*:\s*(?P<reason>.+?)\s*$")

_ledger_raw: list[str] = []                        # guarded-by: _state_lock
_ledger: list[tuple[frozenset, str]] = []          # guarded-by: _state_lock


def set_waiver_ledger(entries) -> int:
    """Install the known-inversion ledger (the [rules.lock-order]
    ``ledger`` entries from analyze.toml, verbatim). Raises ValueError
    on an unparseable entry — a typo'd waiver that silently matches
    nothing is exactly the disagreement this surface exists to
    prevent. Returns the number of entries installed."""
    parsed: list[tuple[frozenset, str]] = []
    for raw in entries:
        m = _LEDGER_RE.match(str(raw))
        if m is None:
            raise ValueError(
                f"unparseable lock-order ledger entry {raw!r} — format "
                "is '<lockA> <-> <lockB> : <reason>'")
        fa = m.group("a").split("::")[0]
        fb = m.group("b").split("::")[0]
        parsed.append((frozenset((fa, fb)), m.group("reason")))
    with _state_lock:
        _ledger_raw[:] = [str(r) for r in entries]
        _ledger[:] = parsed
    return len(parsed)


def load_waiver_ledger_from_config(config_path: str | None = None) -> int:
    """Read the [rules.lock-order] ledger out of analyze.toml (the
    committed one by default) and install it — the one call that keeps
    the runtime detector's waiver set equal to the static rule's."""
    from celestia_app_tpu.tools.analyze import (
        default_config_path,
        load_config,
    )

    if config_path is None:
        config_path = default_config_path()
    cfg = load_config(config_path)
    return set_waiver_ledger(
        cfg.rule("lock-order").options.get("ledger", []))


def waiver_ledger() -> list[str]:
    """The installed ledger entries, verbatim."""
    with _state_lock:
        return list(_ledger_raw)


def _site_file(site: str) -> str:
    f = site.rsplit(":", 1)[0]
    if f.startswith("celestia_app_tpu/"):
        f = f[len("celestia_app_tpu/"):]
    return f


def _waiver_reason_locked(site_a: str, site_b: str) -> str | None:
    """Ledger reason covering the (site_a, site_b) inversion, or None.
    Caller holds _state_lock."""
    pair = frozenset((_site_file(site_a), _site_file(site_b)))
    for files, reason in _ledger:
        if files == pair:
            return reason
    return None


def violations(include_waived: bool = False) -> list[dict]:
    with _state_lock:
        return [dict(v) for v in _violations
                if include_waived or not v.get("waived")]


def waived_violations() -> list[dict]:
    """Inversions that occurred but are covered by the ledger."""
    with _state_lock:
        return [dict(v) for v in _violations if v.get("waived")]


def edges() -> list[tuple[str, str]]:
    with _state_lock:
        return sorted(_edges)


def reset() -> None:
    with _state_lock:
        _edges.clear()
        _violations.clear()
        _prof_stats.clear()
