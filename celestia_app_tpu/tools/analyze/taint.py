"""Interprocedural taint + reachability rules over the call graph.

PR 5's determinism rules are *intraprocedural*: they flag a wall-clock
read only when it sits lexically inside a file someone remembered to
add to an ``analyze.toml`` include list. These rules close the two
holes that leaves:

- ``det-reach``  — taint sources (wall-clock, ambient RNG,
  ``os.environ`` reads, unordered dict/set iteration feeding a hash)
  **reachable from the consensus roots** are errors, wherever they
  live. Roots are configured (``[rules.det-reach] roots = [...]``) as
  ``path::symbol`` entries — the ABCI surface, ``ValidatorNode.apply``,
  every codec's encode/verify/repair/fraud hooks, the sync plane's
  manifest/chunk digest writers, the pack doc builders. ``allow``
  entries are traversal **barriers**: the observability and fault
  planes are deliberately non-consensus (their outputs never feed a
  hash) and are not descended into. A configured root that no longer
  resolves is itself an error — the root ledger cannot rot either.

- ``scope-drift`` — the consensus-reachable function set computed
  above must be *covered* by the hand-maintained include lists of the
  checked det-* rules (``check = [...]``). A reachable function missing
  from a list is an error naming the file, the rule, and the call path
  that makes it consensus — so forgetting to append a new file to
  ``analyze.toml`` (the PR 7–11 ritual) is now impossible.
  ``analyze --scopes`` prints the computed lists for auditing.

- ``blocking-under-lock`` — network calls, ``fsync``, ``sleep``, or
  potential jit compiles reachable while a ``with self.<lock>`` frame
  is held. The static complement of racecheck's runtime ABBA detector:
  racecheck needs the bad interleaving to strike; this rule finds the
  stall-under-lock before it ships.

``jit-purity`` additionally gains a transitive closure pass (in
``rules_effects``, built on this module): helpers *called from* jitted
program bodies are checked, not just the jitted function's own body.

All three report the full root→sink call path in text and ``--json``
(the ``call_path`` field, FORMATS §11).
"""

from __future__ import annotations

from celestia_app_tpu.tools.analyze.engine import (
    ProgramRule,
    Violation,
    register,
    _in_scope,
)
from celestia_app_tpu.tools.analyze.config import AnalyzeConfig, RuleConfig

_SOURCE_KINDS = {
    "wallclock": "wall-clock read",
    "rng": "nondeterministic rng",
    "env": "environment read",
    "hash-iter": "unordered iteration feeding a hash",
}

_BLOCK_KINDS = {
    "sleep": "sleep",
    "fsync": "fsync",
    "net": "network call",
    "jit-compile": "potential jit compile",
    "subprocess": "subprocess",
}


def entry_covers(entry: str, path: str, qual: str) -> bool:
    """Does a ``path[::symbol]`` config entry cover function `qual` of
    file `path`? Same semantics as the engine's include scoping: prefix
    path match, and a ``::symbol`` suffix matches when the symbol is a
    component of the qualified name (``apply`` covers
    ``ValidatorNode.apply`` and its closures)."""
    base, _, sym = entry.partition("::")
    if not (path == base or path.startswith(base)):
        return False
    if not sym:
        return True
    return sym in qual.split(".")


def _barrier(allow: list[str]):
    def stop(node) -> bool:
        return any(entry_covers(e, node.path, node.qual) for e in allow)
    return stop


def _resolve_roots(program, rcfg: RuleConfig, rule_id: str = "det-reach"):
    """(resolved node ids, [missing-entry violations])."""
    roots: list[str] = []
    missing: list[Violation] = []
    for entry in rcfg.options.get("roots", []):
        nid = program.resolve_entry(str(entry))
        if nid is None:
            missing.append(Violation(
                rule=rule_id, severity="error",
                path=str(entry).split("::")[0], line=0, col=0,
                message=(f"{rule_id} root {entry!r} not found in the "
                         "call graph (stale analyze.toml entry, or the "
                         "function moved — the root ledger must track "
                         "the code)"),
            ))
        else:
            roots.append(nid)
    return roots, missing


def consensus_reachability(program, config: AnalyzeConfig):
    """The shared computation: (visited, parents, roots, missing) for
    the det-reach roots/barriers in `config` — used by det-reach,
    scope-drift, and ``analyze --scopes``."""
    rcfg = config.rule("det-reach")
    roots, missing = _resolve_roots(program, rcfg)
    visited, parents = program.reachable(roots, _barrier(rcfg.allow))
    return visited, parents, roots, missing


@register
class DetReachRule(ProgramRule):
    id = "det-reach"
    help = ("taint sources (wall-clock, rng, os.environ, unordered "
            "iteration into hashes) reachable from the configured "
            "consensus roots fork the chain — reported with the full "
            "call path")

    def check_program(self, program, config: AnalyzeConfig,
                      rcfg: RuleConfig):
        if not rcfg.options.get("roots"):
            yield Violation(
                rule=self.id, severity="error", path="analyze.toml",
                line=0, col=0,
                message=("det-reach is enabled but [rules.det-reach] "
                         "configures no roots — an empty root set makes "
                         "the rule a silent no-op"),
            )
            return
        visited, parents, roots, missing = consensus_reachability(
            program, config)
        for v in missing:
            yield v
        # sorted: set iteration is hash-order and would make the report
        # differ across processes (PYTHONHASHSEED)
        for nid in sorted(visited):
            node = program.nodes[nid]
            chain = program.call_path(parents, nid)
            root_qual = program.nodes[chain[0]].qual
            for kind, line, what in node.sources:
                label = _SOURCE_KINDS.get(kind, kind)
                yield Violation(
                    rule=self.id, severity="error", path=node.path,
                    line=int(line), col=0,
                    message=(f"{label} ({what}) in {node.qual}() is "
                             f"reachable from consensus root "
                             f"{root_qual}() — every validator must "
                             "compute identical bytes on this path"),
                    call_path=chain,
                )


@register
class ScopeDriftRule(ProgramRule):
    id = "scope-drift"
    help = ("the computed consensus-reachable set must be covered by "
            "the checked rules' hand-written include lists in "
            "analyze.toml — a reachable file missing from a det-* list "
            "is a silent hole in the determinism gate")

    def check_program(self, program, config: AnalyzeConfig,
                      rcfg: RuleConfig):
        checked = [str(r) for r in rcfg.options.get("check", [])]
        if not checked:
            yield Violation(
                rule=self.id, severity="error", path="analyze.toml",
                line=0, col=0,
                message=("scope-drift is enabled but [rules.scope-drift]"
                         " configures no checked rules (check = [...])"),
            )
            return
        visited, parents, roots, _missing = consensus_reachability(
            program, config)
        for rid in checked:
            tcfg = config.rule(rid)
            entries = list(tcfg.include)
            if not entries:
                continue  # no include list = whole tree in scope
            covered_entries: set[str] = set()
            seen_files: set[str] = set()
            # sorted: the per-file representative (first uncovered
            # function) must not depend on set hash order
            for nid in sorted(visited):
                node = program.nodes[nid]
                hits = [e for e in entries
                        if entry_covers(e, node.path, node.qual)]
                covered_entries.update(hits)
                if hits:
                    continue
                if any(entry_covers(e, node.path, node.qual)
                       for e in tcfg.allow):
                    continue
                if any(entry_covers(e, node.path, node.qual)
                       for e in rcfg.allow):
                    continue
                if node.path in seen_files:
                    continue
                seen_files.add(node.path)
                chain = program.call_path(parents, nid)
                root_qual = program.nodes[chain[0]].qual
                yield Violation(
                    rule=self.id, severity="error", path=node.path,
                    line=node.line, col=0,
                    message=(f"{node.path} is consensus-reachable "
                             f"({node.qual}() via root {root_qual}()) "
                             f"but not covered by [rules.{rid}] "
                             "include/allow in analyze.toml — the "
                             "determinism scope has drifted from the "
                             "code"),
                    call_path=chain,
                )


@register
class BlockingUnderLockRule(ProgramRule):
    id = "blocking-under-lock"
    help = ("network calls, fsync, sleep, or potential jit compiles "
            "reachable while a 'with self.<lock>' frame is held stall "
            "every thread queued on that lock — hoist the slow work "
            "outside the critical section")

    def check_program(self, program, config: AnalyzeConfig,
                      rcfg: RuleConfig):
        stop = _barrier(rcfg.allow)
        reported: set[tuple] = set()
        for nid in program.nodes:
            holder = program.nodes[nid]
            if not holder.locks:
                continue
            if not _in_scope(holder.path, rcfg):
                continue
            for frame in holder.locks:
                lock, wline = frame["lock"], frame["line"]
                # blocking ops lexically inside the with body
                for kind, line, what in frame["blocking"]:
                    key = (holder.path, wline, kind, holder.path, line)
                    if key in reported:
                        continue
                    reported.add(key)
                    yield Violation(
                        rule=self.id, severity="error",
                        path=holder.path, line=wline, col=0,
                        message=(f"{_BLOCK_KINDS.get(kind, kind)} "
                                 f"({what}) at {holder.path}:{line} "
                                 f"inside 'with self.{lock}' "
                                 f"({holder.qual}()) blocks every "
                                 "thread queued on the lock"),
                        call_path=[nid],
                    )
                # ... and ops reachable through the calls made there
                callees = [t for t, _l in frame["callees"]]
                visited, parents = program.reachable(callees, stop)
                for tid in sorted(visited):
                    tnode = program.nodes[tid]
                    for kind, line, what in tnode.blocking:
                        key = (holder.path, wline, kind, tnode.path,
                               line)
                        if key in reported:
                            continue
                        reported.add(key)
                        chain = [nid] + program.call_path(parents, tid)
                        yield Violation(
                            rule=self.id, severity="error",
                            path=holder.path, line=wline, col=0,
                            message=(f"{_BLOCK_KINDS.get(kind, kind)} "
                                     f"({what}) at {tnode.path}:{line} "
                                     "is reachable while "
                                     f"'with self.{lock}' is held at "
                                     f"{holder.path}:{wline} "
                                     f"({holder.qual}())"),
                            call_path=chain,
                        )


# ---------------------------------------------------------------------------
# --scopes: the audit surface
# ---------------------------------------------------------------------------


def scopes_report(program, config: AnalyzeConfig) -> str:
    """Human-readable computed-scope audit for ``analyze --scopes``:
    the consensus-reachable set, and per checked rule the computed
    minimal include list, entries covering nothing reachable (deletion
    candidates), and reachable-but-uncovered files (the drift)."""
    rcfg = config.rule("det-reach")
    visited, parents, roots, missing = consensus_reachability(
        program, config)
    by_file: dict[str, list] = {}
    for nid in sorted(visited):
        node = program.nodes[nid]
        by_file.setdefault(node.path, []).append(node.qual)
    lines = [
        f"consensus-reachable: {len(visited)} functions in "
        f"{len(by_file)} files ({len(roots)} roots, "
        f"{len(rcfg.allow)} barrier entries)",
    ]
    for entry in missing:
        lines.append(f"  MISSING ROOT: {entry.message}")
    checked = [str(r) for r in
               config.rule("scope-drift").options.get("check", [])]
    sd_allow = config.rule("scope-drift").allow
    for rid in checked:
        tcfg = config.rule(rid)
        lines.append(f"\n[rules.{rid}] computed minimal include:")
        used: set[str] = set()
        for path in sorted(by_file):
            quals = by_file[path]
            hits = {e for e in tcfg.include
                    for q in quals if entry_covers(e, path, q)}
            used |= hits
            allowed = all(
                any(entry_covers(e, path, q) for e in
                    (list(tcfg.allow) + list(sd_allow) + list(rcfg.allow)))
                or any(entry_covers(e, path, q) for e in hits)
                for q in quals)
            mark = " " if (hits or allowed) else "!"
            lines.append(f"  {mark} {path}  "
                         f"({len(quals)} reachable: "
                         f"{', '.join(sorted(quals)[:4])}"
                         f"{', ...' if len(quals) > 4 else ''})")
        unused = [e for e in tcfg.include if e not in used]
        if unused:
            lines.append(f"  unused include entries (cover nothing "
                         f"reachable — deletion candidates): {unused}")
    lines.append(
        "\nlines marked '!' are reachable but uncovered (scope-drift "
        "errors); barrier files are not listed.")
    return "\n".join(lines)
