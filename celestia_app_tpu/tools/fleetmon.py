"""Fleet-wide SLO verdict engine: scrape every node, judge one verdict.

The observability plane produces numbers per node (/metrics,
/consensus/status, /das/availability); nothing so far judges the FLEET.
This tool closes that loop the way the reference's testnet tooling
asserts on scraped Prometheus state after an e2e run: scrape every node
of a devnet (or adapt an in-process sim registry), merge per metric
family, and evaluate a declarative SLO rule file into ONE deterministic
verdict JSON — counter pins (``edscache.host_crossings == 0``,
``commitment.recomputes == 0`` on warmed nodes), p99 latency budgets
(histogram buckets re-quantiled with the registry's own ladder), gauge
ceilings (open breakers), and status-document field checks.

Three layers, split so the verdict is reproducible:

- **Scrape adapters** (non-deterministic edge): ``scrape_fleet(urls)``
  pulls the Prometheus text exposition + status docs over HTTP and
  parses them back into metric families; ``registry_node(base=…)``
  builds the same shape straight from the in-process telemetry registry
  (``telemetry.export()``), optionally as a DELTA against a baseline
  export — the sim scenario op uses that so telemetry accumulated by
  earlier cells in the same process never leaks into a verdict.
- **The rule evaluator** (pure): ``evaluate(rules, fleet)`` is a
  deterministic function of its inputs — no clocks, no rounding noise
  (values round to 9 places), rule order preserved, node labels sorted.
  Two calls against the same fleet state produce byte-identical
  ``verdict_bytes``.
- **The CLI** (``fleetmon`` subcommand / ``python -m
  celestia_app_tpu.tools.fleetmon``): prints the verdict JSON and exits
  0 on pass, 2 on SLO violation — CI wires it after a devnet soak.

Rule file: JSON ``{"slo": [rule, …]}`` (a bare list also works). Each
rule (docs/FORMATS.md §22.1):

    {"name":   "no-unmediated-crossings",     # verdict key, required
     "source": "metrics",                     # metrics|status|availability
     "metric": "edscache.host_crossings",     # dotted registry name
     "kind":   "counter",                     # counter|gauge|p50|p95|p99|
                                              #   count|sum|max (timer kinds)
     "labels": {"site": "edscache.eds"},      # optional label selector
     "op": "==", "value": 0,                  # comparison
     "agg": "each"}                           # each|sum|max|min across nodes

``source: "status"`` / ``"availability"`` rules use ``"path"`` (a dotted
path into the scraped JSON document, e.g. ``"reactor.height"``) instead
of metric/kind. Absent counters/histograms evaluate as 0 — in this
registry absence means never incremented. An unreachable node fails
every ``each`` rule (observed ``null``), which is the right default for
an SLO: a node you cannot see is a node out of budget.
"""

from __future__ import annotations

import json
import re

from celestia_app_tpu.utils.telemetry import (
    BUCKET_BOUNDS,
    _quantile,
)

PREFIX = "celestia"
SCHEMA = "fleetmon/1"

_OPS = ("==", "!=", "<=", "<", ">=", ">")
_KINDS = ("counter", "gauge", "p50", "p95", "p99", "count", "sum", "max")
_AGGS = ("each", "sum", "max", "min")
_SOURCES = ("metrics", "status", "availability")

#: le label values in the exposition, in ladder order (telemetry formats
#: bounds with %.9g); index of a bucket line is looked up here
_LE_INDEX = {f"{b:.9g}": i for i, b in enumerate(BUCKET_BOUNDS)}
_LE_INDEX["+Inf"] = len(BUCKET_BOUNDS)

_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def _san(name: str) -> str:
    """The registry's family-name sanitizer (dots -> underscores): rule
    metric names and exposition family names meet in this space."""
    return "".join(ch if ch.isalnum() or ch == "_" else "_"
                   for ch in name)


def _label_str(labels: dict) -> str:
    return ",".join(f'{k}="{labels[k]}"' for k in sorted(labels))


def _parse_labels(inner: str) -> dict:
    return {m.group(1): m.group(2) for m in _LABEL_RE.finditer(inner)}


def _empty_node() -> dict:
    return {"counter": {}, "gauge": {}, "hist": {}}


def _hist_cell(fam: dict, label_str: str) -> dict:
    return fam.setdefault(label_str, {
        "buckets": [0] * (len(BUCKET_BOUNDS) + 1),
        "count": 0, "sum": 0.0, "max": 0.0,
    })


# ---------------------------------------------------------------------------
# scrape adapters
# ---------------------------------------------------------------------------

def parse_prometheus(text: str, prefix: str = PREFIX) -> dict:
    """Parse the registry's own text exposition back into metric
    families: {"counter"|"gauge": {family: {label_str: value}},
    "hist": {family: {label_str: {"buckets": per-bucket counts,
    "count": n, "sum": s, "max": m}}}} — family names sanitized
    (underscores), suffix/prefix stripped, cumulative bucket lines
    de-cumulated so quantiles recompute with the registry's ladder."""
    out = _empty_node()
    types: dict[str, str] = {}   # exposition metric name -> type
    p = prefix + "_"
    for line in text.splitlines():
        if line.startswith("# TYPE "):
            _h, _t, name, typ = line.split(None, 3)
            types[name] = typ
            continue
        if not line or line.startswith("#"):
            continue
        brace = line.find("{")
        if brace >= 0:
            close = line.rfind("}")
            name, inner = line[:brace], line[brace + 1:close]
            value = line[close + 1:].strip()
        else:
            name, _sp, value = line.partition(" ")
            inner = ""
        try:
            v = float(value)
        except ValueError:
            continue
        labels = _parse_labels(inner)
        # histogram member lines carry the base-name suffixes
        base, suffix = name, ""
        for s in ("_bucket", "_sum", "_count"):
            if name.endswith(s) and types.get(name[:-len(s)]) == "histogram":
                base, suffix = name[:-len(s)], s
                break
        typ = types.get(base)
        if typ == "histogram" and base.startswith(p) \
                and base.endswith("_seconds"):
            fam = base[len(p):-len("_seconds")]
            le = labels.pop("le", None)
            cell = _hist_cell(out["hist"].setdefault(fam, {}),
                              _label_str(labels))
            if suffix == "_bucket" and le in _LE_INDEX:
                # exposition is cumulative; store and de-cumulate below
                cell["buckets"][_LE_INDEX[le]] = int(v)
            elif suffix == "_sum":
                cell["sum"] = v
            elif suffix == "_count":
                cell["count"] = int(v)
        elif typ == "counter" and name.startswith(p) \
                and name.endswith("_total"):
            fam = name[len(p):-len("_total")]
            out["counter"].setdefault(fam, {})[_label_str(labels)] = v
        elif typ == "gauge" and name.startswith(p):
            gname = name[len(p):]
            if gname.endswith("_seconds_max"):
                # the per-timer max rides a separate gauge family; fold
                # it back into its histogram cell
                fam = gname[:-len("_seconds_max")]
                cell = _hist_cell(out["hist"].setdefault(fam, {}),
                                  _label_str(labels))
                cell["max"] = v
            else:
                out["gauge"].setdefault(gname, {})[_label_str(labels)] = v
    for fam in out["hist"].values():
        for cell in fam.values():
            cum, per = 0, []
            for c in cell["buckets"]:
                per.append(max(int(c) - cum, 0))
                cum = max(cum, int(c))
            cell["buckets"] = per
    return out


def registry_node(base: dict | None = None) -> dict:
    """Build one node's metric families straight from the in-process
    registry (`telemetry.export()`), bypassing HTTP. With `base` (a
    prior `telemetry.export()`), counters and histograms are the DELTA
    since that export — the sim scenario op pins rules against only the
    activity of ITS run. Gauges (and timer max) stay absolute: they are
    levels, not flows."""
    from celestia_app_tpu.utils import telemetry

    exp = telemetry.export()
    series = exp["series"]
    base = base or {"counters": {}, "gauges": {}, "timers": {}}

    def split(key: str) -> tuple[str, str]:
        if key in series:
            name, labels = series[key]
            return _san(name), _label_str(labels)
        return _san(key), ""

    out = _empty_node()
    for key, v in exp["counters"].items():
        fam, ls = split(key)
        d = v - base["counters"].get(key, 0)
        out["counter"].setdefault(fam, {})[ls] = float(d)
    for key, v in exp["gauges"].items():
        fam, ls = split(key)
        out["gauge"].setdefault(fam, {})[ls] = float(v)
    for key, t in exp["timers"].items():
        fam, ls = split(key)
        b0 = base["timers"].get(key)
        cell = _hist_cell(out["hist"].setdefault(fam, {}), ls)
        if b0 is None:
            cell["buckets"] = list(t["buckets"])
            cell["count"], cell["sum"] = t["count"], t["total_s"]
        else:
            cell["buckets"] = [a - b for a, b in
                               zip(t["buckets"], b0["buckets"])]
            cell["count"] = t["count"] - b0["count"]
            cell["sum"] = t["total_s"] - b0["total_s"]
        cell["max"] = t["max_s"]
    return out


def scrape_node(url: str, client=None, with_availability: bool = True) -> dict:
    """One node's fleet-state entry: parsed /metrics (required — an
    unparseable node yields metrics None and fails its rules), the
    status doc (/consensus/status, node /status fallback), and the
    /das/availability record at the status height (both optional)."""
    from celestia_app_tpu.net import transport

    client = client or transport.DEFAULT
    url = url.rstrip("/")
    node: dict = {"metrics": None, "status": None, "availability": None}
    try:
        body = client.get(url, "/metrics", raw=True)
        node["metrics"] = parse_prometheus(body.decode("utf-8", "replace"))
    except Exception as e:  # noqa: BLE001 — any scrape failure = node dark
        node["error"] = type(e).__name__
        return node
    for path in ("/consensus/status", "/status"):
        try:
            node["status"] = client.get(url, path)
            break
        except Exception:  # noqa: BLE001
            continue
    if with_availability and isinstance(node["status"], dict):
        h = node["status"].get("height")
        if isinstance(h, int) and h > 0:
            try:
                node["availability"] = client.get(
                    url, f"/das/availability?height={h}")
            except Exception:  # noqa: BLE001
                pass
    return node


def scrape_fleet(urls: list[str], client=None,
                 with_availability: bool = True) -> dict:
    """{"nodes": {url: node_state}} for every given node URL."""
    return {"nodes": {
        u.rstrip("/"): scrape_node(u, client=client,
                                   with_availability=with_availability)
        for u in urls
    }}


# ---------------------------------------------------------------------------
# the rule evaluator (pure, deterministic)
# ---------------------------------------------------------------------------

def normalize_rules(doc) -> list[dict]:
    """Validate + default-fill a rule file ({"slo": [...]} or a bare
    list). Raises ValueError on a malformed rule — a typo'd SLO file
    must fail loudly, not pass vacuously."""
    rules = doc.get("slo") if isinstance(doc, dict) else doc
    if not isinstance(rules, list) or not rules:
        raise ValueError("rule file needs a non-empty 'slo' rule list")
    out = []
    for i, r in enumerate(rules):
        if not isinstance(r, dict) or "name" not in r:
            raise ValueError(f"rule #{i}: not a dict with a 'name'")
        rule = {
            "name": str(r["name"]),
            "source": r.get("source", "metrics"),
            "op": r.get("op", "=="),
            "value": r.get("value", 0),
            "agg": r.get("agg", "each"),
        }
        if rule["source"] not in _SOURCES:
            raise ValueError(f"rule {rule['name']}: unknown source "
                             f"{rule['source']!r}")
        if rule["op"] not in _OPS:
            raise ValueError(f"rule {rule['name']}: unknown op "
                             f"{rule['op']!r}")
        if rule["agg"] not in _AGGS:
            raise ValueError(f"rule {rule['name']}: unknown agg "
                             f"{rule['agg']!r}")
        if not isinstance(rule["value"], (int, float)) \
                or isinstance(rule["value"], bool):
            raise ValueError(f"rule {rule['name']}: value must be a number")
        if rule["source"] == "metrics":
            if "metric" not in r:
                raise ValueError(f"rule {rule['name']}: metrics rules "
                                 "need 'metric'")
            rule["metric"] = str(r["metric"])
            rule["kind"] = r.get("kind", "counter")
            if rule["kind"] not in _KINDS:
                raise ValueError(f"rule {rule['name']}: unknown kind "
                                 f"{rule['kind']!r}")
            labels = r.get("labels")
            if labels is not None:
                if not isinstance(labels, dict):
                    raise ValueError(f"rule {rule['name']}: labels must "
                                     "be a dict")
                rule["labels"] = {str(k): str(v)
                                  for k, v in labels.items()}
        else:
            if "path" not in r:
                raise ValueError(f"rule {rule['name']}: {rule['source']} "
                                 "rules need 'path'")
            rule["path"] = str(r["path"])
        out.append(rule)
    return out


def _labels_match(label_str: str, sel: dict) -> bool:
    have = _parse_labels(label_str)
    return all(have.get(k) == v for k, v in sel.items())


def _path_get(doc, path: str):
    cur = doc
    for part in path.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    if isinstance(cur, bool) or not isinstance(cur, (int, float)):
        return None
    return cur


def _round(v: float):
    """Canonical numeric form for the verdict: ints stay ints, floats
    round to 9 places (kills accumulation-order repr noise)."""
    if isinstance(v, int):
        return v
    r = round(v, 9)
    return int(r) if r == int(r) else r


def _node_value(rule: dict, node: dict):
    """One rule's observed value on one node; None = unobservable
    (dark node, missing status field) which fails the rule."""
    if rule["source"] != "metrics":
        doc = node.get(rule["source"])
        v = _path_get(doc, rule["path"]) if doc is not None else None
        return None if v is None else _round(v)
    m = node.get("metrics")
    if m is None:
        return None
    fam = _san(rule["metric"])
    sel = rule.get("labels") or {}
    kind = rule["kind"]
    if kind in ("counter", "gauge"):
        table = m["counter" if kind == "counter" else "gauge"].get(fam, {})
        # absent family = never written = 0 in this registry; labeled
        # series matching the selector sum into one observed value
        return _round(sum(v for ls, v in table.items()
                          if _labels_match(ls, sel)))
    cells = [c for ls, c in m["hist"].get(fam, {}).items()
             if _labels_match(ls, sel)]
    if kind == "count":
        return _round(sum(c["count"] for c in cells))
    if kind == "sum":
        return _round(sum(c["sum"] for c in cells))
    if kind == "max":
        return _round(max((c["max"] for c in cells), default=0.0))
    q = {"p50": 0.5, "p95": 0.95, "p99": 0.99}[kind]
    buckets = [0] * (len(BUCKET_BOUNDS) + 1)
    count = 0
    for c in cells:
        count += c["count"]
        for i, n in enumerate(c["buckets"]):
            buckets[i] += n
    return _round(_quantile(buckets, count, q))


def _cmp(op: str, observed, target) -> bool:
    if observed is None:
        return False
    if op == "==":
        return abs(observed - target) < 1e-9
    if op == "!=":
        return abs(observed - target) >= 1e-9
    if op == "<=":
        return observed <= target
    if op == "<":
        return observed < target
    if op == ">=":
        return observed >= target
    return observed > target


def evaluate(rules: list[dict], fleet: dict) -> dict:
    """The deterministic core: normalized rules x fleet state -> one
    verdict dict. Carries no clocks or scrape metadata, so two
    evaluations of the same fleet state are byte-identical through
    `verdict_bytes`."""
    nodes = fleet.get("nodes", {})
    labels = sorted(nodes)
    out_rules = []
    failed = []
    for rule in rules:
        observed = {lbl: _node_value(rule, nodes[lbl]) for lbl in labels}
        row = {k: v for k, v in rule.items()}
        row["observed"] = observed
        if rule["agg"] == "each":
            row["pass"] = bool(labels) and all(
                _cmp(rule["op"], observed[lbl], rule["value"])
                for lbl in labels)
        else:
            vals = [observed[lbl] for lbl in labels]
            if not vals or any(v is None for v in vals):
                agg_v = None
            elif rule["agg"] == "sum":
                agg_v = _round(sum(vals))
            elif rule["agg"] == "max":
                agg_v = _round(max(vals))
            else:
                agg_v = _round(min(vals))
            row["aggregate"] = agg_v
            row["pass"] = _cmp(rule["op"], agg_v, rule["value"])
        if not row["pass"]:
            failed.append(rule["name"])
        out_rules.append(row)
    return {
        "schema": SCHEMA,
        "nodes": labels,
        "dark_nodes": sorted(lbl for lbl in labels
                             if nodes[lbl].get("metrics") is None),
        "rules": out_rules,
        "failed": failed,
        "pass": not failed and bool(labels),
    }


def verdict_bytes(verdict: dict) -> bytes:
    """Canonical byte form — the determinism contract: same fleet state
    in, same bytes out."""
    return json.dumps(verdict, sort_keys=True,
                      separators=(",", ":")).encode()


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def main(argv=None) -> int:
    """`fleetmon --nodes url1,url2 --rules slo.json` — scrape, judge,
    print the verdict JSON; exit 0 pass / 2 SLO violation / 1 usage."""
    import argparse
    import sys

    ap = argparse.ArgumentParser(prog="fleetmon")
    ap.add_argument("--nodes", required=True,
                    help="comma-separated node/validator service URLs")
    ap.add_argument("--rules", required=True,
                    help="SLO rule file (JSON, FORMATS §22.1)")
    ap.add_argument("--no-availability", action="store_true",
                    help="skip the /das/availability scrape")
    ap.add_argument("--out", default=None,
                    help="also write the verdict JSON to this file")
    args = ap.parse_args(argv)
    try:
        with open(args.rules, encoding="utf-8") as f:
            rules = normalize_rules(json.load(f))
    except (OSError, ValueError) as e:
        print(f"ERROR: bad rule file: {e}", file=sys.stderr)
        return 1
    urls = [u for u in args.nodes.split(",") if u]
    fleet = scrape_fleet(urls,
                         with_availability=not args.no_availability)
    verdict = evaluate(rules, fleet)
    body = json.dumps(verdict, indent=2, sort_keys=True)
    print(body)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(body + "\n")
    return 0 if verdict["pass"] else 2


if __name__ == "__main__":
    raise SystemExit(main())
