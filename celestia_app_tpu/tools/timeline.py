"""Cross-node trace timeline: merge span tables into per-height waterfalls.

The consumer side of the observability plane (obs/spans.py): every node of
a devnet serves its span rows at ``/trace/spans``; this tool scrapes them
all, groups rows by trace_id (deterministic per height — `trace_id_for`),
and renders a text waterfall answering "where did block H spend its time
between proposer, followers, and light nodes". It is the analog of the
reference's e2e trace pullers (celestia-core pkg/trace + the testnet
tooling that tails BlockSummary/RoundState tables), upgraded with span
structure.

Library surface (used by tests and the CLI `timeline` command):
  scrape(urls)                 {node_label: [span rows]} over HTTP
  scrape_xfers(urls)           {node_label: [transfer ledger rows]}
  merge_spans(rows_by_node)    {trace_id: [rows tagged with "node"]}
  heights_of(merged)           {height: trace_id} for rows carrying one
  render_waterfall(rows)       the text waterfall for one trace
  collect(urls, height=None)   scrape + merge (+ height filter)

The boundary observatory's transfer ledger (obs/xfer.py) writes its
rows into the same per-App trace tables under the ``xfer`` table,
stamped with the covering span's trace id — `collect` scrapes them too
(``/trace/xfer``) and folds each one into its height's waterfall as a
leaf named ``xfer:<site> <dir> <bytes>B`` under the span that covered
the transfer, so a block's host↔device traffic renders inline with its
compute spans (and rides the --json dump for machine consumers).

The renderer needs only row dicts — in-process TraceTables output works
the same as scraped JSON, so a light node that serves no HTTP (an
embedded DASer) can hand its `daser.traces.read("spans")` rows straight
to merge_spans.
"""

from __future__ import annotations

import json

from celestia_app_tpu.obs import SPAN_TABLE
from celestia_app_tpu.obs.xfer import XFER_TABLE

BAR_WIDTH = 40


def fetch_node_spans(url: str, since: int = 0, limit: int = 10_000,
                     client=None) -> list[dict]:
    """Pull one node's span rows over HTTP (node service or validator
    service — both serve /trace/spans)."""
    from celestia_app_tpu.net import transport

    client = client or transport.DEFAULT
    doc = client.get(url.rstrip("/"),
                     f"/trace/{SPAN_TABLE}?since={since}&limit={limit}")
    return list(doc.get("rows", []))


def scrape(urls: list[str], since: int = 0,
           limit: int = 10_000) -> dict[str, list[dict]]:
    """{node_label: rows} for every reachable node; unreachable nodes
    yield an empty list (a partial devnet still renders)."""
    out: dict[str, list[dict]] = {}
    for url in urls:
        label = url.rstrip("/")
        try:
            out[label] = fetch_node_spans(url, since=since, limit=limit)
        except (OSError, ValueError, KeyError):
            out[label] = []
    return out


def fetch_node_xfers(url: str, since: int = 0, limit: int = 10_000,
                     client=None) -> list[dict]:
    """Pull one node's transfer-ledger rows (obs/xfer.py) over HTTP."""
    from celestia_app_tpu.net import transport

    client = client or transport.DEFAULT
    doc = client.get(url.rstrip("/"),
                     f"/trace/{XFER_TABLE}?since={since}&limit={limit}")
    return list(doc.get("rows", []))


def scrape_xfers(urls: list[str], since: int = 0,
                 limit: int = 10_000) -> dict[str, list[dict]]:
    """{node_label: ledger rows}; unreachable nodes yield []."""
    out: dict[str, list[dict]] = {}
    for url in urls:
        label = url.rstrip("/")
        try:
            out[label] = fetch_node_xfers(url, since=since, limit=limit)
        except (OSError, ValueError, KeyError):
            out[label] = []
    return out


def _xfer_as_span_row(row: dict) -> dict:
    """A ledger row shaped like a leaf span: named by call site + bytes,
    parented (via parent_id) under the span that covered the transfer.
    It carries no span_id — the renderer indents it one level below its
    parent."""
    return {
        **row,
        "table": XFER_TABLE,
        "name": (f"xfer:{row.get('site', '?')} {row.get('dir', '?')} "
                 f"{int(row.get('bytes', 0))}B"),
    }


def merge_spans(rows_by_node: dict[str, list[dict]]) -> dict[str, list[dict]]:
    """Group every node's span rows by trace_id, tagging each row with its
    source node. Rows inside a trace sort by start_unix — valid across
    processes on one host (the devnet case); cross-host clock skew only
    shifts bars, never the parent/child edges."""
    merged: dict[str, list[dict]] = {}
    for node, rows in rows_by_node.items():
        for row in rows:
            tid = row.get("trace_id")
            if not tid:
                continue
            merged.setdefault(tid, []).append({**row, "node": node})
    for rows in merged.values():
        rows.sort(key=lambda r: (r.get("start_unix", 0.0),
                                 r.get("_index", 0)))
    return merged


def heights_of(merged: dict[str, list[dict]]) -> dict[int, str]:
    """{height: trace_id} for traces whose rows carry a height attr."""
    out: dict[int, str] = {}
    for tid, rows in merged.items():
        for row in rows:
            h = row.get("height")
            if isinstance(h, int):
                out.setdefault(h, tid)
                break
    return out


def _depths(rows: list[dict]) -> dict[str, int]:
    by_id = {r.get("span_id"): r for r in rows if r.get("span_id")}

    def depth(row, hops=0) -> int:
        if hops > len(rows):  # defensive: a parent cycle must not hang
            return 0
        parent = by_id.get(row.get("parent_id"))
        if parent is None:
            return 0
        return 1 + depth(parent, hops + 1)

    return {sid: depth(row) for sid, row in by_id.items()}


def render_waterfall(rows: list[dict], width: int = BAR_WIDTH) -> str:
    """One trace's rows -> a text waterfall: offset from the earliest
    span, indentation by parent depth, a proportional bar, node label."""
    if not rows:
        return "(no spans)"
    t0 = min(r.get("start_unix", 0.0) for r in rows)
    t_end = max(r.get("start_unix", 0.0) + r.get("dur_ms", 0.0) / 1e3
                for r in rows)
    total_s = max(t_end - t0, 1e-9)
    depths = _depths(rows)

    def row_depth(row: dict) -> int:
        sid = row.get("span_id")
        if sid in depths:
            return depths[sid]
        # ledger rows (and any span-id-less leaf): one level below the
        # parent span that covered them; orphans sit at the root
        return depths.get(row.get("parent_id"), -1) + 1

    tid = rows[0].get("trace_id", "?")
    heights = {r["height"] for r in rows if isinstance(r.get("height"), int)}
    head = f"trace {tid}"
    if heights:
        head += f" (height {', '.join(str(h) for h in sorted(heights))})"
    lines = [head,
             f"{'offset':>10}  {'dur':>9}  span"]
    for row in sorted(rows, key=lambda r: (r.get("start_unix", 0.0),
                                           row_depth(r))):
        off_s = row.get("start_unix", 0.0) - t0
        dur_s = row.get("dur_ms", 0.0) / 1e3
        lo = min(int(off_s / total_s * width), width - 1)
        hi = min(max(int((off_s + dur_s) / total_s * width), lo + 1), width)
        bar = " " * lo + "#" * (hi - lo) + " " * (width - hi)
        indent = "  " * row_depth(row)
        name = row.get("name", "?")
        node = row.get("node", "")
        lines.append(
            f"{off_s * 1e3:8.1f}ms {row.get('dur_ms', 0.0):8.2f}ms "
            f"|{bar}| {indent}{name}"
            + (f"  [{node}]" if node else "")
        )
    return "\n".join(lines)


def collect(urls: list[str], height: int | None = None,
            since: int = 0, limit: int = 10_000,
            xfers: bool = True) -> dict:
    """Scrape + merge a devnet; optionally keep only the given height's
    trace. With `xfers` (default) the transfer-ledger rows of every node
    join their heights' traces as leaf rows (table == "xfer").
    Returns {"traces": {trace_id: rows}, "heights": {h: tid}}."""
    rows_by_node = scrape(urls, since=since, limit=limit)
    if xfers:
        for node, xrows in scrape_xfers(urls, since=since,
                                        limit=limit).items():
            rows_by_node[node] = (rows_by_node.get(node, [])
                                  + [_xfer_as_span_row(r) for r in xrows])
    merged = merge_spans(rows_by_node)
    heights = heights_of(merged)
    if height is not None:
        tid = heights.get(height)
        merged = {tid: merged[tid]} if tid else {}
        heights = {height: tid} if tid else {}
    return {"traces": merged, "heights": heights}


def report_text(doc: dict, last: int = 5) -> str:
    """Render the `last` most recent heights' waterfalls (all traces
    without a height attr are skipped — they are ad-hoc roots)."""
    heights = doc.get("heights", {})
    if not heights:
        return "(no height-bearing traces found)"
    chunks = []
    for h in sorted(heights)[-last:]:
        chunks.append(render_waterfall(doc["traces"][heights[h]]))
    return "\n\n".join(chunks)


def main(argv=None) -> int:
    """`python -m celestia_app_tpu.tools.timeline --nodes url1,url2`
    (the CLI `timeline` subcommand wraps this)."""
    import argparse

    ap = argparse.ArgumentParser(prog="timeline")
    ap.add_argument("--nodes", required=True,
                    help="comma-separated node/validator service URLs")
    ap.add_argument("--height", type=int, default=None)
    ap.add_argument("--since", type=int, default=0)
    ap.add_argument("--limit", type=int, default=10_000)
    ap.add_argument("--last", type=int, default=5,
                    help="render the N most recent heights (text mode)")
    ap.add_argument("--json", action="store_true",
                    help="dump the merged span rows as JSON instead")
    ap.add_argument("--no-xfer", action="store_true",
                    help="skip the transfer-ledger rows (/trace/xfer)")
    args = ap.parse_args(argv)
    doc = collect([u for u in args.nodes.split(",") if u],
                  height=args.height, since=args.since, limit=args.limit,
                  xfers=not args.no_xfer)
    if args.json:
        print(json.dumps(doc, indent=2, sort_keys=True))
    else:
        print(report_text(doc, last=args.last))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
