"""blockscan: summarize stored blocks (tools/blockscan analog)."""

from __future__ import annotations

from celestia_app_tpu.chain.storage import ChainDB
from celestia_app_tpu.da.blob import is_blob_tx, unmarshal_blob_tx


def scan(data_dir: str, from_height: int | None = None, to_height: int | None = None):
    # read_only: scanning a LIVE validator home must neither take the
    # writer flock nor truncate a tail the writer is mid-appending
    db = ChainDB(data_dir, read_only=True)
    for h in db.block_heights():
        if from_height is not None and h < from_height:
            continue
        if to_height is not None and h > to_height:
            continue
        blk = db.load_block(h)
        n_blob_txs = sum(1 for t in blk.txs if is_blob_tx(t))
        blob_bytes = sum(
            len(b.data)
            for t in blk.txs
            if is_blob_tx(t)
            for b in unmarshal_blob_tx(t).blobs
        )
        yield {
            "height": h,
            "time_unix": blk.header.time_unix,
            "square_size": blk.header.square_size,
            "txs": len(blk.txs),
            "blob_txs": n_blob_txs,
            "blob_bytes": blob_bytes,
            "data_hash": blk.header.data_hash.hex(),
        }
