"""IBC relayer: automatic packet + ack + timeout settlement between two
chains.

The reference ecosystem delegates relaying to an external daemon
(hermes/rly): watch chain A for send_packet events, update chain B's
light client, submit MsgRecvPacket with a membership proof, then carry
the written acknowledgement (or an expiry + ack-ABSENCE proof) back to A
the same way. This module is that daemon for two instances of THIS
framework, speaking only public surfaces — committed tx events (ibc-go's
event-sourcing reality: the chain stores only commitment hashes), store
proofs, and ordinary signed transactions for delivery.

Two chain transports share one relay engine:

  ChainHandle      — in-process Node/ValidatorNode (tests, embedded use)
  HttpChainHandle  — a LIVE node over its HTTP service (/ibc/* routes on
                     service/server.py) — the hermes deployment shape:
                     the relayer is its own process holding only its key.

Idempotent by construction — no local database: a packet is pending-recv
iff the destination has no ack recorded for it, pending-ack-settle iff
the source still holds its commitment (take_commitment deletes it on
settlement), and pending-timeout iff expired with no ack ever written. A
crashed-and-restarted relayer re-derives exactly the remaining work from
chain state.
"""

from __future__ import annotations

import base64
import dataclasses
import json
import urllib.error

from celestia_app_tpu.chain.ibc import ChannelKeeper
from celestia_app_tpu.net.transport import PeerClient, TransportConfig
from celestia_app_tpu.chain.state import (
    Context,
    InfiniteGasMeter,
    canonical_json,
)
from celestia_app_tpu.chain.tx import (
    MsgAcknowledgePacket,
    MsgRecvPacket,
    MsgTimeoutPacket,
    MsgUpdateClient,
)


def _commit_key(packet: dict) -> bytes:
    return (
        ChannelKeeper.COMMIT
        + f"{packet['source_port']}/{packet['source_channel']}/"
          f"{packet['sequence']}".encode()
    )


def _ack_key(packet: dict) -> bytes:
    return (
        ChannelKeeper.ACK
        + f"{packet['destination_port']}/{packet['destination_channel']}/"
          f"{packet['sequence']}".encode()
    )


def _submit_with_resync(signer, relayer: bytes, msg, gas: int,
                        broadcast) -> None:
    """Sign + broadcast (via `broadcast(raw) -> (code, log)`), bumping the
    cached sequence on acceptance. On a sequence-mismatch rejection (a
    node restart flushed the mempool, a prior tx was dropped), re-sync
    the sequence from the node's nonce-mismatch answer and retry ONCE —
    a long-running relayer daemon must never wedge permanently on one
    lost tx (round-4 advisor finding). One definition for both
    transports so the retry policy cannot silently diverge."""
    from celestia_app_tpu.client.tx_client import parse_expected_sequence

    for attempt in (0, 1):
        tx = signer.create_tx(relayer, [msg], fee=2000, gas_limit=gas)
        code, log = broadcast(tx.encode())
        if code == 0:
            signer.accounts[relayer].sequence += 1
            return
        expected = parse_expected_sequence(log)
        if expected is None or attempt:
            raise RuntimeError(f"relay tx rejected: {log}")
        signer.accounts[relayer].sequence = expected


@dataclasses.dataclass
class ChainHandle:
    """One side of the relay, in-process: a node + a funded relayer key.
    `client_id` is the IBC client ON THIS CHAIN tracking the
    counterparty; `verifying` marks that client as header-verified
    (create_client with a trusted valset), which switches the relay
    engine to the real light-client flow: prove at height H, update the
    client with the CERTIFIED header for H+1 (whose app_hash IS the
    state root after H), deliver with proof_height = H+1."""

    node: object  # Node or ValidatorNode (broadcast_tx-capable)
    signer: object  # client.tx_client.Signer with the relayer account
    relayer: bytes  # 20-byte relayer address
    client_id: str
    # verifying is the DEFAULT: between two instances of this framework
    # there is no reason to relay on say-so (VERDICT r4 #4). Set False
    # only for the explicitly-insecure trusting fixture (a client created
    # with insecure_relayer=<this relayer>).
    verifying: bool = True

    @property
    def app(self):
        return self.node.app

    def ctx(self) -> Context:
        return Context(self.app.store, InfiniteGasMeter(), self.app.height,
                       0, self.app.chain_id, self.app.app_version)

    # -- the transport surface the relay engine consumes -----------------

    def height(self) -> int:
        return self.app.height

    def events(self, type_: str) -> list[dict]:
        out = []
        for _txhash, (_h, res) in sorted(
            self.node.committed.items(), key=lambda kv: kv[1][0]
        ):
            if res.code != 0:
                continue
            out.extend(ev for ev in res.events if ev.get("type") == type_)
        return out

    def get_ack(self, packet: dict):
        return self.app.ibc.channels.get_ack(self.ctx(), packet)

    def has_commitment(self, packet: dict) -> bool:
        return self.app.store.get(_commit_key(packet)) is not None

    def prove(self, key: bytes) -> dict:
        return self.app.store.prove(key)

    def prove_absence(self, key: bytes) -> dict:
        return self.app.store.prove_absence(key)

    def client_latest_height(self):
        return self.app.ibc.clients.latest_height(self.ctx(), self.client_id)

    def submit(self, msg, gas: int = 500_000) -> None:
        def broadcast(raw: bytes):
            res = self.node.broadcast_tx(raw)
            return res.code, res.log

        _submit_with_resync(self.signer, self.relayer, msg, gas, broadcast)

    def status_pair(self) -> tuple[int, bytes]:
        """(height, last_root), paired consistently against a concurrent
        commit (round-4 advisor finding: two independent reads straddling
        a commit mis-bind root to height). App.commit writes the root
        BEFORE the height, so a read can never observe a new height with
        the previous block's root; the double pair-read below retries the
        other interleavings. The one unclosable window — both reads
        landing between commit's two stores — yields (old height, new
        root), which is benign: the proofs this relayer then captures are
        against the SAME new root, so they verify against the recorded
        binding."""
        for _ in range(4):
            h, root = self.app.height, self.app.last_app_hash
            if (self.app.height, self.app.last_app_hash) == (h, root):
                return h, root
        return h, root

    def update_payload(self, height: int):
        """(header_json, cert_json) for a CERTIFIED block at `height` —
        what a counterparty's VERIFYING client demands. Available when
        this chain's node is consensus-backed (ValidatorNode: block
        store + commit certificates); None until that block is certified
        (the relayer retries next pass)."""
        from celestia_app_tpu.chain import consensus as c

        certs = getattr(self.node, "certificates", None)
        db = getattr(self.app, "db", None)
        if not certs or height not in certs or db is None:
            return None
        try:
            block = db.load_block(height)
        except (KeyError, ValueError, OSError):
            return None
        return (
            json.dumps(c.header_to_json(block.header)).encode(),
            json.dumps(c.cert_to_json(certs[height])).encode(),
        )


@dataclasses.dataclass
class HttpChainHandle:
    """One side of the relay over a LIVE node's HTTP service — the hermes
    deployment shape: the relayer process holds only its signing key and
    the node URL; everything else comes from /ibc/* + /status +
    /broadcast_tx (service/server.py)."""

    url: str
    signer: object
    relayer: bytes
    client_id: str
    verifying: bool = True  # see ChainHandle: say-so relay is opt-in
    timeout: float = 15.0
    # the hardened transport (net/transport.py); HTTP status errors still
    # propagate as HTTPError — has_commitment reads 404-means-absent
    client: PeerClient = None

    def __post_init__(self):
        if self.client is None:
            self.client = PeerClient(
                TransportConfig(timeout=self.timeout, retries=2),
                name="relayer",
            )

    def _get(self, path: str):
        return self.client.get(self.url, path, timeout=self.timeout)

    def _post(self, path: str, payload: dict):
        return self.client.post(self.url, path, payload,
                                timeout=self.timeout)

    def height(self) -> int:
        return self._get("/status")["height"]

    def events(self, type_: str) -> list[dict]:
        return self._post("/ibc/events", {"type": type_})["events"]

    def get_ack(self, packet: dict):
        return self._post("/ibc/ack", {"packet": packet})["ack"]

    def has_commitment(self, packet: dict) -> bool:
        # membership proof doubles as the existence check (404 = absent)
        try:
            self._post("/ibc/prove", {"key": _commit_key(packet).hex()})
            return True
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return False
            raise

    def prove(self, key: bytes) -> dict:
        return self._post("/ibc/prove", {"key": key.hex()})["proof"]

    def prove_absence(self, key: bytes) -> dict:
        return self._post(
            "/ibc/prove", {"key": key.hex(), "absence": True}
        )["proof"]

    def client_latest_height(self):
        return self._post(
            "/ibc/client_height", {"client_id": self.client_id}
        )["latest_height"]

    def submit(self, msg, gas: int = 500_000) -> None:
        def broadcast(raw: bytes):
            res = self._post("/broadcast_tx", {
                "tx": base64.b64encode(raw).decode()
            })
            return res["code"], res.get("log", "")

        _submit_with_resync(self.signer, self.relayer, msg, gas, broadcast)

    def status_pair(self) -> tuple[int, bytes]:
        """(height, last_root) from ONE /status response — two separate
        HTTP reads could straddle a commit and mis-bind root to height
        (round-4 advisor finding)."""
        st = self._get("/status")
        return st["height"], bytes.fromhex(st["last_app_hash"])

    def update_payload(self, height: int):
        try:
            out = self._post("/ibc/header", {"height": height})
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            raise
        if not out.get("header"):
            return None
        return (
            json.dumps(out["header"]).encode(),
            json.dumps(out["cert"]).encode(),
        )


class Relayer:
    """Bidirectional relay engine over two handles (either transport)."""

    def __init__(self, a, b):
        self.a = a
        self.b = b
        # heights already SUBMITTED (possibly uncommitted) per client: the
        # committed latest_height lags the mempool within one pass, and a
        # duplicate same-height MsgUpdateClient deterministically fails
        # the monotonicity check, burning the fee for nothing
        self._submitted_updates: dict[str, int] = {}
        # verifying-mode proof cache: a proof captured at tip H stays
        # valid against its static root forever, but the header carrying
        # that root (H+1) certifies one block LATER — without the cache
        # every pass would re-prove at the new tip and chase it forever.
        # Restart-safe: a fresh relayer re-captures and waits one block.
        self._proof_cache: dict[bytes, tuple[dict, int]] = {}

    # -- work discovery (pure chain state; no local database) ------------

    def _pending_packets(self, src, dst) -> list[dict]:
        """Packets src committed that dst has not acknowledged yet —
        excluding expired ones (hermes refuses to deliver past the
        timeout; the timeout pass settles those instead)."""
        pending = []
        dst_height = dst.height()
        for ev in src.events("send_packet"):
            packet = json.loads(ev["packet_json"])
            timeout = int(packet.get("timeout_height") or 0)
            if timeout and dst_height >= timeout:
                continue
            if dst.get_ack(packet) is None:
                pending.append(packet)
        return pending

    def _expired_packets(self, src, dst) -> list[dict]:
        """src's packets whose timeout height has passed on dst with no
        ack ever written — the set MsgTimeout settles (refund)."""
        out = []
        dst_height = dst.height()
        for ev in src.events("send_packet"):
            packet = json.loads(ev["packet_json"])
            timeout = int(packet.get("timeout_height") or 0)
            if timeout <= 0 or dst_height < timeout:
                continue
            if not src.has_commitment(packet):
                continue  # already settled (ack or prior timeout)
            if dst.get_ack(packet) is not None:
                continue  # received in time: the ack pass settles it
            out.append(packet)
        return out

    def _unsettled_acks(self, src, dst) -> list[tuple[dict, dict]]:
        """(packet, ack) pairs dst wrote whose commitment still sits on
        src (i.e. the ack has not settled back)."""
        out = []
        for ev in dst.events("write_acknowledgement"):
            packet = json.loads(ev["packet_json"])
            if src.has_commitment(packet):
                out.append((packet, json.loads(ev["ack_json"])))
        return out

    # -- client updates --------------------------------------------------

    def _update_client(self, viewer, viewed) -> int:
        """Record `viewed`'s latest committed root on `viewer`'s client —
        as a CONSENSUS TX (MsgUpdateClient), never a direct keeper write:
        on a replicated `viewer` chain, node-local client state would
        fork validators. Sequenced from the same relayer account as the
        recv/ack that follows, so it executes first. Root-based (say-so)
        updates here; a VERIFYING client additionally needs the header/
        cert/valset JSON payloads the msg carries (wire them from a
        light-client follower when the viewed chain runs one)."""
        height, root = viewed.status_pair()
        known = viewer.client_latest_height()
        if known is not None and known >= height:
            return known  # already recorded — prove at that height
        if self._submitted_updates.get(viewer.client_id, -1) >= height:
            return height  # update already in this pass's mempool
        viewer.submit(MsgUpdateClient(
            relayer=viewer.relayer,
            client_id=viewer.client_id,
            height=height,
            root=root,
        ), gas=200_000)
        self._submitted_updates[viewer.client_id] = height
        return height

    # -- delivery --------------------------------------------------------

    def _verified_update(self, viewer, viewed, proof_state_height: int):
        """The light-client flow for a VERIFYING viewer client: the state
        root after height H only appears in header H+1's app_hash, so
        prove against the tip (H) and update the client with the
        certified header for H+1. Returns the proof height to use, or
        None when H+1 is not certified yet (retry next pass — the proof
        already captured stays valid against its static root)."""
        target = proof_state_height + 1
        known = viewer.client_latest_height()
        if known is not None and known >= target:
            return target  # header already recorded
        payload = viewed.update_payload(target)
        if payload is None:
            return None
        if self._submitted_updates.get(viewer.client_id, -1) >= target:
            return target
        header_json, cert_json = payload
        viewer.submit(MsgUpdateClient(
            relayer=viewer.relayer,
            client_id=viewer.client_id,
            height=target,
            root=b"",  # the verified header supplies the root
            header_json=header_json,
            cert_json=cert_json,
        ), gas=300_000)
        self._submitted_updates[viewer.client_id] = target
        return target

    def _proof_for(self, chain, key: bytes, absence: bool = False):
        """(proof, state_height) with verifying-mode caching (see
        _proof_cache): the height is read BEFORE proving so the proof
        binds to that height's root."""
        cached = self._proof_cache.get(key)
        if cached is not None:
            return cached
        state_height = chain.height()
        proof = (chain.prove_absence(key) if absence
                 else chain.prove(key))
        self._proof_cache[key] = (proof, state_height)
        return proof, state_height

    def _relay_packets(self, src, dst) -> int:
        n = 0
        for packet in self._pending_packets(src, dst):
            key = _commit_key(packet)
            if dst.verifying:
                proof, state_height = self._proof_for(src, key)
                height = self._verified_update(dst, src, state_height)
                if height is None:
                    continue  # src's next header not certified yet
                self._proof_cache.pop(key, None)
            else:
                # trusting client: record the current root, prove against
                # it in the same pass (no block in between)
                height = self._update_client(dst, src)
                proof = src.prove(key)
            dst.submit(MsgRecvPacket(
                relayer=dst.relayer,
                packet_json=canonical_json(packet),
                proof_json=canonical_json(proof),
                proof_height=height,
            ))
            n += 1
        return n

    def _relay_acks(self, src, dst) -> int:
        """Settle on `src` the acks `dst` wrote for src's packets."""
        n = 0
        for packet, ack in self._unsettled_acks(src, dst):
            key = _ack_key(packet)
            if src.verifying:
                proof, state_height = self._proof_for(dst, key)
                height = self._verified_update(src, dst, state_height)
                if height is None:
                    continue
                self._proof_cache.pop(key, None)
            else:
                height = self._update_client(src, dst)
                proof = dst.prove(key)
            src.submit(MsgAcknowledgePacket(
                relayer=src.relayer,
                packet_json=canonical_json(packet),
                ack_json=canonical_json(ack),
                proof_json=canonical_json(proof),
                proof_height=height,
            ))
            n += 1
        return n

    def _relay_timeouts(self, src, dst) -> int:
        """Refund src's expired packets: client view advanced past the
        timeout height, plus an ABSENCE proof that dst never wrote the
        ack (the receipt-absence gate in chain/ibc.timeout_packet)."""
        n = 0
        for packet in self._expired_packets(src, dst):
            key = _ack_key(packet)
            if src.verifying:
                proof, state_height = self._proof_for(dst, key,
                                                      absence=True)
                height = self._verified_update(src, dst, state_height)
                if height is None:
                    continue
                self._proof_cache.pop(key, None)
            else:
                height = self._update_client(src, dst)
                proof = dst.prove_absence(key)
            if height < int(packet["timeout_height"]):
                continue  # client not past expiry yet; next pass
            src.submit(MsgTimeoutPacket(
                relayer=src.relayer,
                packet_json=canonical_json(packet),
                proof_json=canonical_json(proof),
                proof_height=height,
            ))
            n += 1
        return n

    def step(self) -> dict:
        """One relay pass in both directions. Delivery txs enter the
        mempools; the caller drives block production (or consensus does,
        on validator nodes) and calls step() again — acks for this pass's
        packets settle on the NEXT pass, after they commit."""
        return {
            "recv_a_to_b": self._relay_packets(self.a, self.b),
            "recv_b_to_a": self._relay_packets(self.b, self.a),
            "acks_to_a": self._relay_acks(self.a, self.b),
            "acks_to_b": self._relay_acks(self.b, self.a),
            "timeouts_to_a": self._relay_timeouts(self.a, self.b),
            "timeouts_to_b": self._relay_timeouts(self.b, self.a),
        }
