"""IBC relayer: automatic packet + ack + timeout settlement between two
chains.

The reference ecosystem delegates relaying to an external daemon
(hermes/rly): watch chain A for send_packet events, update chain B's
light client, submit MsgRecvPacket with a membership proof, then carry
the written acknowledgement (or an expiry + ack-ABSENCE proof) back to A
the same way. This module is that daemon for two instances of THIS
framework, speaking only public surfaces — committed tx events (ibc-go's
event-sourcing reality: the chain stores only commitment hashes), store
proofs, and ordinary signed transactions for delivery.

Two chain transports share one relay engine:

  ChainHandle      — in-process Node/ValidatorNode (tests, embedded use)
  HttpChainHandle  — a LIVE node over its HTTP service (/ibc/* routes on
                     service/server.py) — the hermes deployment shape:
                     the relayer is its own process holding only its key.

Idempotent by construction — no local database: a packet is pending-recv
iff the destination has no ack recorded for it, pending-ack-settle iff
the source still holds its commitment (take_commitment deletes it on
settlement), and pending-timeout iff expired with no ack ever written. A
crashed-and-restarted relayer re-derives exactly the remaining work from
chain state.
"""

from __future__ import annotations

import base64
import dataclasses
import json
import urllib.error
import urllib.request

from celestia_app_tpu.chain.ibc import ChannelKeeper
from celestia_app_tpu.chain.state import (
    Context,
    InfiniteGasMeter,
    canonical_json,
)
from celestia_app_tpu.chain.tx import (
    MsgAcknowledgePacket,
    MsgRecvPacket,
    MsgTimeoutPacket,
    MsgUpdateClient,
)


def _commit_key(packet: dict) -> bytes:
    return (
        ChannelKeeper.COMMIT
        + f"{packet['source_port']}/{packet['source_channel']}/"
          f"{packet['sequence']}".encode()
    )


def _ack_key(packet: dict) -> bytes:
    return (
        ChannelKeeper.ACK
        + f"{packet['destination_port']}/{packet['destination_channel']}/"
          f"{packet['sequence']}".encode()
    )


@dataclasses.dataclass
class ChainHandle:
    """One side of the relay, in-process: a node + a funded relayer key.
    `client_id` is the IBC client ON THIS CHAIN tracking the
    counterparty."""

    node: object  # Node or ValidatorNode (broadcast_tx-capable)
    signer: object  # client.tx_client.Signer with the relayer account
    relayer: bytes  # 20-byte relayer address
    client_id: str

    @property
    def app(self):
        return self.node.app

    def ctx(self) -> Context:
        return Context(self.app.store, InfiniteGasMeter(), self.app.height,
                       0, self.app.chain_id, self.app.app_version)

    # -- the transport surface the relay engine consumes -----------------

    def height(self) -> int:
        return self.app.height

    def last_root(self) -> bytes:
        return self.app.last_app_hash

    def events(self, type_: str) -> list[dict]:
        out = []
        for _txhash, (_h, res) in sorted(
            self.node.committed.items(), key=lambda kv: kv[1][0]
        ):
            if res.code != 0:
                continue
            out.extend(ev for ev in res.events if ev.get("type") == type_)
        return out

    def get_ack(self, packet: dict):
        return self.app.ibc.channels.get_ack(self.ctx(), packet)

    def has_commitment(self, packet: dict) -> bool:
        return self.app.store.get(_commit_key(packet)) is not None

    def prove(self, key: bytes) -> dict:
        return self.app.store.prove(key)

    def prove_absence(self, key: bytes) -> dict:
        return self.app.store.prove_absence(key)

    def client_latest_height(self):
        return self.app.ibc.clients.latest_height(self.ctx(), self.client_id)

    def submit(self, msg, gas: int = 500_000) -> None:
        tx = self.signer.create_tx(self.relayer, [msg], fee=2000,
                                   gas_limit=gas)
        res = self.node.broadcast_tx(tx.encode())
        if res.code != 0:
            raise RuntimeError(f"relay tx rejected: {res.log}")
        self.signer.accounts[self.relayer].sequence += 1


@dataclasses.dataclass
class HttpChainHandle:
    """One side of the relay over a LIVE node's HTTP service — the hermes
    deployment shape: the relayer process holds only its signing key and
    the node URL; everything else comes from /ibc/* + /status +
    /broadcast_tx (service/server.py)."""

    url: str
    signer: object
    relayer: bytes
    client_id: str
    timeout: float = 15.0

    def _get(self, path: str):
        with urllib.request.urlopen(self.url.rstrip("/") + path,
                                    timeout=self.timeout) as r:
            return json.loads(r.read())

    def _post(self, path: str, payload: dict):
        req = urllib.request.Request(
            self.url.rstrip("/") + path,
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"}, method="POST",
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            return json.loads(r.read())

    def height(self) -> int:
        return self._get("/status")["height"]

    def last_root(self) -> bytes:
        return bytes.fromhex(self._get("/status")["last_app_hash"])

    def events(self, type_: str) -> list[dict]:
        return self._post("/ibc/events", {"type": type_})["events"]

    def get_ack(self, packet: dict):
        return self._post("/ibc/ack", {"packet": packet})["ack"]

    def has_commitment(self, packet: dict) -> bool:
        # membership proof doubles as the existence check (404 = absent)
        try:
            self._post("/ibc/prove", {"key": _commit_key(packet).hex()})
            return True
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return False
            raise

    def prove(self, key: bytes) -> dict:
        return self._post("/ibc/prove", {"key": key.hex()})["proof"]

    def prove_absence(self, key: bytes) -> dict:
        return self._post(
            "/ibc/prove", {"key": key.hex(), "absence": True}
        )["proof"]

    def client_latest_height(self):
        return self._post(
            "/ibc/client_height", {"client_id": self.client_id}
        )["latest_height"]

    def submit(self, msg, gas: int = 500_000) -> None:
        tx = self.signer.create_tx(self.relayer, [msg], fee=2000,
                                   gas_limit=gas)
        res = self._post("/broadcast_tx", {
            "tx": base64.b64encode(tx.encode()).decode()
        })
        if res["code"] != 0:
            raise RuntimeError(f"relay tx rejected: {res['log']}")
        self.signer.accounts[self.relayer].sequence += 1


class Relayer:
    """Bidirectional relay engine over two handles (either transport)."""

    def __init__(self, a, b):
        self.a = a
        self.b = b
        # heights already SUBMITTED (possibly uncommitted) per client: the
        # committed latest_height lags the mempool within one pass, and a
        # duplicate same-height MsgUpdateClient deterministically fails
        # the monotonicity check, burning the fee for nothing
        self._submitted_updates: dict[str, int] = {}

    # -- work discovery (pure chain state; no local database) ------------

    def _pending_packets(self, src, dst) -> list[dict]:
        """Packets src committed that dst has not acknowledged yet —
        excluding expired ones (hermes refuses to deliver past the
        timeout; the timeout pass settles those instead)."""
        pending = []
        dst_height = dst.height()
        for ev in src.events("send_packet"):
            packet = json.loads(ev["packet_json"])
            timeout = int(packet.get("timeout_height") or 0)
            if timeout and dst_height >= timeout:
                continue
            if dst.get_ack(packet) is None:
                pending.append(packet)
        return pending

    def _expired_packets(self, src, dst) -> list[dict]:
        """src's packets whose timeout height has passed on dst with no
        ack ever written — the set MsgTimeout settles (refund)."""
        out = []
        dst_height = dst.height()
        for ev in src.events("send_packet"):
            packet = json.loads(ev["packet_json"])
            timeout = int(packet.get("timeout_height") or 0)
            if timeout <= 0 or dst_height < timeout:
                continue
            if not src.has_commitment(packet):
                continue  # already settled (ack or prior timeout)
            if dst.get_ack(packet) is not None:
                continue  # received in time: the ack pass settles it
            out.append(packet)
        return out

    def _unsettled_acks(self, src, dst) -> list[tuple[dict, dict]]:
        """(packet, ack) pairs dst wrote whose commitment still sits on
        src (i.e. the ack has not settled back)."""
        out = []
        for ev in dst.events("write_acknowledgement"):
            packet = json.loads(ev["packet_json"])
            if src.has_commitment(packet):
                out.append((packet, json.loads(ev["ack_json"])))
        return out

    # -- client updates --------------------------------------------------

    def _update_client(self, viewer, viewed) -> int:
        """Record `viewed`'s latest committed root on `viewer`'s client —
        as a CONSENSUS TX (MsgUpdateClient), never a direct keeper write:
        on a replicated `viewer` chain, node-local client state would
        fork validators. Sequenced from the same relayer account as the
        recv/ack that follows, so it executes first. Root-based (say-so)
        updates here; a VERIFYING client additionally needs the header/
        cert/valset JSON payloads the msg carries (wire them from a
        light-client follower when the viewed chain runs one)."""
        height = viewed.height()
        root = viewed.last_root()
        known = viewer.client_latest_height()
        if known is not None and known >= height:
            return known  # already recorded — prove at that height
        if self._submitted_updates.get(viewer.client_id, -1) >= height:
            return height  # update already in this pass's mempool
        viewer.submit(MsgUpdateClient(
            relayer=viewer.relayer,
            client_id=viewer.client_id,
            height=height,
            root=root,
        ), gas=200_000)
        self._submitted_updates[viewer.client_id] = height
        return height

    # -- delivery --------------------------------------------------------

    def _relay_packets(self, src, dst) -> int:
        n = 0
        for packet in self._pending_packets(src, dst):
            height = self._update_client(dst, src)
            proof = src.prove(_commit_key(packet))
            dst.submit(MsgRecvPacket(
                relayer=dst.relayer,
                packet_json=canonical_json(packet),
                proof_json=canonical_json(proof),
                proof_height=height,
            ))
            n += 1
        return n

    def _relay_acks(self, src, dst) -> int:
        """Settle on `src` the acks `dst` wrote for src's packets."""
        n = 0
        for packet, ack in self._unsettled_acks(src, dst):
            height = self._update_client(src, dst)
            proof = dst.prove(_ack_key(packet))
            src.submit(MsgAcknowledgePacket(
                relayer=src.relayer,
                packet_json=canonical_json(packet),
                ack_json=canonical_json(ack),
                proof_json=canonical_json(proof),
                proof_height=height,
            ))
            n += 1
        return n

    def _relay_timeouts(self, src, dst) -> int:
        """Refund src's expired packets: client view advanced past the
        timeout height, plus an ABSENCE proof that dst never wrote the
        ack (the receipt-absence gate in chain/ibc.timeout_packet)."""
        n = 0
        for packet in self._expired_packets(src, dst):
            height = self._update_client(src, dst)
            if height < int(packet["timeout_height"]):
                continue  # client not past expiry yet; next pass
            proof = dst.prove_absence(_ack_key(packet))
            src.submit(MsgTimeoutPacket(
                relayer=src.relayer,
                packet_json=canonical_json(packet),
                proof_json=canonical_json(proof),
                proof_height=height,
            ))
            n += 1
        return n

    def step(self) -> dict:
        """One relay pass in both directions. Delivery txs enter the
        mempools; the caller drives block production (or consensus does,
        on validator nodes) and calls step() again — acks for this pass's
        packets settle on the NEXT pass, after they commit."""
        return {
            "recv_a_to_b": self._relay_packets(self.a, self.b),
            "recv_b_to_a": self._relay_packets(self.b, self.a),
            "acks_to_a": self._relay_acks(self.a, self.b),
            "acks_to_b": self._relay_acks(self.b, self.a),
            "timeouts_to_a": self._relay_timeouts(self.a, self.b),
            "timeouts_to_b": self._relay_timeouts(self.b, self.a),
        }
