"""IBC relayer: automatic packet + ack settlement between two chains.

The reference ecosystem delegates relaying to an external daemon
(hermes/rly): watch chain A for send_packet events, update chain B's
light client, submit MsgRecvPacket with a membership proof, then carry
the written acknowledgement back to A the same way. This module is that
daemon for two instances of THIS framework, speaking only public
surfaces — committed tx events (ibc-go's event-sourcing reality: the
chain stores only commitment hashes), `store.prove` for the membership
proofs, and ordinary signed transactions for delivery.

Idempotent by construction — no local database: a packet is pending-recv
iff the destination has no ack recorded for it, and pending-ack-settle
iff the source still holds its commitment (take_commitment deletes it on
settlement). A crashed-and-restarted relayer re-derives exactly the
remaining work from chain state.
"""

from __future__ import annotations

import dataclasses
import json

from celestia_app_tpu.chain.ibc import ChannelKeeper
from celestia_app_tpu.chain.state import (
    Context,
    InfiniteGasMeter,
    canonical_json,
)
from celestia_app_tpu.chain.tx import (
    MsgAcknowledgePacket,
    MsgRecvPacket,
    MsgTimeoutPacket,
    MsgUpdateClient,
)


@dataclasses.dataclass
class ChainHandle:
    """One side of the relay: an in-process node + a funded relayer key.

    `client_id` is the IBC client ON THIS CHAIN that tracks the
    counterparty. `scan_heights` caps how far back events are re-read
    each step (committed results are pruned node-side anyway)."""

    node: object  # Node or ValidatorNode (broadcast_tx/produce-capable)
    signer: object  # client.tx_client.Signer with the relayer account
    relayer: bytes  # 20-byte relayer address
    client_id: str

    @property
    def app(self):
        return self.node.app

    def ctx(self) -> Context:
        return Context(self.app.store, InfiniteGasMeter(), self.app.height,
                       0, self.app.chain_id, self.app.app_version)


def _commit_key(packet: dict) -> bytes:
    return (
        ChannelKeeper.COMMIT
        + f"{packet['source_port']}/{packet['source_channel']}/"
          f"{packet['sequence']}".encode()
    )


def _ack_key(packet: dict) -> bytes:
    return (
        ChannelKeeper.ACK
        + f"{packet['destination_port']}/{packet['destination_channel']}/"
          f"{packet['sequence']}".encode()
    )


class Relayer:
    """Bidirectional relayer over two ChainHandles."""

    def __init__(self, a: ChainHandle, b: ChainHandle):
        self.a = a
        self.b = b
        # heights already SUBMITTED (possibly uncommitted) per client: the
        # committed latest_height lags the mempool within one pass, and a
        # duplicate same-height MsgUpdateClient deterministically fails
        # the monotonicity check, burning the fee for nothing
        self._submitted_updates: dict[str, int] = {}

    # -- event sourcing --------------------------------------------------

    def _events(self, h: ChainHandle, type_: str) -> list[dict]:
        out = []
        for _txhash, (_height, res) in sorted(
            h.node.committed.items(), key=lambda kv: kv[1][0]
        ):
            if res.code != 0:
                continue
            for ev in res.events:
                if ev.get("type") == type_:
                    out.append(ev)
        return out

    def _pending_packets(self, src: ChainHandle,
                         dst: ChainHandle) -> list[dict]:
        """Packets src committed that dst has not acknowledged yet —
        excluding expired ones (hermes refuses to deliver past the
        timeout; the timeout pass settles those instead)."""
        pending = []
        for ev in self._events(src, "send_packet"):
            packet = json.loads(ev["packet_json"])
            timeout = int(packet.get("timeout_height") or 0)
            if timeout and dst.app.height >= timeout:
                continue
            if dst.app.ibc.channels.get_ack(dst.ctx(), packet) is None:
                pending.append(packet)
        return pending

    def _expired_packets(self, src: ChainHandle,
                         dst: ChainHandle) -> list[dict]:
        """src's packets whose timeout height has passed on dst with no
        ack ever written — the set MsgTimeout settles (refund)."""
        out = []
        for ev in self._events(src, "send_packet"):
            packet = json.loads(ev["packet_json"])
            timeout = int(packet.get("timeout_height") or 0)
            if timeout <= 0 or dst.app.height < timeout:
                continue
            if src.app.store.get(_commit_key(packet)) is None:
                continue  # already settled (ack or prior timeout)
            if dst.app.ibc.channels.get_ack(dst.ctx(), packet) is not None:
                continue  # received in time: the ack pass settles it
            out.append(packet)
        return out

    def _unsettled_acks(self, src: ChainHandle,
                        dst: ChainHandle) -> list[tuple[dict, dict]]:
        """(packet, ack) pairs dst wrote whose commitment still sits on
        src (i.e. the ack has not settled back)."""
        out = []
        for ev in self._events(dst, "write_acknowledgement"):
            packet = json.loads(ev["packet_json"])
            if src.app.store.get(_commit_key(packet)) is not None:
                out.append((packet, json.loads(ev["ack_json"])))
        return out

    # -- client updates --------------------------------------------------

    def _update_client(self, viewer: ChainHandle,
                       viewed: ChainHandle) -> int:
        """Record `viewed`'s latest committed root on `viewer`'s client —
        as a CONSENSUS TX (MsgUpdateClient), never a direct keeper write:
        on a replicated `viewer` chain, node-local client state would
        fork validators. Sequenced from the same relayer account as the
        recv/ack that follows, so it executes first. Root-based (say-so)
        updates here; a VERIFYING client additionally needs the header/
        cert/valset JSON payloads the msg carries (wire them from a
        light-client follower when the viewed chain runs one)."""
        height = viewed.app.height
        root = viewed.app.last_app_hash
        known = viewer.app.ibc.clients.latest_height(
            viewer.ctx(), viewer.client_id
        )
        if known is not None and known >= height:
            return known  # already recorded — prove at that height
        if self._submitted_updates.get(viewer.client_id, -1) >= height:
            return height  # update already in this pass's mempool
        self._submit(viewer, MsgUpdateClient(
            relayer=viewer.relayer,
            client_id=viewer.client_id,
            height=height,
            root=root,
        ), gas=200_000)
        self._submitted_updates[viewer.client_id] = height
        return height

    # -- delivery --------------------------------------------------------

    def _submit(self, h: ChainHandle, msg, gas: int = 500_000) -> None:
        tx = h.signer.create_tx(h.relayer, [msg], fee=2000, gas_limit=gas)
        res = h.node.broadcast_tx(tx.encode())
        if res.code != 0:
            raise RuntimeError(f"relay tx rejected: {res.log}")
        h.signer.accounts[h.relayer].sequence += 1

    def _relay_packets(self, src: ChainHandle, dst: ChainHandle) -> int:
        n = 0
        for packet in self._pending_packets(src, dst):
            height = self._update_client(dst, src)
            proof = src.app.store.prove(_commit_key(packet))
            self._submit(dst, MsgRecvPacket(
                relayer=dst.relayer,
                packet_json=canonical_json(packet),
                proof_json=canonical_json(proof),
                proof_height=height,
            ))
            n += 1
        return n

    def _relay_acks(self, src: ChainHandle, dst: ChainHandle) -> int:
        """Settle on `src` the acks `dst` wrote for src's packets."""
        n = 0
        for packet, ack in self._unsettled_acks(src, dst):
            height = self._update_client(src, dst)
            proof = dst.app.store.prove(_ack_key(packet))
            self._submit(src, MsgAcknowledgePacket(
                relayer=src.relayer,
                packet_json=canonical_json(packet),
                ack_json=canonical_json(ack),
                proof_json=canonical_json(proof),
                proof_height=height,
            ))
            n += 1
        return n

    def _relay_timeouts(self, src: ChainHandle, dst: ChainHandle) -> int:
        """Refund src's expired packets: client view advanced past the
        timeout height, plus an ABSENCE proof that dst never wrote the
        ack (the receipt-absence gate in chain/ibc.timeout_packet)."""
        n = 0
        for packet in self._expired_packets(src, dst):
            height = self._update_client(src, dst)
            if height < int(packet["timeout_height"]):
                continue  # client not past expiry yet; next pass
            proof = dst.app.store.prove_absence(_ack_key(packet))
            self._submit(src, MsgTimeoutPacket(
                relayer=src.relayer,
                packet_json=canonical_json(packet),
                proof_json=canonical_json(proof),
                proof_height=height,
            ))
            n += 1
        return n

    def step(self) -> dict:
        """One relay pass in both directions. Delivery txs enter the
        mempools; the caller drives block production (or consensus does,
        on validator nodes) and calls step() again — acks for this pass's
        packets settle on the NEXT pass, after they commit."""
        return {
            "recv_a_to_b": self._relay_packets(self.a, self.b),
            "recv_b_to_a": self._relay_packets(self.b, self.a),
            "acks_to_a": self._relay_acks(self.a, self.b),
            "acks_to_b": self._relay_acks(self.b, self.a),
            "timeouts_to_a": self._relay_timeouts(self.a, self.b),
            "timeouts_to_b": self._relay_timeouts(self.b, self.a),
        }
