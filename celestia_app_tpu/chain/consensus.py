"""Multi-node consensus coordination: votes, gossip, WAL replay, state sync.

The reference delegates this plane to celestia-core (Tendermint: p2p gossip
of txs/proposals/votes, the write-ahead log replayed on crash recovery
(app/app.go:435 LoadLatestVersion + WAL), and state-sync snapshots serving
fast bootstrap (default_overrides.go:294-297)). This module coordinates
N validator instances of THIS framework the same way, with an in-process
message bus standing in for TCP gossip (the single-container analog of
test/util/testnode's real-node network):

- **Proposals + votes**: the height's proposer (round-robin by voting
  power order) runs PrepareProposal; every validator independently replays
  it through ProcessProposal and casts a SIGNED prevote for the block hash
  (or nil on rejection). ≥2/3 of voting power on the same hash forms a
  commit certificate; every node then finalizes + commits the identical
  block and must land on the identical app hash (divergence raises).
- **Commit certificates** are persisted with each height and verifiable
  offline: height, block hash, and the validators' signatures over the
  canonical vote bytes.
- **WAL**: each node appends {proposal, votes} to a height-keyed JSON WAL
  BEFORE applying the block; a node that crashed between WAL write and
  commit replays the WAL entry on restart and converges without re-running
  consensus (Tendermint's replay semantics).
- **State sync**: a fresh node bootstraps from a peer by fetching snapshot
  CHUNKS (the peer's committed store in deterministic key-ranged pieces),
  verifying the reassembled store's app hash against the trusted header's
  app_hash before adopting it — a wrong/altered chunk set is rejected.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os

from celestia_app_tpu.chain.app import App
from celestia_app_tpu.chain.block import Block, Header
from celestia_app_tpu.chain.crypto import PrivateKey, PublicKey
from celestia_app_tpu.chain.state import Context, InfiniteGasMeter


@dataclasses.dataclass(frozen=True)
class Vote:
    height: int
    block_hash: bytes | None  # None = nil vote (proposal rejected)
    validator: bytes  # 20-byte operator address
    signature: bytes

    @staticmethod
    def sign_bytes(chain_id: str, height: int, block_hash: bytes | None) -> bytes:
        doc = {
            "chain_id": chain_id,
            "height": height,
            "block_hash": block_hash.hex() if block_hash else None,
            "type": "precommit",
        }
        return json.dumps(doc, sort_keys=True).encode()


@dataclasses.dataclass(frozen=True)
class CommitCertificate:
    height: int
    block_hash: bytes
    votes: tuple[Vote, ...]

    def verify(self, chain_id: str, validators: dict[bytes, bytes],
               total_power: int, powers: dict[bytes, int]) -> bool:
        """Check ≥2/3 of `total_power` signed this block hash. `validators`
        maps operator address -> 33-byte pubkey."""
        signed = 0
        seen: set[bytes] = set()
        doc = Vote.sign_bytes(chain_id, self.height, self.block_hash)
        for v in self.votes:
            if v.validator in seen or v.block_hash != self.block_hash:
                continue
            pub = validators.get(v.validator)
            if pub is None or PublicKey(pub).address() != v.validator:
                continue
            if not PublicKey(pub).verify(v.signature, doc):
                continue
            seen.add(v.validator)
            signed += powers.get(v.validator, 0)
        # STRICTLY more than 2/3 (Tendermint): at exactly 2/3, two
        # conflicting certificates could overlap in only 1/3 of power —
        # all of it byzantine — losing the accountability guarantee
        return signed * 3 > total_power * 2


class ValidatorNode:
    """One validator: an App + key + mempool + WAL."""

    def __init__(self, name: str, priv: PrivateKey, genesis: dict,
                 chain_id: str, data_dir: str | None = None):
        self.name = name
        self.priv = priv
        self.address = priv.public_key().address()
        self.app = App(chain_id=chain_id, engine="host", data_dir=data_dir)
        self.app.init_chain(genesis)
        self.mempool: list[bytes] = []
        self.wal_dir = os.path.join(data_dir, "wal") if data_dir else None
        if self.wal_dir:
            os.makedirs(self.wal_dir, exist_ok=True)
        self.certificates: dict[int, CommitCertificate] = {}
        # consensus pubkeys ride the genesis doc (Tendermint genesis
        # validators carry pub_key the same way) so a rebooted node can
        # verify WAL'd certificate votes without any peer alive
        self.validator_pubkeys: dict[bytes, bytes] = {
            bytes.fromhex(v["operator"]): bytes.fromhex(v["pubkey"])
            for v in genesis.get("validators", [])
            if "pubkey" in v
        }

    # -- mempool (gossiped) ---------------------------------------------

    def add_tx(self, raw: bytes) -> bool:
        res = self.app.check_tx(raw)
        if res.code == 0:
            self.mempool.append(raw)
            return True
        return False

    # -- consensus steps -------------------------------------------------

    def propose(self, t: float):
        prop = self.app.prepare_proposal(self.mempool, proposer=self.address, t=t)
        return prop.block

    def vote_on(self, block: Block) -> Vote:
        ok = self.app.process_proposal(block)
        bh = block.header.hash() if ok else None
        sig = self.priv.sign(
            Vote.sign_bytes(self.app.chain_id, block.header.height, bh)
        )
        return Vote(block.header.height, bh, self.address, sig)

    def _wal_path(self, height: int) -> str:
        return os.path.join(self.wal_dir, f"{height:020d}.json")

    def write_wal(
        self, block: Block, cert: CommitCertificate,
        evidence: tuple["DuplicateVoteEvidence", ...] = (),
    ) -> None:
        """Append-before-apply: the crash-recovery record. Evidence applied
        with the block is PART of the record — replay must re-apply it or
        the replayed app hash diverges from live peers."""
        if self.wal_dir is None:
            return
        import base64

        doc = {
            "evidence": [
                {
                    "height": ev.height,
                    "votes": [
                        {
                            "block_hash": v.block_hash.hex(),
                            "validator": v.validator.hex(),
                            "signature": v.signature.hex(),
                        }
                        for v in (ev.vote_a, ev.vote_b)
                    ],
                }
                for ev in evidence
            ],
            "height": block.header.height,
            "header": {
                "chain_id": block.header.chain_id,
                "height": block.header.height,
                "time_unix": block.header.time_unix,
                "data_hash": block.header.data_hash.hex(),
                "square_size": block.header.square_size,
                "app_hash": block.header.app_hash.hex(),
                "proposer": block.header.proposer.hex(),
                "app_version": block.header.app_version,
                "last_block_hash": block.header.last_block_hash.hex(),
            },
            "txs": [base64.b64encode(tx).decode() for tx in block.txs],
            "votes": [
                {
                    "height": v.height,
                    "block_hash": v.block_hash.hex() if v.block_hash else None,
                    "validator": v.validator.hex(),
                    "signature": v.signature.hex(),
                }
                for v in cert.votes
            ],
        }
        tmp = self._wal_path(block.header.height) + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._wal_path(block.header.height))

    def _mark_absent_from_votes(self, cert: CommitCertificate) -> None:
        """LastCommitInfo reconstruction shared by the live commit path and
        WAL replay: a validator counts as present only with a precommit FOR
        the committed block at the certificate's height — a vote for a
        different block / stale height / junk signature is an absence, so
        misbehaving validators cannot suppress their own liveness window.
        Each vote's signature is checked against the genesis-known validator
        pubkeys (mirroring cert.verify), so a cert padded with forged
        presence-votes for offline validators cannot suppress their
        downtime accounting; a validator with no genesis pubkey (legacy
        fixture genesis) falls back to unverified matching."""
        doc = Vote.sign_bytes(self.app.chain_id, cert.height, cert.block_hash)
        voted = set()
        for v in cert.votes:
            if v.block_hash != cert.block_hash or v.height != cert.height:
                continue
            pub = self.validator_pubkeys.get(v.validator)
            if pub is not None and not PublicKey(pub).verify(v.signature, doc):
                continue
            voted.add(v.validator)
        ctx = Context(
            self.app.store, InfiniteGasMeter(), self.app.height, 0,
            self.app.chain_id, self.app.app_version,
        )
        self.app.absent_validators = {
            op for op, _p in self.app.staking.validators(ctx)
            if op not in voted
        }

    def _apply_evidence(
        self, evidence: tuple["DuplicateVoteEvidence", ...]
    ) -> None:
        for ev in evidence:
            ctx = Context(
                self.app.store, InfiniteGasMeter(), self.app.height, 0,
                self.app.chain_id, self.app.app_version,
            )
            self.app.slashing.handle_equivocation(
                ctx, ev.vote_a.validator, infraction_height=ev.height
            )

    def apply(
        self, block: Block, cert: CommitCertificate,
        evidence: tuple["DuplicateVoteEvidence", ...] = (),
    ) -> bytes:
        """Finalize + commit a certified block (evidence first — the
        x/evidence BeginBlock position); returns the app hash. Evidence is
        in the WAL record, so crash replay re-applies it identically.

        LastCommitInfo analog: validators whose precommit is absent from
        the certificate are marked absent, consumed by THIS block's
        BeginBlock liveness accounting (one height earlier than Tendermint
        wires LastCommitInfo, which carries height H's commit into H+1 —
        deterministic either way since every node applies the same
        certificate in the same order)."""
        self.write_wal(block, cert, evidence)
        self._apply_evidence(evidence)
        # ordering invariant shared with replay_wal: evidence FIRST, then
        # absences — both paths must compute the absent set against the
        # same post-evidence validator set or replayed nodes diverge
        self._mark_absent_from_votes(cert)
        self.app.finalize_block(block)
        app_hash = self.app.commit(block)
        self.certificates[block.header.height] = cert
        committed = {tx for tx in block.txs}
        self.mempool = [tx for tx in self.mempool if tx not in committed]
        return app_hash

    def replay_wal(self) -> int:
        """Crash recovery: apply WAL entries above the committed height
        (Tendermint replay). Returns how many blocks were replayed."""
        if self.wal_dir is None:
            return 0
        import base64

        replayed = 0
        for name in sorted(os.listdir(self.wal_dir)):
            if not name.endswith(".json"):
                continue
            height = int(name.split(".")[0])
            if height <= self.app.height:
                continue
            with open(os.path.join(self.wal_dir, name)) as f:
                doc = json.load(f)
            hd = doc["header"]
            block = Block(
                header=Header(
                    chain_id=hd["chain_id"],
                    height=hd["height"],
                    time_unix=hd["time_unix"],
                    data_hash=bytes.fromhex(hd["data_hash"]),
                    square_size=hd["square_size"],
                    app_hash=bytes.fromhex(hd["app_hash"]),
                    proposer=bytes.fromhex(hd["proposer"]),
                    app_version=hd["app_version"],
                    last_block_hash=bytes.fromhex(hd["last_block_hash"]),
                ),
                txs=[base64.b64decode(t) for t in doc["txs"]],
            )
            votes = tuple(
                Vote(
                    v["height"],
                    bytes.fromhex(v["block_hash"]) if v["block_hash"] else None,
                    bytes.fromhex(v["validator"]),
                    bytes.fromhex(v["signature"]),
                )
                for v in doc["votes"]
            )
            cert = CommitCertificate(height, block.header.hash(), votes)
            evidence = tuple(
                DuplicateVoteEvidence(
                    e["height"],
                    *[
                        Vote(
                            e["height"],
                            bytes.fromhex(v["block_hash"]),
                            bytes.fromhex(v["validator"]),
                            bytes.fromhex(v["signature"]),
                        )
                        for v in e["votes"]
                    ],
                )
                for e in doc.get("evidence", [])
            )
            self._apply_evidence(evidence)
            # reconstruct the LastCommitInfo absences from the WAL's cert so
            # the replayed liveness accounting matches the live run (same
            # evidence-then-absences order as apply())
            self._mark_absent_from_votes(cert)
            self.app.finalize_block(block)
            self.app.commit(block)
            self.certificates[height] = cert
            replayed += 1
        return replayed

    # -- state sync (serving side) ---------------------------------------

    def snapshot_chunks(self) -> tuple[dict, list[bytes]]:
        return snapshot_app_chunks(self.app)


SNAPSHOT_CHUNK_KEYS = 64


def snapshot_app_chunks(app: App) -> tuple[dict, list[bytes]]:
    """(manifest, chunks): the committed store split into deterministic
    key-ranged chunks (state-sync serving, default_overrides.go:294)."""
    items = sorted(app.store.snapshot().items())
    chunks: list[bytes] = []
    for i in range(0, max(len(items), 1), SNAPSHOT_CHUNK_KEYS):
        part = items[i : i + SNAPSHOT_CHUNK_KEYS]
        chunks.append(
            json.dumps(
                [[k.hex(), v.hex()] for k, v in part], sort_keys=True
            ).encode()
        )
    manifest = {
        "height": app.height,
        "app_hash": app.last_app_hash.hex(),
        "app_version": app.app_version,
        "chain_id": app.chain_id,
        "genesis_time": app.genesis_time,
        "last_block_hash": app.last_block_hash.hex(),
        "n_chunks": len(chunks),
        "chunk_hashes": [hashlib.sha256(c).hexdigest() for c in chunks],
    }
    return manifest, chunks


def state_sync_bootstrap(node_or_app, manifest: dict, chunks: list[bytes]) -> None:
    """Adopt a snapshot AFTER verification: every chunk must match the
    manifest hash, and the reassembled store's app hash must equal the
    trusted header's app_hash — altered chunks are rejected wholesale.
    Accepts a ValidatorNode or a bare App."""
    app = getattr(node_or_app, "app", node_or_app)
    if len(chunks) != manifest["n_chunks"]:
        raise ValueError("chunk count mismatch")
    for i, c in enumerate(chunks):
        if hashlib.sha256(c).hexdigest() != manifest["chunk_hashes"][i]:
            raise ValueError(f"chunk {i} hash mismatch")
    data: dict[bytes, bytes] = {}
    for c in chunks:
        for k_hex, v_hex in json.loads(c):
            data[bytes.fromhex(k_hex)] = bytes.fromhex(v_hex)
    from celestia_app_tpu.chain.state import KVStore

    probe = KVStore(data)
    if probe.app_hash().hex() != manifest["app_hash"]:
        raise ValueError("snapshot app hash does not match trusted header")
    app.store.restore(data)
    app.height = manifest["height"]
    app.app_version = manifest["app_version"]
    app.last_app_hash = bytes.fromhex(manifest["app_hash"])
    app.last_block_hash = bytes.fromhex(manifest["last_block_hash"])
    app.genesis_time = manifest["genesis_time"]
    app._check_state = None


@dataclasses.dataclass(frozen=True)
class DuplicateVoteEvidence:
    """Two signed votes by the same validator for DIFFERENT blocks at one
    height — the Tendermint double-sign evidence type. Verifiable offline
    (both signatures check out against the validator's key), and submitted
    to x/evidence → tombstone + slash (sdk_modules.handle_equivocation)."""

    height: int
    vote_a: Vote
    vote_b: Vote

    def verify(self, chain_id: str, pubkey: bytes) -> bool:
        a, b = self.vote_a, self.vote_b
        if a.validator != b.validator:
            return False
        if a.height != self.height or b.height != self.height:
            return False  # both votes must be AT the evidence height
        if a.block_hash == b.block_hash:
            return False  # same block: not equivocation
        pub = PublicKey(pubkey)
        if pub.address() != a.validator:
            return False
        return pub.verify(
            a.signature, Vote.sign_bytes(chain_id, a.height, a.block_hash)
        ) and pub.verify(
            b.signature, Vote.sign_bytes(chain_id, b.height, b.block_hash)
        )


def detect_equivocation(
    chain_id: str, votes_by_round: list[list[Vote]],
    validators: dict[bytes, bytes],
) -> list[DuplicateVoteEvidence]:
    """Scan one height's votes (across rounds) for validators that signed
    two different block hashes; returns verified evidence only."""
    seen: dict[tuple[bytes, int], Vote] = {}  # (validator, height) -> vote
    out: list[DuplicateVoteEvidence] = []
    accused: set[bytes] = set()
    for votes in votes_by_round:
        for v in votes:
            if v.block_hash is None or v.validator in accused:
                continue
            key = (v.validator, v.height)
            prior = seen.get(key)
            if prior is None:
                seen[key] = v
            elif prior.block_hash != v.block_hash:
                ev = DuplicateVoteEvidence(v.height, prior, v)
                pub = validators.get(v.validator)
                if pub is not None and ev.verify(chain_id, pub):
                    out.append(ev)
                    accused.add(v.validator)
    return out


class LocalNetwork:
    """N validators + an in-process gossip bus (tx fan-out, proposal/vote
    exchange). Proposer rotation is deterministic round-robin over the
    address-sorted validator set. Votes from failed rounds are retained
    per height and scanned for equivocation; verified double-sign evidence
    is submitted to every node's x/evidence handler (tombstone + slash)."""

    def __init__(self, nodes: list[ValidatorNode]):
        if not nodes:
            raise ValueError("need at least one validator")
        self.nodes = sorted(nodes, key=lambda n: n.address)
        self.chain_id = nodes[0].app.chain_id
        # every member learns every member's consensus pubkey (gossiped at
        # handshake in real p2p); genesis-carried keys take precedence
        peer_keys = {n.address: n.priv.public_key().compressed for n in nodes}
        for n in self.nodes:
            n.validator_pubkeys = {**peer_keys, **n.validator_pubkeys}
        self._round = 0  # advances on failed rounds so the proposer rotates
        # signature-verified votes retained for the evidence window, so a
        # conflicting vote surfacing a few heights late still pairs up
        # (Tendermint's evidence max-age analog)
        self._vote_pool: list[Vote] = []

    EVIDENCE_MAX_AGE = 10  # heights a vote stays eligible for evidence

    def _powers(self, app: App) -> dict[bytes, int]:
        ctx = Context(app.store, InfiniteGasMeter(), app.height, 0,
                      app.chain_id, app.app_version)
        return dict(app.staking.validators(ctx))

    def broadcast_tx(self, raw: bytes) -> bool:
        """Gossip: every node's mempool sees the tx (first node's CheckTx
        verdict is authoritative for the caller)."""
        results = [n.add_tx(raw) for n in self.nodes]
        return results[0]

    def proposer_for(self, height: int, round_: int = 0) -> ValidatorNode:
        return self.nodes[(height + round_) % len(self.nodes)]

    def produce_height(self, t: float) -> tuple[Block | None, CommitCertificate | None]:
        """One consensus round. Returns (block, certificate) on commit, or
        (None, None) when the proposal failed to reach >2/3 — the round
        counter then advances, so the NEXT call rotates past a faulty
        proposer instead of retrying it forever (Tendermint round schedule)."""
        height = self.nodes[0].app.height + 1
        proposer = self.proposer_for(height, self._round)
        block = proposer.propose(t)
        votes = tuple(n.vote_on(block) for n in self.nodes)
        self._vote_pool.extend(v for v in votes if v.block_hash is not None)
        self._prune_vote_pool(height)
        bh = block.header.hash()
        powers = self._powers(self.nodes[0].app)
        total = sum(powers.values())
        cert = CommitCertificate(height, bh, votes)
        validators = {
            n.address: n.priv.public_key().compressed for n in self.nodes
        }
        if not cert.verify(self.chain_id, validators, total, powers):
            self._round += 1
            return None, None
        self._round = 0
        # evidence rides the committed block (the x/evidence position):
        # deterministic across nodes and recorded in every WAL entry
        evidence = tuple(
            detect_equivocation(self.chain_id, [self._vote_pool], validators)
        )
        if evidence:
            punished = {ev.vote_a.validator for ev in evidence}
            self._vote_pool = [
                v for v in self._vote_pool if v.validator not in punished
            ]
        hashes = {n.apply(block, cert, evidence) for n in self.nodes}
        if len(hashes) != 1:
            raise AssertionError(
                f"state divergence after height {height}: {sorted(h.hex() for h in hashes)}"
            )
        return block, cert

    def _prune_vote_pool(self, current_height: int) -> None:
        floor = current_height - self.EVIDENCE_MAX_AGE
        self._vote_pool = [v for v in self._vote_pool if v.height > floor]

    def inject_vote(self, vote: Vote) -> None:
        """Gossip entry for an externally-received vote. The signature is
        verified AT THE DOOR against the known validator set — a forged
        vote must never enter the pool, where it could poison the
        first-seen slot and mask real equivocation."""
        by_addr = {n.address: n.priv.public_key().compressed for n in self.nodes}
        pub = by_addr.get(vote.validator)
        if pub is None or vote.block_hash is None:
            raise ValueError("vote from unknown validator or nil vote")
        if not PublicKey(pub).verify(
            vote.signature,
            Vote.sign_bytes(self.chain_id, vote.height, vote.block_hash),
        ):
            raise ValueError("vote signature verification failed")
        self._vote_pool.append(vote)
