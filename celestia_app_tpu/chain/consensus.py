"""Multi-node consensus coordination: votes, gossip, WAL replay, state sync.

The reference delegates this plane to celestia-core (Tendermint: p2p gossip
of txs/proposals/votes, the write-ahead log replayed on crash recovery
(app/app.go:435 LoadLatestVersion + WAL), and state-sync snapshots serving
fast bootstrap (default_overrides.go:294-297)). This module coordinates
N validator instances of THIS framework the same way, with an in-process
message bus standing in for TCP gossip (the single-container analog of
test/util/testnode's real-node network):

- **Proposals + votes**: the height's proposer (round-robin by voting
  power order) runs PrepareProposal; every validator independently replays
  it through ProcessProposal and casts a SIGNED prevote for the block hash
  (or nil on rejection). ≥2/3 of voting power on the same hash forms a
  commit certificate; every node then finalizes + commits the identical
  block and must land on the identical app hash (divergence raises).
- **Commit certificates** are persisted with each height and verifiable
  offline: height, block hash, and the validators' signatures over the
  canonical vote bytes.
- **WAL**: each node appends {proposal, votes} to a height-keyed JSON WAL
  BEFORE applying the block; a node that crashed between WAL write and
  commit replays the WAL entry on restart and converges without re-running
  consensus (Tendermint's replay semantics).
- **State sync**: a fresh node bootstraps from a peer by fetching snapshot
  CHUNKS (the peer's committed store in deterministic key-ranged pieces),
  verifying the reassembled store's app hash against the trusted header's
  app_hash before adopting it — a wrong/altered chunk set is rejected.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os

from celestia_app_tpu import faults
from celestia_app_tpu import obs
from celestia_app_tpu.chain.app import App
from celestia_app_tpu.chain.block import Block, Header
from celestia_app_tpu.chain.crypto import PrivateKey, PublicKey
from celestia_app_tpu.chain.state import Context, InfiniteGasMeter
from celestia_app_tpu.utils import telemetry


@dataclasses.dataclass(frozen=True)
class Vote:
    height: int
    block_hash: bytes | None  # None = nil vote (proposal rejected)
    validator: bytes  # 20-byte operator address
    signature: bytes
    phase: str = "precommit"  # "prevote" | "precommit" (Tendermint steps)
    round: int = 0

    @staticmethod
    def sign_bytes(
        chain_id: str, height: int, block_hash: bytes | None,
        phase: str = "precommit", round_: int = 0,
    ) -> bytes:
        """The signed vote document. It commits to (chain_id, height,
        ROUND, block hash, phase) — Tendermint's CanonicalVote fields
        (celestia-core types/vote.go) — so a relayed old-round vote can
        never be replayed into a newer round, per-round attribution is
        exact, and same-round duplicate votes (either phase) are the
        slashable double-sign."""
        doc = {
            "chain_id": chain_id,
            "height": height,
            "round": round_,
            "block_hash": block_hash.hex() if block_hash else None,
            "type": phase,
        }
        return json.dumps(doc, sort_keys=True).encode()


@dataclasses.dataclass(frozen=True)
class CommitCertificate:
    height: int
    block_hash: bytes
    votes: tuple[Vote, ...]
    # The commit round (Tendermint Commit.Round): every counted precommit
    # must be FROM this round. Cross-round aggregation would void the
    # textbook safety proof once unlock-on-higher-polka lets an honest
    # validator legally precommit different hashes in different rounds.
    round: int = 0

    def signed_power(self, chain_id: str, validators: dict[bytes, bytes],
                     powers: dict[bytes, int]) -> int:
        """THE vote-counting core: total power of distinct validators whose
        precommit signature over THIS (height, round, block_hash) verifies
        against `validators` (operator address -> 33-byte pubkey; the pubkey
        must derive the address). Shared by certificate verification, the
        light client's 2/3 and 1/3-overlap checks (chain/light.py), and the
        IBC verifying client — one hardening fix reaches every consumer."""
        signed = 0
        seen: set[bytes] = set()
        doc = Vote.sign_bytes(chain_id, self.height, self.block_hash,
                              round_=self.round)
        for v in self.votes:
            if (v.validator in seen or v.block_hash != self.block_hash
                    or v.height != self.height or v.phase != "precommit"
                    or v.round != self.round):
                continue
            pub = validators.get(v.validator)
            if pub is None or PublicKey(pub).address() != v.validator:
                continue
            if not PublicKey(pub).verify(v.signature, doc):
                continue
            seen.add(v.validator)
            signed += powers.get(v.validator, 0)
        return signed

    def verify(self, chain_id: str, validators: dict[bytes, bytes],
               total_power: int, powers: dict[bytes, int]) -> bool:
        """Check ≥2/3 of `total_power` signed this block hash.

        STRICTLY more than 2/3 (Tendermint): at exactly 2/3, two
        conflicting certificates could overlap in only 1/3 of power —
        all of it byzantine — losing the accountability guarantee."""
        return self.signed_power(chain_id, validators, powers) * 3 > total_power * 2


# ---------------------------------------------------------------------------
# JSON codecs for consensus types: one definition shared by the WAL record,
# the socket wire (service/validator_server.py), and state-sync manifests —
# divergent encodings would let a replayed WAL disagree with live peers.
# ---------------------------------------------------------------------------


def header_to_json(h: Header) -> dict:
    doc = {
        "chain_id": h.chain_id,
        "height": h.height,
        "time_unix": h.time_unix,
        "data_hash": h.data_hash.hex(),
        "square_size": h.square_size,
        "app_hash": h.app_hash.hex(),
        "proposer": h.proposer.hex(),
        "app_version": h.app_version,
        "last_block_hash": h.last_block_hash.hex(),
        "validators_hash": h.validators_hash.hex(),
    }
    if h.da_scheme:
        # emitted only for non-default schemes: default-scheme docs stay
        # byte-identical to pre-codec-plane ones (WAL/socket/snapshot
        # encodings are shared — FORMATS §16.1)
        doc["da_scheme"] = h.da_scheme
    return doc


def header_from_json(d: dict) -> Header:
    return Header(
        chain_id=d["chain_id"],
        height=d["height"],
        time_unix=d["time_unix"],
        data_hash=bytes.fromhex(d["data_hash"]),
        square_size=d["square_size"],
        app_hash=bytes.fromhex(d["app_hash"]),
        proposer=bytes.fromhex(d["proposer"]),
        app_version=d["app_version"],
        last_block_hash=bytes.fromhex(d["last_block_hash"]),
        # STRICT: a header doc without the validators_hash commitment is
        # from a pre-commitment encoding whose block hash no longer matches
        # this code — failing loudly here beats silently re-hashing it to a
        # value none of its stored votes cover
        validators_hash=bytes.fromhex(d["validators_hash"]),
        # absent ⇒ 0 = 2D-RS+NMT (the codec plane's back-compat rule)
        da_scheme=d.get("da_scheme", 0),
    )


def block_to_json(b: Block) -> dict:
    import base64

    return {
        "header": header_to_json(b.header),
        "txs": [base64.b64encode(tx).decode() for tx in b.txs],
    }


def block_from_json(d: dict) -> Block:
    import base64

    return Block(
        header=header_from_json(d["header"]),
        txs=[base64.b64decode(t) for t in d["txs"]],
    )


def vote_to_json(v: Vote) -> dict:
    return {
        "height": v.height,
        "block_hash": v.block_hash.hex() if v.block_hash else None,
        "validator": v.validator.hex(),
        "signature": v.signature.hex(),
        "phase": v.phase,
        "round": v.round,
    }


def vote_from_json(d: dict) -> Vote:
    return Vote(
        d["height"],
        bytes.fromhex(d["block_hash"]) if d["block_hash"] else None,
        bytes.fromhex(d["validator"]),
        bytes.fromhex(d["signature"]),
        d.get("phase", "precommit"),
        int(d.get("round", 0)),
    )


def cert_to_json(c: CommitCertificate) -> dict:
    return {
        "height": c.height,
        "block_hash": c.block_hash.hex(),
        "votes": [vote_to_json(v) for v in c.votes],
        "round": c.round,
    }


def cert_from_json(d: dict) -> CommitCertificate:
    return CommitCertificate(
        d["height"],
        bytes.fromhex(d["block_hash"]),
        tuple(vote_from_json(v) for v in d["votes"]),
        int(d.get("round", 0)),
    )


def evidence_to_json(ev: "DuplicateVoteEvidence") -> dict:
    return {
        "height": ev.height,
        "votes": [vote_to_json(v) for v in (ev.vote_a, ev.vote_b)],
    }


def evidence_from_json(d: dict) -> "DuplicateVoteEvidence":
    # pre-round-4 WAL evidence votes carried height only on the OUTER dict
    a, b = (
        vote_from_json({"height": d["height"], **v}) for v in d["votes"]
    )
    return DuplicateVoteEvidence(d["height"], a, b)


@dataclasses.dataclass(frozen=True)
class Proposal:
    """Signed proposal envelope for the autonomous (gossip) consensus mode.

    Tendermint proposals carry the block plus the consensus-critical
    commit-info the whole network must apply IDENTICALLY: the commit
    certificate for height-1 (LastCommitInfo — what liveness accounting
    reads) and the equivocation evidence for this height. In the
    orchestrated socket mode one coordinator picks those for everyone; in
    autonomous mode every node assembles its own certificate from gossip,
    so cert contents differ per node — the proposer's choice, committed to
    by this envelope's signature, is what keeps app hashes equal.

    The signature covers (chain_id, height, round, block hash, and a
    digest of last_cert+evidence), so a relaying peer cannot swap the
    commit-info under a real proposal.
    """

    height: int
    round: int
    block: Block
    proposer: bytes  # 20-byte operator address
    signature: bytes
    last_cert: CommitCertificate | None  # None only at height 1
    evidence: tuple["DuplicateVoteEvidence", ...] = ()

    @staticmethod
    def commit_info_digest(
        last_cert: CommitCertificate | None,
        evidence: tuple["DuplicateVoteEvidence", ...],
    ) -> bytes:
        doc = {
            "last_cert": cert_to_json(last_cert) if last_cert else None,
            "evidence": [evidence_to_json(e) for e in evidence],
        }
        return hashlib.sha256(
            json.dumps(doc, sort_keys=True).encode()
        ).digest()

    @staticmethod
    def sign_bytes(
        chain_id: str, height: int, round_: int, block_hash: bytes,
        info_digest: bytes,
    ) -> bytes:
        doc = {
            "chain_id": chain_id,
            "height": height,
            "round": round_,
            "block_hash": block_hash.hex(),
            "commit_info": info_digest.hex(),
            "type": "proposal",
        }
        return json.dumps(doc, sort_keys=True).encode()

    def verify(self, chain_id: str, pubkey: bytes) -> bool:
        doc = Proposal.sign_bytes(
            chain_id, self.height, self.round, self.block.header.hash(),
            Proposal.commit_info_digest(self.last_cert, self.evidence),
        )
        pk = PublicKey(pubkey)
        return pk.address() == self.proposer and pk.verify(
            self.signature, doc
        )


def proposal_to_json(p: Proposal) -> dict:
    return {
        "height": p.height,
        "round": p.round,
        "block": block_to_json(p.block),
        "proposer": p.proposer.hex(),
        "signature": p.signature.hex(),
        "last_cert": cert_to_json(p.last_cert) if p.last_cert else None,
        "evidence": [evidence_to_json(e) for e in p.evidence],
    }


def proposal_from_json(d: dict) -> Proposal:
    return Proposal(
        height=d["height"],
        round=d["round"],
        block=block_from_json(d["block"]),
        proposer=bytes.fromhex(d["proposer"]),
        signature=bytes.fromhex(d["signature"]),
        last_cert=cert_from_json(d["last_cert"]) if d["last_cert"] else None,
        evidence=tuple(evidence_from_json(e) for e in d["evidence"]),
    )


class ValidatorNode:
    """One validator: an App + key + mempool + WAL."""

    def __init__(self, name: str, priv: PrivateKey, genesis: dict,
                 chain_id: str, data_dir: str | None = None,
                 v2_upgrade_height: int | None = None,
                 upgrade_height_delay: int | None = None,
                 engine: str = "host",
                 da_scheme: str = "rs2d-nmt",
                 pack_keep: int | None = None,
                 max_square_size: int | None = None):
        self.name = name
        self.priv = priv
        self.address = priv.public_key().address()
        # engine="host" stays the validator default (a validator process
        # must not hang on a dead accelerator relay mid-consensus), but
        # device-engine validators are constructible now that the block
        # plane's EDS cache (da/edscache.py) is populated bit-identically
        # by both engines — a TPU proposer and a host follower land on
        # the same content-addressed entries and the same roots; a mesh
        # validator (engine="mesh") additionally keeps its entries
        # device-resident. max_square_size is the mesh plane's
        # consensus-critical k=256/512 admission override (chain/app.py).
        self.app = App(chain_id=chain_id, engine=engine, data_dir=data_dir,
                       v2_upgrade_height=v2_upgrade_height,
                       upgrade_height_delay=upgrade_height_delay,
                       da_scheme=da_scheme, pack_keep=pack_keep,
                       max_square_size=max_square_size)
        self.app.init_chain(genesis)
        # THE mempool: the shared CAT pool (celestia_app_tpu/mempool) —
        # the pre-CAT validator list grew unboundedly (no cap, no TTL) and
        # leaked per-tx metadata (_tx_meta) for any tx that never
        # committed; pool entries now carry their own metadata, so
        # lifetime follows membership by construction
        from celestia_app_tpu.mempool.pool import CATPool

        self.pool = CATPool()
        self.committed: dict[bytes, tuple[int, object]] = {}
        self.wal_dir = os.path.join(data_dir, "wal") if data_dir else None
        if self.wal_dir:
            os.makedirs(self.wal_dir, exist_ok=True)
        self.certificates: dict[int, CommitCertificate] = {}
        # lock-on-polka state (Tendermint lockedValue/lockedRound)
        self.locked_block: Block | None = None
        self.locked_round: int = -1
        # consensus pubkeys ride the genesis doc (Tendermint genesis
        # validators carry pub_key the same way) so a rebooted node can
        # verify WAL'd certificate votes without any peer alive
        self.validator_pubkeys: dict[bytes, bytes] = {
            bytes.fromhex(v["operator"]): bytes.fromhex(v["pubkey"])
            for v in genesis.get("validators", [])
            if "pubkey" in v
        }
        self._load_sign_state()

    # -- mempool (gossiped) ---------------------------------------------

    @property
    def mempool(self):
        """List-of-raw-bytes view over the CAT pool (the pre-CAT shape
        tests and status surfaces read)."""
        from celestia_app_tpu.mempool.pool import RawTxView

        return RawTxView(self.pool)

    @mempool.setter
    def mempool(self, items) -> None:
        """Compat for fixtures that assign a replacement list; re-admitted
        WITHOUT CheckTx (the caller vouches)."""
        self.pool.clear()
        for raw in items:
            self.pool.add(raw, height=self.app.height)

    def add_tx(self, raw: bytes):
        """CheckTx + CAT admission; returns the TxResult so transports
        (in-process bus, HTTP validator service, gRPC) share ONE admission
        path — the pool's byte gate (default_overrides.go:271-273),
        hash dedup (a duplicate submission returns the ORIGINAL result),
        and cap eviction included."""
        # mempool TTL stamp: the POOL's injected clock supplies it
        # (node-local state, never hashed) — SystemClock in production,
        # the scenario plane's VirtualClock under simulation
        return self.pool.add(raw, height=self.app.height,
                             check_fn=self.app.check_tx)

    def add_txs(self, raws) -> list:
        """Batched admission (admission plane phase 1 + per-tx CheckTx):
        an ingest burst pays ONE signature dispatch and ONE blob-
        commitment dispatch, not one of each per tx."""
        from celestia_app_tpu.chain import admission

        # TTL stamp comes from the pool's injected clock (see add_tx)
        return self.pool.add_batch(
            raws, height=self.app.height,
            check_fn=self.app.check_tx,
            prevalidate_fn=lambda rs: admission.prevalidate(
                self.app, rs, check_state=True),
        )

    def prevalidate_txs(self, raws) -> int:
        """Admission plane phase 1 ALONE: batch-verify the signatures of
        not-yet-pooled txs into the verified-sig cache (and batch their
        blobs' share commitments into the verified-commitment cache —
        the traffic plane's half). Stateless and never raises, so the
        reactor runs it OUTSIDE the service lock —
        the first qualifying batch pays the kernel's jit compile, which
        must not stall the consensus loop (a racing commit at worst
        costs a cache miss, never a wrong verdict)."""
        from celestia_app_tpu.chain import admission
        from celestia_app_tpu.mempool.pool import tx_hash

        fresh = [raw for raw in raws if not self.pool.has(tx_hash(raw))]
        if not fresh:
            return 0
        return admission.prevalidate(self.app, fresh, check_state=True)

    def reap_mempool(self) -> list[bytes]:
        """Priority order: gas price desc, per-sender arrival order kept —
        the order FilterTxs receives candidates in (mempool v1 semantics;
        see mempool.pool.priority_order for the nonce-safety rationale)."""
        return self.pool.reap(self.app.height)

    def prewarm_proposals(self, n_blocks: int) -> int:
        """Mesh-plane produce prefetch (reactor ``produce_batch`` knob):
        speculatively plan the next ``n_blocks`` proposal squares from
        the current reap and batch-extend them in one dispatch
        (chain/producer.py), seeding the app's EDS cache with
        device-resident entries so the upcoming propose (and, while the
        pool holds, the following heights this node proposes) hit a warm
        entry instead of dispatching per block. Purely a cache warm:
        proposal bytes are unchanged whether or not it ran. Returns how
        many entries were inserted."""
        from celestia_app_tpu.chain import producer

        plans = producer.plan_block_squares(
            self.app, self.reap_mempool(), n_blocks)
        return producer.warm_block_batch(self.app, plans)

    # -- consensus steps -------------------------------------------------
    # Two-phase Tendermint vote flow with lock-on-polka: prevote after
    # ProcessProposal, lock when >2/3 prevote one hash (a "polka"),
    # precommit only the locked/polka block. A validator locked at an
    # earlier round prevotes nil on any different block, so two conflicting
    # certificates at one height would need >1/3 byzantine power — the
    # safety argument LocalNetwork's tests pin.

    def propose(self, t: float):
        # a locked proposer must re-propose its locked block (Tendermint
        # validValue/lockedValue rule), not build a fresh one
        if self.locked_block is not None:
            return self.locked_block
        prop = self.app.prepare_proposal(
            self.reap_mempool(), proposer=self.address, t=t
        )
        return prop.block

    def _sign_state_path(self) -> str | None:
        if self.wal_dir is None:
            return None
        return os.path.join(os.path.dirname(self.wal_dir),
                            "priv_validator_state.json")

    def _load_sign_state(self) -> None:
        """Tendermint's priv_validator_state.json: the last non-nil vote
        hash signed per (height, round, phase), persisted BEFORE each
        signature so a crashed-and-restarted validator can never be
        tricked (or race itself) into signing a second, different non-nil
        vote at a (height, round) it already voted — now that votes sign
        their round, a same-round duplicate in EITHER phase is the
        slashable double-sign (celestia-core privval/file.go
        checkVotesOnlyDifferByTimestamp analog)."""
        self._signed_hashes: dict[tuple[int, int, str], str] = {}
        # Tendermint's monotonic watermark: the highest (round, step)
        # signed per height. A non-nil signature for an EARLIER slot is
        # refused (nil instead) — a lying coordinator replaying an old
        # round's genuine polka after we moved on (locks are in-memory;
        # a restart loses them) could otherwise harvest conflicting
        # cross-round precommits into two certificates at one height.
        self._sign_watermark: dict[int, tuple[int, int]] = {}
        path = self._sign_state_path()
        if path is None or not os.path.exists(path):
            return
        with open(path) as f:
            doc = json.load(f)
        for k, v in doc.get("signed", {}).items():
            parts = k.split(":")
            if len(parts) == 3:  # "height:round:phase"
                self._signed_hashes[(int(parts[0]), int(parts[1]),
                                     parts[2])] = v
            elif len(parts) == 2:  # legacy round-blind "height:phase"
                self._signed_hashes[(int(parts[0]), 0, parts[1])] = v
        for h, rr in doc.get("watermark", {}).items():
            self._sign_watermark[int(h)] = (int(rr[0]), int(rr[1]))

    def _persist_sign_state(self) -> None:
        path = self._sign_state_path()
        if path is None:
            return
        doc = {
            "signed": {
                f"{h}:{r}:{p}": v
                for (h, r, p), v in self._signed_hashes.items()
            },
            "watermark": {
                str(h): list(rr)
                for h, rr in self._sign_watermark.items()
            },
        }
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def _signed(self, height: int, bh: bytes | None, phase: str,
                round_: int = 0) -> Vote:
        """Sign a vote — through the durable double-sign guard (Tendermint
        privval FilePV semantics), refusing with a signed NIL instead
        (safe: nil votes can never form evidence or a certificate). Two
        durable rules:

        1. same-slot: a second non-nil vote at a (height, round, phase)
           we already signed must carry the SAME hash — a same-round
           duplicate is the slashable double-sign;
        2. monotonic: a non-nil vote for a slot EARLIER than the highest
           (round, step) signed at this height is refused — without it a
           lying coordinator (or a crash that dropped the in-memory
           lock) could walk us back to an old round's genuine polka and
           collect the conflicting old-round precommit that forges a
           second certificate at the height.

        Signing different hashes in LATER rounds stays legal (required
        for liveness: re-prevoting a fresh proposal after a failed
        round, re-precommitting after unlock-on-higher-polka). Entries
        are pruned once the chain moves past them.

        Nil signatures are recorded per slot too (the "" sentinel), so a
        later NON-nil vote at a slot we already signed nil is refused —
        Tendermint FilePV's same-HRS rule: two different votes at one
        (height, round, step), nil vs block, are a conflict an external
        privval judge would flag (ADVICE r5 #3). Re-signing nil at a
        nil slot stays legal (nil is also the refusal output)."""
        slot = (round_, 0 if phase == "prevote" else 1)
        wm = self._sign_watermark.get(height)
        changed = False
        key = (height, round_, phase)
        if bh is not None:
            if wm is not None and slot < wm:
                bh = None  # slot regression: refuse
            else:
                prior = self._signed_hashes.get(key)
                if prior is not None and prior != bh.hex():
                    # covers both a DIFFERENT non-nil (the classic
                    # double-sign) and a recorded nil ("" sentinel)
                    bh = None  # refuse; vote nil
                elif prior is None:
                    self._signed_hashes[key] = bh.hex()
                    changed = True
        if bh is None and key not in self._signed_hashes:
            self._signed_hashes[key] = ""  # nil signed at this slot
            changed = True
        if wm is None or slot > wm:
            # every signature advances the watermark — nil ones too
            # (Tendermint persists every signed vote): a nil precommit at
            # round r must block a later non-nil signature for round < r
            self._sign_watermark[height] = slot
            changed = True
        if changed:  # persist REAL transitions only (idempotent re-signs
            # of a recorded slot+hash skip the fsync on the hot path)
            floor = self.app.height - 2
            for k in [k for k in self._signed_hashes if k[0] < floor]:
                del self._signed_hashes[k]
            for h in [h for h in self._sign_watermark if h < floor]:
                del self._sign_watermark[h]
            self._persist_sign_state()
        sig = self.priv.sign(
            Vote.sign_bytes(self.app.chain_id, height, bh, phase, round_)
        )
        return Vote(height, bh, self.address, sig, phase, round_)

    def prevote_on(self, block: Block, round_: int = 0) -> Vote:
        """Prevote step: nil unless the proposal validates AND does not
        conflict with an existing lock."""
        h = block.header.height
        bh = block.header.hash()
        if self.locked_block is not None:
            if self.locked_block.header.hash() == bh:
                return self._signed(h, bh, "prevote", round_)  # validated
            return self._signed(h, None, "prevote", round_)  # locked: nil
        ok = self.app.process_proposal(block)
        return self._signed(h, bh if ok else None, "prevote", round_)

    def lock_permits(self, block_hash: bytes, round_: int) -> bool:
        """THE lock discipline, shared by the autonomous reactor and the
        orchestrated server (one definition — divergent copies would give
        the two modes different consensus safety rules): a locked
        validator may precommit `block_hash` at `round_` iff it is
        unlocked, the hash IS its lock, or the polka is from a LATER
        round than its lock (Tendermint unlock-on-higher-polka, sound
        now that votes sign their round)."""
        return (self.locked_block is None
                or self.locked_block.header.hash() == block_hash
                or round_ > self.locked_round)

    def on_polka(self, block: Block, round_: int) -> None:
        """>2/3 prevoted this block: lock on it (lock-on-polka). A polka
        at a LATER round than the current lock replaces it — Tendermint's
        unlock-on-higher-polka, sound now that votes sign their round."""
        if self.locked_block is not None and round_ < self.locked_round:
            return  # never regress to an older lock
        self.locked_block = block
        self.locked_round = round_

    def precommit_on(self, block: Block | None, round_: int = 0) -> Vote:
        """Precommit the polka block, or nil when no polka was observed."""
        if block is None:
            height = self.app.height + 1
            return self._signed(height, None, "precommit", round_)
        bh = block.header.hash()
        return self._signed(block.header.height, bh, "precommit", round_)

    def clear_lock(self) -> None:
        self.locked_block = None
        self.locked_round = -1

    def vote_on(self, block: Block, round_: int = 0) -> Vote:
        """One-shot validate+precommit (single-phase fixtures and tests);
        the network path uses prevote_on/precommit_on."""
        ok = self.app.process_proposal(block)
        bh = block.header.hash() if ok else None
        return self._signed(block.header.height, bh, "precommit", round_)

    def known_pubkeys(self) -> dict[bytes, bytes]:
        """operator -> consensus pubkey from BOTH trust roots: the genesis
        doc and on-chain registrations (MsgCreateValidator.pubkey via
        staking.consensus_pubkeys) — the full set whose votes this node
        can verify. Runtime-created validators exist only in the latter;
        a genesis entry wins any conflict (it is the older commitment,
        and create_validator refuses existing operators anyway)."""
        ctx = Context(
            self.app.store, InfiniteGasMeter(), self.app.height, 0,
            self.app.chain_id, self.app.app_version,
        )
        out = dict(self.app.staking.consensus_pubkeys(ctx))
        out.update(self.validator_pubkeys)
        return out

    def verify_certificate(self, cert: CommitCertificate,
                           pubkeys: dict[bytes, bytes] | None = None) -> bool:
        """Check a certificate against THIS node's own trust roots — the
        genesis + on-chain-registered pubkeys and the staking-state powers
        — before applying a block a remote orchestrator hands over (the
        socket commit path must not trust the coordinator). `pubkeys`
        optionally supplies a precomputed known_pubkeys() map so hot
        callers avoid repeated staking-store scans."""
        if pubkeys is None:
            pubkeys = self.known_pubkeys()
        if not pubkeys:
            return False
        ctx = Context(
            self.app.store, InfiniteGasMeter(), self.app.height, 0,
            self.app.chain_id, self.app.app_version,
        )
        powers = dict(self.app.staking.validators(ctx))
        return cert.verify(
            self.app.chain_id, pubkeys,
            sum(powers.values()), powers,
        )

    def _wal_path(self, height: int) -> str:
        return os.path.join(self.wal_dir, f"{height:020d}.json")

    def write_wal(
        self, block: Block, cert: CommitCertificate,
        evidence: tuple["DuplicateVoteEvidence", ...] = (),
        present: set[bytes] | None = None,
        record_present: bool = False,
    ) -> None:
        """Append-before-apply: the crash-recovery record. Evidence applied
        with the block is PART of the record — replay must re-apply it or
        the replayed app hash diverges from live peers. When
        `record_present` is set (autonomous mode), the presence set that
        liveness accounting actually used is recorded explicitly, because
        it came from the PROPOSAL's last-commit certificate, not from the
        locally-assembled `cert` this record stores — replay from the cert
        alone would diverge."""
        if self.wal_dir is None:
            return

        with obs.span(
            "wal.append", traces=self.app.traces,
            trace_id=obs.trace_id_for(self.app.chain_id,
                                      block.header.height),
            height=block.header.height,
        ):
            doc = {
                "evidence": [evidence_to_json(ev) for ev in evidence],
                "height": block.header.height,
                **block_to_json(block),
                "votes": [vote_to_json(v) for v in cert.votes],
                # the commit round: replay must rebuild the certificate
                # with it, or a round>0 cert's round-scoped votes count
                # as zero power (and the presence set reads empty) after
                # restart
                "cert_round": cert.round,
            }
            if record_present:
                doc["present"] = (
                    None if present is None
                    else sorted(a.hex() for a in present)
                )
            tmp = self._wal_path(block.header.height) + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f)
                f.flush()
                os.fsync(f.fileno())
            # crash point 1 of the commit matrix: the record is fsync'd
            # as a tmp but NOT renamed — after restart there is no
            # durable WAL entry for this height (the torn tail the replay
            # scanner skips). Recovery: commit-record catch-up from peers
            # (blocksync).
            faults.fire("consensus.wal_append",
                        height=block.header.height)
            os.replace(tmp, self._wal_path(block.header.height))

    def _present_set_from_cert(
        self, cert: CommitCertificate | None
    ) -> set[bytes] | None:
        """LastCommitInfo presence reconstruction: a validator counts as
        present only with a precommit FOR the committed block at the
        certificate's height — a vote for a different block / stale height
        / junk signature is an absence, so misbehaving validators cannot
        suppress their own liveness window. Each vote's signature is
        checked against the genesis-known validator pubkeys (mirroring
        cert.verify), so a cert padded with forged presence-votes for
        offline validators cannot suppress their downtime accounting; a
        validator with no genesis pubkey (legacy fixture genesis) falls
        back to unverified matching. None cert (height 1 in autonomous
        mode: no last commit exists) -> None, meaning everyone present.

        Computed BEFORE evidence is applied (and recorded in the WAL when
        the source cert differs from the stored one), while the absent
        set it induces is derived from the POST-evidence validator set
        (_set_absent). Verification keys are known_pubkeys() — genesis
        plus on-chain registrations — so runtime-created validators'
        presence votes are signature-checked too, not fallback-matched;
        both live apply and WAL replay read the same pre-block state, so
        the computation stays deterministic across them."""
        if cert is None:
            return None
        known = self.known_pubkeys()
        doc = Vote.sign_bytes(self.app.chain_id, cert.height,
                              cert.block_hash, round_=cert.round)
        voted = set()
        for v in cert.votes:
            if (v.block_hash != cert.block_hash or v.height != cert.height
                    or v.round != cert.round):
                continue
            pub = known.get(v.validator)
            if pub is not None and not PublicKey(pub).verify(v.signature, doc):
                continue
            voted.add(v.validator)
        return voted

    def _set_absent(self, present: set[bytes] | None) -> None:
        ctx = Context(
            self.app.store, InfiniteGasMeter(), self.app.height, 0,
            self.app.chain_id, self.app.app_version,
        )
        if present is None:
            self.app.absent_validators = set()
            return
        self.app.absent_validators = {
            op for op, _p in self.app.staking.validators(ctx)
            if op not in present
        }

    def _mark_absent_from_votes(self, cert: CommitCertificate) -> None:
        self._set_absent(self._present_set_from_cert(cert))

    def _apply_evidence(
        self, evidence: tuple["DuplicateVoteEvidence", ...]
    ) -> None:
        for ev in evidence:
            ctx = Context(
                self.app.store, InfiniteGasMeter(), self.app.height, 0,
                self.app.chain_id, self.app.app_version,
            )
            self.app.slashing.handle_equivocation(
                ctx, ev.vote_a.validator, infraction_height=ev.height
            )

    # sentinel: "derive the presence set from the commit certificate itself"
    # (the orchestrated modes, where one coordinator hands every node the
    # same cert). Autonomous mode passes the proposal's last_cert instead.
    _ABSENT_FROM_CERT = object()

    def apply(
        self, block: Block, cert: CommitCertificate,
        evidence: tuple["DuplicateVoteEvidence", ...] = (),
        absent_cert=_ABSENT_FROM_CERT,
    ) -> bytes:
        """Finalize + commit a certified block (evidence first — the
        x/evidence BeginBlock position); returns the app hash. Evidence is
        in the WAL record, so crash replay re-applies it identically.

        LastCommitInfo analog: validators whose precommit is absent from
        the presence source are marked absent, consumed by THIS block's
        BeginBlock liveness accounting. The presence source is the commit
        certificate itself in the orchestrated modes (every node receives
        the identical cert), or — in autonomous mode, where each node
        assembles its OWN cert from gossip — the height-1 certificate the
        PROPOSER embedded in the signed proposal (`absent_cert`), which is
        the one choice all nodes share (Tendermint's LastCommitInfo-in-
        block wiring). The WAL records the presence set whenever it did
        not come from `cert`."""
        with obs.span(
            "apply", traces=self.app.traces,
            trace_id=obs.trace_id_for(self.app.chain_id,
                                      block.header.height),
            height=block.header.height, node=self.name,
        ):
            from_proposal = \
                absent_cert is not ValidatorNode._ABSENT_FROM_CERT
            src = cert if not from_proposal else absent_cert
            present = self._present_set_from_cert(src)
            self.write_wal(block, cert, evidence, present=present,
                           record_present=from_proposal)
            # crash point 2 of the commit matrix: the WAL record IS
            # durable but no state has been touched. Recovery:
            # replay_wal() re-applies the recorded block on restart
            # (Tendermint's replay semantics).
            faults.fire("consensus.post_wal_pre_apply",
                        height=block.header.height)
            self._apply_evidence(evidence)
            # ordering invariant shared with replay_wal: evidence FIRST,
            # then absences — both paths must compute the absent set
            # against the same post-evidence validator set or replayed
            # nodes diverge
            self._set_absent(present)
            results = self.app.finalize_block(block)
            app_hash = self.app.commit(block)
            self.certificates[block.header.height] = cert
            self._record_committed(block, results)
            self.pool.remove_committed(block.txs)
            # post-commit recheck (RecheckTx): survivors re-run CheckTx
            # against the fresh check state so nonce-stale txs (their
            # sender's sequence advanced in THIS block via a different
            # tx) drop instead of wasting the next proposal slot
            self.pool.recheck(self.app.check_tx)
            return app_hash

    def _record_committed(self, block: Block, results) -> None:
        """Tx-hash -> (height, result) index backing the gRPC GetTx /
        ConfirmTx surface a validator process serves — the ONE recorder
        shared with Node (height-windowed; node.record_committed)."""
        from celestia_app_tpu.chain.node import record_committed

        record_committed(self.committed, block, results)

    # GrpcTxServer and NodeService speak to anything exposing
    # broadcast_tx/app/committed; a validator process IS that node
    # (one binary per validator)
    def broadcast_tx(self, raw: bytes):
        return self.add_tx(raw)

    def produce_block(self, t: float | None = None):
        """Blocked on purpose: a validator's blocks come from consensus
        (the socket round schedule), never from a local convenience route
        — NodeService's /produce_block surfaces this as a 400 policy
        refusal (QueryError), not a server error."""
        from celestia_app_tpu.chain.query import QueryError

        raise QueryError(
            "validator blocks are produced by consensus, not on demand"
        )

    def replay_wal(self) -> int:
        """Crash recovery: apply WAL entries above the committed height
        (Tendermint replay). Returns how many blocks were replayed."""
        if self.wal_dir is None:
            return 0

        replayed = 0
        for name in sorted(os.listdir(self.wal_dir)):
            if not name.endswith(".json"):
                continue
            height = int(name.split(".")[0])
            if height <= self.app.height:
                continue
            with open(os.path.join(self.wal_dir, name)) as f:
                doc = json.load(f)
            block = block_from_json(doc)
            votes = tuple(vote_from_json(v) for v in doc["votes"])
            cert = CommitCertificate(height, block.header.hash(), votes,
                                     int(doc.get("cert_round", 0)))
            evidence = tuple(
                evidence_from_json(e) for e in doc.get("evidence", [])
            )
            self._apply_evidence(evidence)
            # reconstruct the LastCommitInfo absences exactly as the live
            # run applied them (same evidence-then-absences order as
            # apply()): from the WAL's explicit presence record when one
            # was written (autonomous mode), else from the stored cert
            if "present" in doc:
                present = (
                    None if doc["present"] is None
                    else {bytes.fromhex(a) for a in doc["present"]}
                )
                self._set_absent(present)
            else:
                self._mark_absent_from_votes(cert)
            # admission plane: one batched dispatch verifies the whole
            # replayed block's signatures (replay skips process_proposal,
            # where the live path prevalidates); the delivery ante below
            # hits the verified-sig cache instead of re-verifying per tx.
            # commitments=False: delivery under a commit certificate
            # validates no blob commitments, so the commitment batch
            # would be pure wasted hashing on the recovery path
            from celestia_app_tpu.chain import admission

            admission.prevalidate(self.app, block.txs, commitments=False)
            results = self.app.finalize_block(block)
            self.app.commit(block)
            self.certificates[height] = cert
            self._record_committed(block, results)
            replayed += 1
        return replayed

    # -- state sync (serving side) ---------------------------------------

    def snapshot_chunks(self) -> tuple[dict, list[bytes]]:
        return snapshot_app_chunks(self.app)


# keys per state-sync snapshot chunk. Env-tunable (chunking is a serving-
# local choice: the manifest commits to whatever chunking the server used,
# and the joiner verifies against THAT manifest) — chaos tests shrink it
# to force multi-chunk restores out of small devnet states.
SNAPSHOT_CHUNK_KEYS = int(
    os.environ.get("CELESTIA_SNAPSHOT_CHUNK_KEYS", "64")
)


def capture_app_snapshot(app: App) -> dict:
    """The part that must run under the node's writer lock: copy the
    committed store + chain identity at one instant. Cheap (dict copy);
    the expensive chunk encoding happens in encode_app_snapshot, safely
    outside the lock."""
    capture = {
        "items": app.store.snapshot(),  # already a fresh copy (state.py)
        "height": app.height,
        "app_hash": app.last_app_hash.hex(),
        "app_version": app.app_version,
        "chain_id": app.chain_id,
        "genesis_time": app.genesis_time,
        "last_block_hash": app.last_block_hash.hex(),
    }
    codec = getattr(app, "codec", None)
    if codec is not None and codec.scheme_id:
        # codec plane: a joiner must refuse a snapshot from a chain run
        # under a different DA scheme (its stored blocks would neither
        # replay nor serve samples here). Stamped only for non-default
        # schemes so default-scheme manifests — and their content
        # digests, which key restore resume dirs — stay byte-identical
        # to pre-plane ones (FORMATS §16.1 back-compat rule).
        capture["da_scheme"] = codec.name
    return capture


def encode_app_snapshot(capture: dict) -> tuple[dict, list[bytes]]:
    """Pure: deterministic key-ranged chunks + manifest from a capture."""
    items = sorted(capture["items"].items())
    chunks: list[bytes] = []
    for i in range(0, max(len(items), 1), SNAPSHOT_CHUNK_KEYS):
        part = items[i : i + SNAPSHOT_CHUNK_KEYS]
        chunks.append(
            json.dumps(
                [[k.hex(), v.hex()] for k, v in part], sort_keys=True
            ).encode()
        )
    manifest = {
        **{k: v for k, v in capture.items() if k != "items"},
        "n_chunks": len(chunks),
        "chunk_hashes": [hashlib.sha256(c).hexdigest() for c in chunks],
    }
    return manifest, chunks


def snapshot_app_chunks(app: App) -> tuple[dict, list[bytes]]:
    """(manifest, chunks): the committed store split into deterministic
    key-ranged chunks (state-sync serving, default_overrides.go:294).
    One-shot convenience; lock-conscious callers split into
    capture_app_snapshot (under lock) + encode_app_snapshot (outside)."""
    return encode_app_snapshot(capture_app_snapshot(app))


def state_sync_bootstrap(node_or_app, manifest: dict, chunks: list[bytes]) -> None:
    """Adopt a snapshot AFTER verification: every chunk must match the
    manifest hash, and the reassembled store's app hash must equal the
    trusted header's app_hash — altered chunks are rejected wholesale.
    Accepts a ValidatorNode or a bare App."""
    app = getattr(node_or_app, "app", node_or_app)
    codec = getattr(app, "codec", None)
    local_scheme = codec.name if codec is not None else "rs2d-nmt"
    if manifest.get("da_scheme", "rs2d-nmt") != local_scheme:
        # codec plane: adopting another scheme's state would leave this
        # node unable to replay or serve the chain it just joined
        raise ValueError(
            f"snapshot is from a {manifest.get('da_scheme')!r}-scheme "
            f"chain; this node runs {local_scheme!r}")
    if len(chunks) != manifest["n_chunks"]:
        raise ValueError("chunk count mismatch")
    for i, c in enumerate(chunks):
        if hashlib.sha256(c).hexdigest() != manifest["chunk_hashes"][i]:
            raise ValueError(f"chunk {i} hash mismatch")
    data: dict[bytes, bytes] = {}
    for c in chunks:
        for k_hex, v_hex in json.loads(c):
            data[bytes.fromhex(k_hex)] = bytes.fromhex(v_hex)
    from celestia_app_tpu.chain.state import KVStore

    probe = KVStore(data)
    if probe.app_hash().hex() != manifest["app_hash"]:
        raise ValueError("snapshot app hash does not match trusted header")
    app.store.restore(data)
    app.height = manifest["height"]
    app.app_version = manifest["app_version"]
    app.last_app_hash = bytes.fromhex(manifest["app_hash"])
    app.last_block_hash = bytes.fromhex(manifest["last_block_hash"])
    app.genesis_time = manifest["genesis_time"]
    app._check_state = None


@dataclasses.dataclass(frozen=True)
class DuplicateVoteEvidence:
    """Two signed votes by the same validator for DIFFERENT blocks at one
    height — the Tendermint double-sign evidence type. Verifiable offline
    (both signatures check out against the validator's key), and submitted
    to x/evidence → tombstone + slash (sdk_modules.handle_equivocation)."""

    height: int
    vote_a: Vote
    vote_b: Vote

    def verify(self, chain_id: str, pubkey: bytes) -> bool:
        a, b = self.vote_a, self.vote_b
        if a.validator != b.validator:
            return False
        if a.height != self.height or b.height != self.height:
            return False  # both votes must be AT the evidence height
        if a.block_hash is None or b.block_hash is None:
            # nil votes are never equivocation — the sign guard's refusal
            # path EMITS signed nils at slots where a non-nil already
            # exists, so a (non-nil, nil) pair is an honest validator
            # protecting itself, not a double-sign
            return False
        if a.block_hash == b.block_hash:
            return False  # same block: not equivocation
        if a.phase != b.phase:
            # prevote(A)+precommit(B) is a legal Tendermint history
            # (unlock via a later polka); only duplicate votes in the
            # SAME step are slashable
            return False
        if a.round != b.round:
            # different rounds: legal protocol behavior in BOTH phases —
            # re-prevoting a fresh proposal after a failed round, or
            # re-precommitting after unlock-on-higher-polka. Without this
            # check a byzantine proposer could package two honest
            # cross-round votes as "evidence" and have the network slash
            # an honest validator (round-4 advisor finding).
            return False
        pub = PublicKey(pubkey)
        if pub.address() != a.validator:
            return False
        return pub.verify(
            a.signature,
            Vote.sign_bytes(chain_id, a.height, a.block_hash, a.phase,
                            a.round),
        ) and pub.verify(
            b.signature,
            Vote.sign_bytes(chain_id, b.height, b.block_hash, b.phase,
                            b.round),
        )


def detect_equivocation(
    chain_id: str, votes_by_round: list[list[Vote]],
    validators: dict[bytes, bytes],
) -> list[DuplicateVoteEvidence]:
    """Scan one height's votes for validators that signed two different
    block hashes at the same (round, phase); returns verified evidence
    only."""
    # SAME (round, phase) only. Votes sign their round, so an honest
    # validator produces at most one non-nil vote per (height, round,
    # phase) — prevoting different blocks in different ROUNDS (failed
    # round rotates the proposal) and precommitting different blocks in
    # different rounds (unlock-on-higher-polka) are both legal protocol
    # histories. A same-round duplicate in EITHER phase is the classic
    # slashable double-sign (celestia-core types/evidence.go).
    seen: dict[tuple[bytes, int, int, str], Vote] = {}
    out: list[DuplicateVoteEvidence] = []
    accused: set[bytes] = set()
    for votes in votes_by_round:
        for v in votes:
            if v.block_hash is None or v.validator in accused:
                continue
            if v.phase not in ("prevote", "precommit"):
                continue
            key = (v.validator, v.height, v.round, v.phase)
            prior = seen.get(key)
            if prior is None:
                seen[key] = v
            elif prior.block_hash != v.block_hash:
                ev = DuplicateVoteEvidence(v.height, prior, v)
                pub = validators.get(v.validator)
                if pub is not None and ev.verify(chain_id, pub):
                    out.append(ev)
                    accused.add(v.validator)
    return out


class LocalNetwork:
    """N validators + an in-process gossip bus (tx fan-out, proposal/vote
    exchange). Proposer rotation is deterministic round-robin over the
    address-sorted validator set. Votes from failed rounds are retained
    per height and scanned for equivocation; verified double-sign evidence
    is submitted to every node's x/evidence handler (tombstone + slash)."""

    def __init__(self, nodes: list[ValidatorNode]):
        if not nodes:
            raise ValueError("need at least one validator")
        self.nodes = sorted(nodes, key=lambda n: n.address)
        self.chain_id = nodes[0].app.chain_id
        # every member learns every member's consensus pubkey (gossiped at
        # handshake in real p2p); genesis-carried keys take precedence
        peer_keys = {n.address: n.priv.public_key().compressed for n in nodes}
        for n in self.nodes:
            n.validator_pubkeys = {**peer_keys, **n.validator_pubkeys}
        self._round = 0  # advances on failed rounds so the proposer rotates
        # signature-verified votes retained for the evidence window, so a
        # conflicting vote surfacing a few heights late still pairs up
        # (Tendermint's evidence max-age analog)
        self._vote_pool: list[Vote] = []

    EVIDENCE_MAX_AGE = 10  # heights a vote stays eligible for evidence

    def _powers(self, app: App) -> dict[bytes, int]:
        ctx = Context(app.store, InfiniteGasMeter(), app.height, 0,
                      app.chain_id, app.app_version)
        return dict(app.staking.validators(ctx))

    def broadcast_tx(self, raw: bytes, via: int = 0) -> bool:
        """Gossip: every node runs CheckTx on the tx independently (the
        Tendermint model — mempools can disagree). The caller's verdict is
        the verdict of the node it submitted through (`via`), exactly as a
        client sees only its own node's CheckTx; `broadcast_tx_all` exposes
        the full per-node picture for tests and the devnet monitor."""
        return self.broadcast_tx_all(raw)[via]

    def broadcast_tx_all(self, raw: bytes) -> list[bool]:
        return [n.add_tx(raw).code == 0 for n in self.nodes]

    def proposer_for(self, height: int, round_: int = 0) -> ValidatorNode:
        return self.nodes[(height + round_) % len(self.nodes)]

    def produce_height(
        self, t: float, vote_filter=None,
    ) -> tuple[Block | None, CommitCertificate | None]:
        """One consensus round, two vote phases (Tendermint):

        propose → prevote → [polka? lock] → precommit → [>2/3? commit].

        A "polka" (>2/3 prevote power on one hash) locks every validator
        that observed it onto the block; locked validators prevote nil on
        any OTHER block in later rounds and a locked proposer re-proposes
        its lock — so a split round is SAFE (no second certificate can
        form at the height), not merely rotated. Round timeouts are
        schedule-driven here: a round that fails any quorum advances
        `_round`, rotating the proposer exactly as Tendermint's timeout
        cascade does, and locks persist across rounds.

        `vote_filter(phase, votes) -> votes` (tests only) models partitions
        and message loss by dropping votes in flight."""
        height = self.nodes[0].app.height + 1
        proposer = self.proposer_for(height, self._round)
        try:
            block = proposer.propose(t)
        except Exception:
            # proposer crash = propose-timeout: nil round, rotate
            telemetry.incr("consensus.propose_errors")
            self._round += 1
            return None, None
        bh = block.header.hash()
        powers = self._powers(self.nodes[0].app)
        total = sum(powers.values())
        validators = {
            n.address: n.priv.public_key().compressed for n in self.nodes
        }

        # -- prevote phase ----------------------------------------------
        own_prevotes = [n.prevote_on(block, self._round) for n in self.nodes]
        prevotes = list(own_prevotes)
        if vote_filter is not None:
            prevotes = list(vote_filter("prevote", prevotes))
        # prevotes enter the evidence pool too: votes sign their round, so
        # detect_equivocation pairs only same-round duplicates — a legal
        # round-0-A/round-1-B prevote history can no longer be mistaken
        # for equivocation
        self._vote_pool.extend(
            v for v in prevotes if v.block_hash is not None
        )
        prevote_power = sum(
            powers.get(v.validator, 0)
            for v in prevotes
            if v.block_hash == bh and v.height == height
        )
        polka = prevote_power * 3 > total * 2

        # -- precommit phase --------------------------------------------
        # a polka locks only validators whose OWN prevote accepted the
        # block — one that judged it invalid precommits nil regardless of
        # what >2/3 of the others claim (Tendermint validity gate)
        precommits = []
        for n, pv in zip(self.nodes, own_prevotes):
            if polka and pv.block_hash == bh:
                n.on_polka(block, self._round)
                precommits.append(n.precommit_on(block, self._round))
            else:
                precommits.append(n.precommit_on(None, self._round))
        if vote_filter is not None:
            precommits = list(vote_filter("precommit", precommits))
        self._vote_pool.extend(
            v for v in precommits if v.block_hash is not None
        )
        self._prune_vote_pool(height)

        cert = CommitCertificate(height, bh, tuple(precommits), self._round)
        if not cert.verify(self.chain_id, validators, total, powers):
            self._round += 1
            return None, None
        self._round = 0
        # evidence rides the committed block (the x/evidence position):
        # deterministic across nodes and recorded in every WAL entry
        evidence = tuple(
            detect_equivocation(self.chain_id, [self._vote_pool], validators)
        )
        if evidence:
            punished = {ev.vote_a.validator for ev in evidence}
            self._vote_pool = [
                v for v in self._vote_pool if v.validator not in punished
            ]
        hashes = {n.apply(block, cert, evidence) for n in self.nodes}
        if len(hashes) != 1:
            raise AssertionError(
                f"state divergence after height {height}: {sorted(h.hex() for h in hashes)}"
            )
        for n in self.nodes:
            n.clear_lock()
        return block, cert

    def _prune_vote_pool(self, current_height: int) -> None:
        floor = current_height - self.EVIDENCE_MAX_AGE
        self._vote_pool = [v for v in self._vote_pool if v.height > floor]

    def inject_vote(self, vote: Vote) -> None:
        """Gossip entry for an externally-received vote. The signature is
        verified AT THE DOOR against the known validator set — a forged
        vote must never enter the pool, where it could poison the
        first-seen slot and mask real equivocation."""
        by_addr = {n.address: n.priv.public_key().compressed for n in self.nodes}
        pub = by_addr.get(vote.validator)
        if pub is None or vote.block_hash is None:
            raise ValueError("vote from unknown validator or nil vote")
        if not PublicKey(pub).verify(
            vote.signature,
            Vote.sign_bytes(self.chain_id, vote.height, vote.block_hash,
                            vote.phase, vote.round),
        ):
            raise ValueError("vote signature verification failed")
        self._vote_pool.append(vote)
