"""The remaining cosmos-sdk module set the reference wires: distribution,
slashing, evidence, authz, feegrant, vesting, crisis.

Reference parity: app/app.go's module manager registers these around the
celestia-specific modules. Semantics follow the SDK keepers, sized to this
framework's flat store:

- distribution: F1-style reward accounting — every validator carries a
  cumulative rewards-per-share index; a delegation records the index at its
  last touch, so pending rewards = shares * (index_now − index_then). Fees
  + inflation collected in FEE_COLLECTOR are distributed per block
  proportional to power (allocation, x/distribution/keeper/allocation.go).
- slashing: per-validator signing info over a sliding window; falling below
  the minimum signed ratio jails + slashes slash_fraction_downtime; double
  signs (evidence) slash slash_fraction_double_sign and tombstone
  (x/slashing/keeper/infractions.go, x/evidence handler).
- authz: (granter, grantee, msg_type) grants with expiry; exec runs a
  message with the granter as effective signer.
- feegrant: (granter, grantee) allowances with spend limit + expiry,
  honored by the ante fee deduction when a tx names a fee_granter.
- vesting: continuous vesting locks a linear fraction of original_vesting
  until end_time; bank transfers of locked funds are rejected.
- crisis: registered invariants (supply == Σ balances + module pools,
  staking pool consistency) assertable per block or on demand.
"""

from __future__ import annotations

import json

from celestia_app_tpu.chain.staking import BONDED_POOL, NOT_BONDED_POOL
from celestia_app_tpu.chain.state import Context, get_json, put_json

from celestia_app_tpu.chain.modules import FEE_COLLECTOR


def _put(ctx, key: bytes, obj) -> None:
    put_json(ctx, key, obj)


def _get(ctx, key: bytes):
    return get_json(ctx, key)


# ---------------------------------------------------------------------------
# distribution (F1-lite)
# ---------------------------------------------------------------------------

DISTRIBUTION_POOL = b"\x00" * 19 + b"\x05"

# Reward indices are integers scaled by INDEX_SCALE: utia-per-share-unit in
# 1e18ths (the SDK's sdk.Dec precision). Shares themselves are integer share
# units (staking.SHARE_SCALE per utia), so every value the store sees is an
# int and the app hash is reproducible by any implementation.
INDEX_SCALE = 10**18


class DistributionKeeper:
    IDX = b"dist/val_index/"  # cumulative rewards-per-share (int, 1e18 scale)
    REF = b"dist/del_ref/"  # (operator+delegator) -> index at last touch
    ACC = b"dist/del_acc/"  # accrued-but-unclaimed rewards (int utia)

    def __init__(self, staking, bank):
        self.staking = staking
        self.bank = bank

    def _index(self, ctx: Context, op: bytes) -> int:
        return _get(ctx, self.IDX + op) or 0

    def allocate(self, ctx: Context) -> int:
        """BeginBlocker: move the fee collector's balance into per-validator
        reward indices, proportional to power (allocation.go:14-80).
        Flooring leaves dust in DISTRIBUTION_POOL — the SDK's community-pool
        remainder analog."""
        pot = self.bank.balance(ctx, FEE_COLLECTOR)
        if pot <= 0:
            return 0
        # only validators with outstanding shares can be credited; excluding
        # the rest from the denominator keeps every utia of the pot reachable
        vals = [
            (op, p, self.staking.validator(ctx, op))
            for op, p in self.staking.validators(ctx)
        ]
        vals = [(op, p, v) for op, p, v in vals if v["shares"] > 0]
        total = sum(p for _, p, _ in vals)
        if total == 0:
            return 0
        self.bank.send(ctx, FEE_COLLECTOR, DISTRIBUTION_POOL, pot)
        for op, power, v in vals:
            share = pot * power // total
            _put(
                ctx,
                self.IDX + op,
                self._index(ctx, op) + share * INDEX_SCALE // v["shares"],
            )
        return pot

    def _settle(self, ctx: Context, op: bytes, delegator: bytes) -> int:
        """Bank accrued rewards up to the current index (called before any
        delegation change and by withdraw)."""
        shares = self.staking.delegation(ctx, op, delegator)
        key = self.REF + op + delegator
        ref = _get(ctx, key) or 0
        idx = self._index(ctx, op)
        accrued = shares * (idx - ref) // INDEX_SCALE
        if accrued:
            acc_key = self.ACC + op + delegator
            _put(ctx, acc_key, (_get(ctx, acc_key) or 0) + accrued)
        _put(ctx, key, idx)
        return accrued

    def pending_rewards(self, ctx: Context, op: bytes, delegator: bytes) -> int:
        shares = self.staking.delegation(ctx, op, delegator)
        ref = _get(ctx, self.REF + op + delegator) or 0
        acc = _get(ctx, self.ACC + op + delegator) or 0
        return acc + shares * (self._index(ctx, op) - ref) // INDEX_SCALE

    # staking hook (registered in staking.hooks): settle before any
    # delegation change so new shares never accrue retroactive rewards and
    # removed shares bank what they earned (the SDK's F1 hook pattern)
    def before_delegation_modified(self, ctx: Context, op: bytes, delegator: bytes) -> None:
        self._settle(ctx, op, delegator)

    def withdraw(self, ctx: Context, op: bytes, delegator: bytes) -> int:
        self._settle(ctx, op, delegator)
        acc_key = self.ACC + op + delegator
        amount = _get(ctx, acc_key) or 0
        if amount > 0:
            self.bank.send(ctx, DISTRIBUTION_POOL, delegator, amount)
        ctx.store.delete(acc_key)
        return amount


# ---------------------------------------------------------------------------
# slashing + evidence
# ---------------------------------------------------------------------------

SIGNED_BLOCKS_WINDOW = 5000
MIN_SIGNED_PER_WINDOW = (3, 4)  # exact rational, sdk Dec "0.75"
SLASH_FRACTION_DOWNTIME = (1, 100)
SLASH_FRACTION_DOUBLE_SIGN = (5, 100)
DOWNTIME_JAIL_SECONDS = 600
JAILED_FOREVER = 1 << 62  # tombstone sentinel (JSON-safe, no float inf)


class SlashingKeeper:
    INFO = b"slashing/info/"

    def __init__(self, staking):
        self.staking = staking

    def info(self, ctx: Context, op: bytes) -> dict:
        return _get(ctx, self.INFO + op) or {
            "missed": 0,
            "window_start": ctx.height,
            "jailed_until": 0,
            "tombstoned": False,
        }

    def handle_signature(self, ctx: Context, op: bytes, signed: bool) -> None:
        """Per-block liveness accounting (infractions.go HandleValidatorSignature)."""
        info = self.info(ctx, op)
        if ctx.height - info["window_start"] >= SIGNED_BLOCKS_WINDOW:
            info["missed"] = 0
            info["window_start"] = ctx.height
        if not signed:
            info["missed"] += 1
            num, den = MIN_SIGNED_PER_WINDOW
            allowed = SIGNED_BLOCKS_WINDOW * (den - num) // den
            if info["missed"] > allowed and not info["tombstoned"]:
                # the SDK passes distributionHeight ≈ the current height to
                # Slash for downtime (keeper/infractions.go), so existing
                # unbonding/redelegation entries — created strictly earlier —
                # are all spared; only the bonded stake is cut
                self.staking.slash(
                    ctx, op, SLASH_FRACTION_DOWNTIME,
                    infraction_height=ctx.height,
                )
                info["jailed_until"] = int(ctx.time_unix) + DOWNTIME_JAIL_SECONDS
                info["missed"] = 0
                info["window_start"] = ctx.height
                ctx.emit_event("slashing.downtime", validator=op.hex())
        _put(ctx, self.INFO + op, info)

    def handle_equivocation(
        self, ctx: Context, op: bytes, infraction_height: int | None = None
    ) -> None:
        """x/evidence: double-sign slashes harder and tombstones forever.
        `infraction_height` is the evidence's height (x/evidence handler
        passes it so entries predating the double-sign are untouched)."""
        info = self.info(ctx, op)
        if info["tombstoned"]:
            return
        self.staking.slash(
            ctx, op, SLASH_FRACTION_DOUBLE_SIGN,
            infraction_height=infraction_height,
        )
        info["tombstoned"] = True
        info["jailed_until"] = JAILED_FOREVER
        _put(ctx, self.INFO + op, info)
        ctx.emit_event("slashing.double_sign", validator=op.hex())

    def unjail(self, ctx: Context, op: bytes) -> None:
        info = self.info(ctx, op)
        if info["tombstoned"]:
            raise ValueError("validator is tombstoned")
        if ctx.time_unix < info["jailed_until"]:
            raise ValueError("still jailed")
        self.staking.unjail(ctx, op)


# ---------------------------------------------------------------------------
# authz
# ---------------------------------------------------------------------------


class AuthzKeeper:
    GRANT = b"authz/"

    def grant(self, ctx: Context, granter: bytes, grantee: bytes,
              msg_type: str, expiration: float | None = None) -> None:
        _put(ctx, self.GRANT + granter + grantee + msg_type.encode(),
             {"expiration": expiration})

    def revoke(self, ctx: Context, granter: bytes, grantee: bytes, msg_type: str) -> None:
        ctx.store.delete(self.GRANT + granter + grantee + msg_type.encode())

    def has_authorization(self, ctx: Context, granter: bytes, grantee: bytes,
                          msg_type: str) -> bool:
        g = _get(ctx, self.GRANT + granter + grantee + msg_type.encode())
        if g is None:
            return False
        exp = g.get("expiration")
        if exp is not None and ctx.time_unix > exp:
            return False
        return True


# ---------------------------------------------------------------------------
# feegrant
# ---------------------------------------------------------------------------


class FeeGrantKeeper:
    GRANT = b"feegrant/"

    def grant(self, ctx: Context, granter: bytes, grantee: bytes,
              spend_limit: int | None = None, expiration: float | None = None) -> None:
        _put(ctx, self.GRANT + granter + grantee,
             {"spend_limit": spend_limit, "expiration": expiration})

    def revoke(self, ctx: Context, granter: bytes, grantee: bytes) -> None:
        ctx.store.delete(self.GRANT + granter + grantee)

    def use_grant(self, ctx: Context, granter: bytes, grantee: bytes, fee: int) -> None:
        """Charge `fee` against the allowance (ante DeductFeeDecorator with a
        fee_granter set); raises ValueError if absent/expired/exceeded."""
        key = self.GRANT + granter + grantee
        g = _get(ctx, key)
        if g is None:
            raise ValueError("no fee allowance")
        exp = g.get("expiration")
        if exp is not None and ctx.time_unix > exp:
            raise ValueError("fee allowance expired")
        limit = g.get("spend_limit")
        if limit is not None:
            if fee > limit:
                raise ValueError("fee exceeds allowance")
            remaining = limit - fee
            if remaining == 0:
                ctx.store.delete(key)
            else:
                g["spend_limit"] = remaining
                _put(ctx, key, g)


# ---------------------------------------------------------------------------
# vesting
# ---------------------------------------------------------------------------


class VestingKeeper:
    """Continuous vesting accounts: a linear fraction of original_vesting
    stays locked between start and end times."""

    ACC = b"vesting/"

    def create(self, ctx: Context, addr: bytes, original_vesting: int,
               start_time: float, end_time: float) -> None:
        if end_time <= start_time:
            raise ValueError("vesting end must follow start")
        _put(ctx, self.ACC + addr, {
            "original_vesting": original_vesting,
            "start_time": int(start_time),
            "end_time": int(end_time),
        })

    def locked(self, ctx: Context, addr: bytes) -> int:
        v = _get(ctx, self.ACC + addr)
        if v is None:
            return 0
        t = int(ctx.time_unix)
        if t >= v["end_time"]:
            return 0
        if t <= v["start_time"]:
            return v["original_vesting"]
        # integer pro-rata: locked = orig * remaining / total (floor)
        return (
            v["original_vesting"] * (v["end_time"] - t)
            // (v["end_time"] - v["start_time"])
        )

    def check_spendable(self, ctx: Context, bank, addr: bytes, amount: int) -> None:
        locked = self.locked(ctx, addr)
        if locked and bank.balance(ctx, addr) - amount < locked:
            raise ValueError(
                f"insufficient spendable balance: {locked} utia still vesting"
            )


# ---------------------------------------------------------------------------
# crisis
# ---------------------------------------------------------------------------


class CrisisKeeper:
    def __init__(self):
        self.invariants: list = []  # [(name, fn(ctx) -> error_string|None)]

    def register(self, name: str, fn) -> None:
        self.invariants.append((name, fn))

    def assert_invariants(self, ctx: Context) -> None:
        for name, fn in self.invariants:
            err = fn(ctx)
            if err:
                raise AssertionError(f"invariant {name!r} broken: {err}")


def register_default_invariants(crisis: CrisisKeeper, app) -> None:
    """The supply and staking-pool invariants the reference's crisis module
    asserts (bank + staking module invariants)."""

    def supply_matches_balances(ctx: Context) -> str | None:
        total = 0
        for k, v in ctx.store.iterate_prefix(b"bank/bal/"):
            total += json.loads(v)
        supply = app.bank.supply(ctx)
        if total != supply:
            return f"sum of balances {total} != supply {supply}"
        return None

    def bonded_pool_covers_validators(ctx: Context) -> str | None:
        tokens = sum(
            json.loads(v)["tokens"]
            for _, v in ctx.store.iterate_prefix(b"staking/val/")
        )
        pool = app.bank.balance(ctx, BONDED_POOL)
        if pool < tokens:
            return f"bonded pool {pool} < validator tokens {tokens}"
        return None

    crisis.register("bank/supply", supply_matches_balances)
    crisis.register("staking/bonded-pool", bonded_pool_covers_validators)
