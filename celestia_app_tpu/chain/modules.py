"""State-machine modules: auth, bank, blob, mint, signal, minfee.

Staking lives in chain/staking.py (full delegation/unbonding mechanics);
governance + paramfilter in chain/gov.py.

Reference parity (SURVEY.md §2.1): x/blob (keeper/keeper.go:43-57, gas model
payforblob.go:158-179), x/mint time-based inflation (types/constants.go:17-25,
types/minter.go:56-66, abci.go:14-60), x/signal rolling upgrades
(keeper.go:18,26-36,65-116), x/minfee network floor price (params.go:16-27),
plus the SDK auth/bank/staking subset the reference wires through its
versioned module manager. Records are stored as canonical JSON under
per-module key prefixes (deterministic: sorted keys, no whitespace).
"""

from __future__ import annotations

import json

from celestia_app_tpu import appconsts
from celestia_app_tpu.chain.state import Context, get_json, put_json
from celestia_app_tpu.chain.staking import StakingKeeper  # full mechanics
from celestia_app_tpu.da import shares as shares_mod


def _put(ctx, key: bytes, obj) -> None:
    put_json(ctx, key, obj)


def _get(ctx, key: bytes):
    return get_json(ctx, key)


# ---------------------------------------------------------------------------
# auth: accounts with numbers and sequences
# ---------------------------------------------------------------------------


class AuthKeeper:
    PREFIX = b"auth/acc/"
    COUNTER = b"auth/next_account_number"

    def account(self, ctx: Context, addr: bytes):
        return _get(ctx, self.PREFIX + addr)

    def ensure_account(self, ctx: Context, addr: bytes):
        acc = self.account(ctx, addr)
        if acc is None:
            num = _get(ctx, self.COUNTER) or 0
            _put(ctx, self.COUNTER, num + 1)
            acc = {"number": num, "sequence": 0, "pubkey": None}
            _put(ctx, self.PREFIX + addr, acc)
        return acc

    def set_pubkey(self, ctx: Context, addr: bytes, pubkey: bytes) -> None:
        acc = self.ensure_account(ctx, addr)
        if acc["pubkey"] is None:
            acc["pubkey"] = pubkey.hex()
            _put(ctx, self.PREFIX + addr, acc)

    def increment_sequence(self, ctx: Context, addr: bytes) -> None:
        acc = self.ensure_account(ctx, addr)
        acc["sequence"] += 1
        _put(ctx, self.PREFIX + addr, acc)


# ---------------------------------------------------------------------------
# bank: balances in utia
# ---------------------------------------------------------------------------


class BankKeeper:
    PREFIX = b"bank/bal/"
    SUPPLY = b"bank/supply"

    def __init__(self):
        # optional VestingKeeper: when set, send() refuses to move locked
        # tokens — enforced HERE so fees, delegations, deposits, and any
        # future message all hit the same gate (no per-message special cases)
        self.vesting = None

    def balance(self, ctx: Context, addr: bytes) -> int:
        return _get(ctx, self.PREFIX + addr) or 0

    def set_balance(self, ctx: Context, addr: bytes, amount: int) -> None:
        _put(ctx, self.PREFIX + addr, amount)

    def send(self, ctx: Context, from_addr: bytes, to_addr: bytes, amount: int) -> None:
        if amount < 0:
            raise ValueError("negative send amount")
        if self.vesting is not None:
            self.vesting.check_spendable(ctx, self, from_addr, amount)
        bal = self.balance(ctx, from_addr)
        if bal < amount:
            raise ValueError(f"insufficient funds: {bal} < {amount}")
        self.set_balance(ctx, from_addr, bal - amount)
        self.set_balance(ctx, to_addr, self.balance(ctx, to_addr) + amount)

    def mint(self, ctx: Context, to_addr: bytes, amount: int) -> None:
        self.set_balance(ctx, to_addr, self.balance(ctx, to_addr) + amount)
        _put(ctx, self.SUPPLY, (self.supply(ctx)) + amount)

    def burn(self, ctx: Context, from_addr: bytes, amount: int) -> None:
        bal = self.balance(ctx, from_addr)
        if bal < amount:
            raise ValueError("insufficient funds to burn")
        self.set_balance(ctx, from_addr, bal - amount)
        _put(ctx, self.SUPPLY, self.supply(ctx) - amount)

    def supply(self, ctx: Context) -> int:
        return _get(ctx, self.SUPPLY) or 0


FEE_COLLECTOR = b"\x00" * 19 + b"\x01"  # module account for fees + inflation


# ---------------------------------------------------------------------------
# blob: the PayForBlobs module
# ---------------------------------------------------------------------------


class BlobKeeper:
    """x/blob: burns gas proportional to blob shares; blobs never touch state
    (keeper/keeper.go:43-57)."""

    PARAMS = b"blob/params"

    def params(self, ctx: Context) -> dict:
        return _get(ctx, self.PARAMS) or {
            "gas_per_blob_byte": appconsts.DEFAULT_GAS_PER_BLOB_BYTE,
            "gov_max_square_size": appconsts.DEFAULT_GOV_MAX_SQUARE_SIZE,
        }

    def set_params(self, ctx: Context, params: dict) -> None:
        _put(ctx, self.PARAMS, params)

    @staticmethod
    def gas_to_consume(blob_sizes, gas_per_blob_byte: int) -> int:
        """payforblob.go:158-165: shares x 512 x gasPerBlobByte."""
        total_shares = sum(shares_mod.sparse_shares_needed(s) for s in blob_sizes)
        return total_shares * appconsts.SHARE_SIZE * gas_per_blob_byte

    def pay_for_blobs(self, ctx: Context, msg) -> None:
        gas = self.gas_to_consume(
            msg.blob_sizes, self.params(ctx)["gas_per_blob_byte"]
        )
        ctx.gas_meter.consume(gas, "pay for blobs")
        ctx.emit_event(
            "celestia.blob.v1.EventPayForBlobs",
            signer=msg.signer.hex(),
            blob_sizes=list(msg.blob_sizes),
            namespaces=[n.hex() for n in msg.namespaces],
        )


def estimate_pfb_gas(blob_sizes, gas_per_blob_byte: int = appconsts.DEFAULT_GAS_PER_BLOB_BYTE) -> int:
    """Client-side linear gas model (payforblob.go:171-179)."""
    shares_gas = BlobKeeper.gas_to_consume(blob_sizes, gas_per_blob_byte)
    return (
        shares_gas
        + appconsts.BYTES_PER_BLOB_INFO * len(blob_sizes) * appconsts.versioned(2).tx_size_cost_per_byte
        + appconsts.PFB_GAS_FIXED_COST
    )


# ---------------------------------------------------------------------------
# mint: time-based inflation (x/mint)
# ---------------------------------------------------------------------------

# Inflation as an exact rational in parts-per-million: 8% = 80_000 ppm,
# shrinking ×0.9 per whole elapsed year, floored at 1.5% = 15_000 ppm
# (x/mint/types/constants.go:17-25). All minter state is integers so the
# app hash has no float semantics baked in.
INITIAL_INFLATION_PPM = 80_000
TARGET_INFLATION_PPM = 15_000
PPM = 1_000_000
SECONDS_PER_YEAR = 31_556_952  # 365.2425 days (constants.go DaysPerYear), exact


class MintKeeper:
    STATE = b"mint/minter"

    def minter(self, ctx: Context) -> dict:
        return _get(ctx, self.STATE) or {
            "inflation_ppm": INITIAL_INFLATION_PPM,
            "genesis_time": None,
            "previous_block_time": None,
            "annual_provisions": 0,
            "bond_denom": appconsts.BOND_DENOM,
        }

    def set_minter(self, ctx: Context, m: dict) -> None:
        _put(ctx, self.STATE, m)

    @staticmethod
    def inflation_rate_ppm(years_since_genesis: int) -> int:
        """constants.go:17-25: 8% × 0.9^floor(years), floored at 1.5% —
        computed exactly as 80_000 · 9^y / 10^y (floor)."""
        y = max(0, int(years_since_genesis))
        rate = INITIAL_INFLATION_PPM * 9**y // 10**y
        return max(rate, TARGET_INFLATION_PPM)

    def begin_blocker(self, ctx: Context, bank: BankKeeper) -> int:
        """Mint block provision ∝ wall-clock since last block (minter.go:56-66)."""
        m = self.minter(ctx)
        now = int(ctx.time_unix)
        if m["genesis_time"] is None:
            m["genesis_time"] = now
            m["previous_block_time"] = now
            m["annual_provisions"] = (
                m["inflation_ppm"] * bank.supply(ctx) // PPM
            )
            self.set_minter(ctx, m)
            return 0
        years = (now - m["genesis_time"]) // SECONDS_PER_YEAR
        m["inflation_ppm"] = self.inflation_rate_ppm(years)
        m["annual_provisions"] = m["inflation_ppm"] * bank.supply(ctx) // PPM
        elapsed = max(0, now - (m["previous_block_time"] or now))
        provision = m["annual_provisions"] * elapsed // SECONDS_PER_YEAR
        if provision > 0:
            bank.mint(ctx, FEE_COLLECTOR, provision)
            ctx.emit_event(
                "mint", amount=provision, inflation_ppm=m["inflation_ppm"]
            )
        m["previous_block_time"] = now
        self.set_minter(ctx, m)
        return provision


# ---------------------------------------------------------------------------
# signal: rolling upgrade coordination (x/signal)
# ---------------------------------------------------------------------------

UPGRADE_THRESHOLD_NUM = 5
UPGRADE_THRESHOLD_DEN = 6


class SignalKeeper:
    PREFIX = b"signal/sig/"
    UPGRADE = b"signal/pending_upgrade"

    def __init__(self, staking: StakingKeeper,
                 upgrade_height_delay: int | None = None):
        self.staking = staking
        # consensus-critical: every validator must be provisioned with the
        # same value (home config / genesis), like v2_upgrade_height
        self.upgrade_height_delay = (
            appconsts.DEFAULT_UPGRADE_HEIGHT_DELAY
            if upgrade_height_delay is None else int(upgrade_height_delay)
        )
        if self.upgrade_height_delay < 1:
            raise ValueError("upgrade_height_delay must be >= 1")

    def signal_version(self, ctx: Context, validator: bytes, version: int) -> None:
        if self.staking.validator_power(ctx, validator) == 0:
            raise ValueError("signal from unknown validator")
        if version <= ctx.app_version:
            raise ValueError(
                f"cannot signal version {version} <= current {ctx.app_version}"
            )
        if version > appconsts.LATEST_VERSION:
            raise ValueError(f"unsupported version {version}")
        _put(ctx, self.PREFIX + validator, {"version": version})

    def tally(self, ctx: Context, version: int) -> tuple[int, int]:
        voting = 0
        for k, v in ctx.store.iterate_prefix(self.PREFIX):
            if json.loads(v)["version"] == version:
                voting += self.staking.validator_power(ctx, k[len(self.PREFIX) :])
        return voting, self.staking.total_power(ctx)

    def try_upgrade(self, ctx: Context) -> bool:
        """keeper.go:96-116: >= 5/6 power on some version schedules it
        upgrade_height_delay blocks out."""
        if _get(ctx, self.UPGRADE) is not None:
            raise ValueError("upgrade already pending")
        for version in range(ctx.app_version + 1, appconsts.LATEST_VERSION + 1):
            power, total = self.tally(ctx, version)
            if total > 0 and power * UPGRADE_THRESHOLD_DEN >= total * UPGRADE_THRESHOLD_NUM:
                _put(
                    ctx,
                    self.UPGRADE,
                    {
                        "version": version,
                        "height": ctx.height + self.upgrade_height_delay,
                    },
                )
                ctx.emit_event("signal.upgrade_scheduled", version=version)
                return True
        return False

    def pending_upgrade(self, ctx: Context):
        return _get(ctx, self.UPGRADE)

    def should_upgrade(self, ctx: Context) -> int | None:
        """EndBlocker check (app/app.go:472-478): version to flip to, if due."""
        up = _get(ctx, self.UPGRADE)
        if up is not None and ctx.height >= up["height"]:
            return up["version"]
        return None

    def clear_upgrade(self, ctx: Context) -> None:
        ctx.store.delete(self.UPGRADE)
        for k, _ in list(ctx.store.iterate_prefix(self.PREFIX)):
            ctx.store.delete(k)


# ---------------------------------------------------------------------------
# minfee: network-wide minimum gas price (v2+)
# ---------------------------------------------------------------------------


class MinFeeKeeper:
    KEY = b"minfee/network_min_gas_price"  # int atto (1e18) utia-per-gas

    def network_min_gas_price_atto(self, ctx: Context) -> int:
        """The consensus value: integer atto units (1e18 per utia/gas)."""
        v = _get(ctx, self.KEY)
        if v is not None:
            return v
        return appconsts.gas_price_to_atto(appconsts.DEFAULT_NETWORK_MIN_GAS_PRICE)

    def network_min_gas_price(self, ctx: Context) -> float:
        """Display-only float view (status endpoints, logs)."""
        return self.network_min_gas_price_atto(ctx) / appconsts.ATTO  # lint: disable=det-float

    def set_network_min_gas_price(self, ctx: Context, price) -> None:
        """Accepts a float/decimal literal or an already-scaled int is NOT
        assumed: any numeric input is interpreted as utia-per-gas and scaled
        exactly (0.000001 → 10**12 atto)."""
        _put(ctx, self.KEY, appconsts.gas_price_to_atto(price))
