"""Single-process node: mempool + block production loop around the App.

Reference parity: test/util/testnode (an in-process chain producing blocks
against a real app via the local ABCI client, full_node.go:20-49) plus the
mempool behavior celestia tunes in app/default_overrides.go:258-284 (priority
mempool with per-tx TTL of 5 blocks, gas-price priority ordering).

The mempool itself is the content-addressable CAT pool
(celestia_app_tpu/mempool/pool.py) — hash-keyed dedup, byte+count caps with
lowest-priority eviction, TTL by height and wall-clock, and post-commit
recheck — shared with ValidatorNode and the autonomous reactor so all three
consumers have ONE admission path and ONE eviction policy. `self.mempool`
stays a list-shaped view for compatibility (tests, tools, status surfaces).
"""

from __future__ import annotations

import time as time_mod

from celestia_app_tpu import appconsts
from celestia_app_tpu.chain.app import App
from celestia_app_tpu.chain.block import Block, TxResult
from celestia_app_tpu.mempool.pool import (  # noqa: F401  (re-exports: the
    CATPool,      # historical import surface for these lived here)
    EntryView,
    PoolTx as MempoolTx,
    check_mempool_size,
    priority_order,
)

# heights of committed-tx lookups retained for GetTx/ConfirmTx; the
# reference's default lookback for confirmation polling is far shorter
COMMITTED_INDEX_WINDOW = 1000


def record_committed(index: dict, block: "Block", results) -> None:
    """THE committed-tx index recorder (tx-hash -> (height, result)), shared
    by Node and ValidatorNode so the gRPC GetTx/ConfirmTx contract stays
    single-sourced. Prunes entries older than COMMITTED_INDEX_WINDOW
    heights (amortized) so a long-lived validator process does not grow
    its index with the whole chain history."""
    import hashlib

    h = block.header.height
    for raw, res in zip(block.txs, results):
        index[hashlib.sha256(raw).digest()] = (h, res)
    if h % 50 == 0:
        floor = h - COMMITTED_INDEX_WINDOW
        if floor > 0:
            for key in [k for k, (hh, _r) in index.items() if hh <= floor]:
                del index[key]


class Node:
    def __init__(self, app: App,
                 mempool_ttl: int = appconsts.MEMPOOL_TX_TTL_BLOCKS,
                 mempool_max_txs: int = appconsts.MEMPOOL_MAX_TXS,
                 mempool_max_bytes: int = appconsts.MEMPOOL_MAX_POOL_BYTES,
                 mempool_ttl_seconds: float | None =
                 appconsts.MEMPOOL_TX_TTL_SECONDS):
        self.app = app
        self.pool = CATPool(
            max_pool_bytes=mempool_max_bytes,
            max_txs=mempool_max_txs,
            ttl_blocks=mempool_ttl,
            ttl_seconds=mempool_ttl_seconds,
        )
        self.mempool_ttl = mempool_ttl
        self.committed: dict[bytes, tuple[int, TxResult]] = {}  # tx hash -> (height, result)
        self.blocks: list[Block] = []

    # -- mempool -------------------------------------------------------

    @property
    def mempool(self) -> EntryView:
        """List-shaped view over the CAT pool (entries carry
        .raw/.gas_price/.height_added/.sender, the old MempoolTx shape)."""
        return EntryView(self.pool)

    @mempool.setter
    def mempool(self, items) -> None:
        """Compat for tests/tools that assign a replacement list; entries
        are re-admitted WITHOUT CheckTx (the caller already vouched)."""
        self.pool.clear()
        for it in items:
            raw = it.raw if hasattr(it, "raw") else it
            self.pool.add(raw, height=self.app.height)

    def broadcast_tx(self, raw: bytes) -> TxResult:
        """BroadcastMode_SYNC: CheckTx + CAT admission (size gate, hash
        dedup returning the original result, cap eviction) — the ONE
        admission path."""
        return self.pool.add(raw, height=self.app.height,
                             check_fn=self.app.check_tx)

    def broadcast_txs(self, raws) -> list[TxResult]:
        """Batched BroadcastMode_SYNC: one stateless prevalidation pass
        (admission plane phase 1 — a batched signature dispatch AND a
        batched blob-commitment dispatch), then the usual per-tx
        stateful CheckTx admission hitting the verified-sig and
        verified-commitment caches."""
        from celestia_app_tpu.chain import admission

        return self.pool.add_batch(
            raws, height=self.app.height, check_fn=self.app.check_tx,
            prevalidate_fn=lambda rs: admission.prevalidate(
                self.app, rs, check_state=True),
        )

    def _reap(self) -> list[bytes]:
        """Priority order: gas price desc, per-sender arrival order kept."""
        return self.pool.reap(self.app.height)

    # -- DAS serving (block plane) -------------------------------------

    def attach_das_core(self, core=None):
        """Create (or adopt) a DAS sample-serving core seeded by this
        node's commits: every committed height's EDS/DAH cache entry is
        handed over on the warmer's background thread with provers
        pre-built (da/edscache.py), so the first sample after a commit
        is pure index arithmetic. The canonical wiring for in-process
        embeddings (benches, tests, tools); the HTTP services register
        their own lock-guarded cores the same way."""
        if core is None:
            from celestia_app_tpu.das.server import SampleCore

            core = SampleCore(self.app)
        self.app.add_da_seed_listener(core.seed_cache_entry)
        return core

    # -- consensus loop ------------------------------------------------

    def produce_block(self, t: float | None = None) -> tuple[Block, list[TxResult]]:
        # the PROPOSER's clock is the protocol's source of header time
        # (same prerogative as App.prepare_proposal)
        t = t if t is not None else time_mod.time()  # lint: disable=det-wallclock
        # one root span for the whole round — prepare/process/finalize/
        # commit nest under it with the height's deterministic trace id
        from celestia_app_tpu import obs

        with obs.span(
            "block.produce", traces=self.app.traces,
            trace_id=obs.trace_id_for(self.app.chain_id,
                                      self.app.height + 1),
            height=self.app.height + 1,
        ):
            prop = self.app.prepare_proposal(self._reap(), t=t)
            if not self.app.process_proposal(prop.block):
                raise RuntimeError("node rejected its own proposal")
            results = self.app.finalize_block(prop.block)
            self.app.commit(prop.block)
            self.blocks.append(prop.block)

            self.pool.remove_committed(prop.block.txs)
            # post-commit recheck (RecheckTx): survivors re-run CheckTx
            # against the fresh check state; nonce-stale/now-unfunded txs
            # drop here instead of wasting the next proposal's slot
            self.pool.recheck(self.app.check_tx)
        record_committed(self.committed, prop.block, results)
        return prop.block, results

    def produce_blocks_batched(self, n_blocks: int, t: float | None = None,
                               t_step: int = 1):
        """Produce ``n_blocks`` consecutive blocks with the extends
        batched: the mempool reap is speculatively partitioned into
        per-block squares (chain/producer.plan_block_squares — the same
        deterministic greedy accounting prepare_proposal runs) and every
        planned square is extended in ONE batched device dispatch,
        seeding the EDS cache with device-resident entries. Each block
        then goes through the UNCHANGED produce_block round — identical
        block/app hashes to per-block production by construction; a plan
        the ante disagreed with just pays its own extend (counted
        ``producer.plan_misses``). Returns the list of (block, results)
        pairs."""
        from celestia_app_tpu.chain import producer
        from celestia_app_tpu.utils import telemetry

        try:
            plans = producer.plan_block_squares(self.app, self._reap(),
                                                n_blocks)
            producer.warm_block_batch(self.app, plans)
        except Exception as e:
            # the prefetch is never fatal (producer.py contract): a
            # failed batch dispatch degrades to per-block extends, the
            # same way plain produce_block would
            telemetry.incr("producer.prewarm_errors")
            from celestia_app_tpu import obs

            obs.get_logger("chain.node").warning(
                "batched produce prewarm failed; falling back to "
                "per-block extends", err=e)
        out = []
        for i in range(n_blocks):
            c0 = telemetry.snapshot()["counters"].get("da.extend_runs", 0)
            out.append(self.produce_block(
                t=None if t is None else t + i * t_step))
            c1 = telemetry.snapshot()["counters"].get("da.extend_runs", 0)
            if c1 > c0:
                # this height's square was not (or no longer) resident —
                # the round paid a normal per-block extend
                telemetry.incr("producer.plan_misses")
        return out

    def confirm_tx(self, raw: bytes):
        """ConfirmTx: drive blocks until the tx commits (tx_client.go:412)."""
        import hashlib

        h = hashlib.sha256(raw).digest()
        for _ in range(self.mempool_ttl + 1):
            if h in self.committed:
                return self.committed[h]
            self.produce_block()
        raise TimeoutError("tx not committed within TTL")
