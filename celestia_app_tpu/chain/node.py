"""Single-process node: mempool + block production loop around the App.

Reference parity: test/util/testnode (an in-process chain producing blocks
against a real app via the local ABCI client, full_node.go:20-49) plus the
mempool behavior celestia tunes in app/default_overrides.go:258-284 (priority
mempool with per-tx TTL of 5 blocks, gas-price priority ordering).
"""

from __future__ import annotations

import dataclasses
import time as time_mod

from celestia_app_tpu import appconsts
from celestia_app_tpu.chain.app import App
from celestia_app_tpu.chain.block import Block, TxResult
from celestia_app_tpu.chain.tx import Tx, decode_tx
from celestia_app_tpu.da import blob as blob_mod


@dataclasses.dataclass
class MempoolTx:
    raw: bytes
    gas_price: float
    height_added: int
    sender: bytes | None = None  # signer pubkey; keys per-sender FIFO


# heights of committed-tx lookups retained for GetTx/ConfirmTx; the
# reference's default lookback for confirmation polling is far shorter
COMMITTED_INDEX_WINDOW = 1000


def check_mempool_size(raw: bytes) -> "TxResult | None":
    """THE mempool byte-cap gate (MaxTxBytes, default_overrides.go:271-273),
    shared by Node and ValidatorNode admission so they can never disagree
    on which txs fit. None = within the cap."""
    if len(raw) > appconsts.MEMPOOL_MAX_TX_BYTES:
        return TxResult(1, "tx exceeds mempool max bytes", 0, 0, [])
    return None


def record_committed(index: dict, block: "Block", results) -> None:
    """THE committed-tx index recorder (tx-hash -> (height, result)), shared
    by Node and ValidatorNode so the gRPC GetTx/ConfirmTx contract stays
    single-sourced. Prunes entries older than COMMITTED_INDEX_WINDOW
    heights (amortized) so a long-lived validator process does not grow
    its index with the whole chain history."""
    import hashlib

    h = block.header.height
    for raw, res in zip(block.txs, results):
        index[hashlib.sha256(raw).digest()] = (h, res)
    if h % 50 == 0:
        floor = h - COMMITTED_INDEX_WINDOW
        if floor > 0:
            for key in [k for k, (hh, _r) in index.items() if hh <= floor]:
                del index[key]


def priority_order(items: list[tuple[bytes, float, bytes | None]]) -> list[bytes]:
    """Gas-price-descending reap that preserves PER-SENDER arrival order.

    `items` = [(raw, gas_price, sender)] in arrival order. A plain
    (-price, arrival) sort would let a sender's later high-fee tx jump its
    own earlier low-fee one — the later tx then fails the ante sequence
    check in the proposal filter and is pointlessly delayed a height. Here
    the sorted positions are kept, but each position is filled with the
    owning sender's OLDEST pending tx, so priority decides which sender
    goes first while nonces stay in submission order."""
    from collections import deque

    def key(i: int):
        sender = items[i][2]
        return sender if sender is not None else (b"raw", items[i][0])

    queues: dict = {}
    for i, (raw, _price, _sender) in enumerate(items):
        queues.setdefault(key(i), deque()).append(raw)
    order = sorted(range(len(items)), key=lambda i: (-items[i][1], i))
    return [queues[key(i)].popleft() for i in order]


class Node:
    def __init__(self, app: App, mempool_ttl: int = appconsts.MEMPOOL_TX_TTL_BLOCKS):
        self.app = app
        self.mempool: list[MempoolTx] = []
        self.mempool_ttl = mempool_ttl
        self.committed: dict[bytes, tuple[int, TxResult]] = {}  # tx hash -> (height, result)
        self.blocks: list[Block] = []

    # -- mempool -------------------------------------------------------

    def broadcast_tx(self, raw: bytes) -> TxResult:
        """BroadcastMode_SYNC: run CheckTx, admit to the mempool on success."""
        oversize = check_mempool_size(raw)
        if oversize is not None:
            return oversize
        res = self.app.check_tx(raw)
        if res.code == 0:
            btx = blob_mod.try_unmarshal_blob_tx(raw)  # single parse
            tx = decode_tx(btx.tx if btx is not None else raw)
            self.mempool.append(
                MempoolTx(
                    raw=raw,
                    gas_price=tx.body.fee / tx.body.gas_limit,
                    height_added=self.app.height,
                    sender=tx.pubkey,
                )
            )
        return res

    def _reap(self) -> list[bytes]:
        """Priority order: gas price desc, per-sender arrival order kept."""
        self.mempool = [
            m
            for m in self.mempool
            if self.app.height - m.height_added < self.mempool_ttl
        ]
        return priority_order(
            [(m.raw, m.gas_price, m.sender) for m in self.mempool]
        )

    # -- consensus loop ------------------------------------------------

    def produce_block(self, t: float | None = None) -> tuple[Block, list[TxResult]]:
        t = t if t is not None else time_mod.time()
        prop = self.app.prepare_proposal(self._reap(), t=t)
        if not self.app.process_proposal(prop.block):
            raise RuntimeError("node rejected its own proposal")
        results = self.app.finalize_block(prop.block)
        self.app.commit(prop.block)
        self.blocks.append(prop.block)

        included = set(prop.block.txs)
        self.mempool = [m for m in self.mempool if m.raw not in included]
        record_committed(self.committed, prop.block, results)
        return prop.block, results

    def confirm_tx(self, raw: bytes):
        """ConfirmTx: drive blocks until the tx commits (tx_client.go:412)."""
        import hashlib

        h = hashlib.sha256(raw).digest()
        for _ in range(self.mempool_ttl + 1):
            if h in self.committed:
                return self.committed[h]
            self.produce_block()
        raise TimeoutError("tx not committed within TTL")
