"""Blobstream EVM-side verification client (x/blobstream/client/verify.go).

The reference's `blobstream verify` CLI proves a tx/blob/share was committed
to by the Blobstream bridge: share proof → data root → DataRootTuple root →
the root STORED IN THE ETHEREUM CONTRACT (client/verify.go:27-38, contract
calls via go-ethereum). No Ethereum endpoint exists in this environment, so
the contract itself is modelled faithfully as a state machine
(`BlobstreamContract` — the blobstream-contracts `QuantumGravityBridge`
semantics): it tracks a validator-set checkpoint and only accepts a
DataRootTuple root carried by ≥2/3 of the checkpointed voting power's
signatures. Everything the reference verifies on-chain is verified here;
swap `BlobstreamContract` for a web3 binding and the client is the CLI.

Orchestrator signing: the contract's ecrecover analog — a signature counts
toward a valset member's power iff it was made by the KEY whose derived
address (default_evm_address of the key's account address) equals that
member's registered EVM address. A validator that registered a CUSTOM EVM
address (MsgRegisterEVMAddress) must therefore sign with the separate
orchestrator key owning that address, exactly as on Ethereum where the
registered address is recovered from the signature itself. A signature
from a non-member key or over a forged payload contributes zero power.
"""

from __future__ import annotations

import dataclasses
import hashlib

from celestia_app_tpu.chain import blobstream as bs
from celestia_app_tpu.chain.crypto import PublicKey
from celestia_app_tpu.utils import merkle_host


class ContractError(ValueError):
    pass


def valset_checkpoint(valset: bs.Valset) -> bytes:
    """The contract's domain-separated validator-set commitment."""
    h = hashlib.sha256(b"blobstream-valset-checkpoint")
    h.update(valset.nonce.to_bytes(8, "big"))
    for m in valset.members:
        h.update(m.power.to_bytes(8, "big"))
        h.update(m.evm_address)
    return h.digest()


def tuple_root_sign_digest(nonce: int, tuple_root: bytes) -> bytes:
    """What orchestrators sign for submitDataRootTupleRoot."""
    return hashlib.sha256(
        b"blobstream-tuple-root" + nonce.to_bytes(8, "big") + tuple_root
    ).digest()


@dataclasses.dataclass(frozen=True)
class OrchestratorSignature:
    pubkey: bytes  # 33-byte compressed secp256k1 (chain key)
    signature: bytes  # 64-byte r||s over the digest


class BlobstreamContract:
    """The deployed bridge contract's state machine."""

    def __init__(self, initial_valset: bs.Valset):
        self._checkpoint = valset_checkpoint(initial_valset)
        self._valset = initial_valset
        self._tuple_roots: dict[int, bytes] = {}
        self._last_nonce = 0

    def _tally(self, digest: bytes, sigs: list[OrchestratorSignature]) -> int:
        power = 0
        by_evm = {m.evm_address: m.power for m in self._valset.members}
        seen: set[bytes] = set()
        for s in sigs:
            pub = PublicKey(s.pubkey)
            evm = bs.default_evm_address(pub.address())
            if evm in seen or evm not in by_evm:
                continue
            if not pub.verify(s.signature, digest):
                continue
            seen.add(evm)
            power += by_evm[evm]
        return power

    def _require_two_thirds(self, digest: bytes,
                            sigs: list[OrchestratorSignature]) -> None:
        total = sum(m.power for m in self._valset.members)
        # strictly more than 2/3, matching the consensus certificate rule
        if self._tally(digest, sigs) * 3 <= total * 2:
            raise ContractError("insufficient voting power signed")

    def update_validator_set(
        self, new_valset: bs.Valset, sigs: list[OrchestratorSignature]
    ) -> None:
        """2/3 of the CURRENT set must sign the new checkpoint."""
        if new_valset.nonce <= self._valset.nonce:
            raise ContractError("valset nonce must increase")
        self._require_two_thirds(valset_checkpoint(new_valset), sigs)
        self._valset = new_valset
        self._checkpoint = valset_checkpoint(new_valset)

    def submit_data_root_tuple_root(
        self, nonce: int, tuple_root: bytes,
        sigs: list[OrchestratorSignature],
    ) -> None:
        if nonce <= self._last_nonce:
            raise ContractError("event nonce must increase")
        self._require_two_thirds(tuple_root_sign_digest(nonce, tuple_root), sigs)
        self._tuple_roots[nonce] = tuple_root
        self._last_nonce = nonce

    def data_root_tuple_root(self, nonce: int) -> bytes | None:
        return self._tuple_roots.get(nonce)


def verify_share_inclusion(
    contract: BlobstreamContract,
    nonce: int,
    height: int,
    data_root: bytes,
    share_proof,
    tuple_proof: merkle_host.Proof,
) -> bool:
    """The full verify.go chain: shares → data root → tuple root → the root
    the contract actually stores for `nonce`. Returns False on ANY broken
    link (never raises for verification failures)."""
    try:
        if not share_proof.verify(data_root):
            return False
        stored = contract.data_root_tuple_root(nonce)
        if stored is None:
            return False
        return bs.verify_data_root_inclusion(
            height, data_root, stored, tuple_proof
        )
    except (ValueError, TypeError, AttributeError):
        return False
