"""The application: celestia-app's ABCI surface rebuilt around the TPU pipeline.

Reference parity (SURVEY.md §3 call stacks):
- CheckTx           app/check_tx.go:16-54   — unwrap BlobTx, ValidateBlobTx,
                    strip blobs, run ante chain in check mode
- PrepareProposal   app/prepare_proposal.go:22-91 — ante-filter txs, build the
                    square, extend + DAH on device, return txs/size/data root
- ProcessProposal   app/process_proposal.go:24-158 — re-validate every tx,
                    deterministically reconstruct the square, recompute the
                    data root, byte-compare vs the header; ANY failure/panic
                    votes reject (liveness-first, :29-35)
- FinalizeBlock     BeginBlock (mint) -> DeliverTx each -> EndBlock (signal
                    upgrade flip, height-based v1->v2) -> Commit (app hash)
- multi-version behavior: one App serves versions 1..3 with per-version msg
  acceptance (ante.MSG_VERSIONS) and store migrations on upgrade
  (app/app.go:458-508 analog).

The square pipeline runs on device (da/eds.py, one jitted dispatch) when a
JAX backend is available, with a bit-identical host fallback (utils/refimpl).
"""

from __future__ import annotations

import dataclasses
import os
import time as time_mod

from celestia_app_tpu import appconsts
from celestia_app_tpu import obs
from celestia_app_tpu.obs import xfer
from celestia_app_tpu.chain import admission as admission_mod
from celestia_app_tpu.chain import ante as ante_mod
from celestia_app_tpu.chain import blobstream as blobstream_mod
from celestia_app_tpu.chain import gov as gov_mod
from celestia_app_tpu.chain import ibc as ibc_mod
from celestia_app_tpu.chain import sdk_modules
from celestia_app_tpu.chain import modules
from celestia_app_tpu.chain import storage
from celestia_app_tpu.utils import telemetry
from celestia_app_tpu.chain.block import Block, Header, TxResult
from celestia_app_tpu.chain.blob_validation import (
    BlobTxError,
    resolve_commitments,
    validate_blob_tx,
)
from celestia_app_tpu.chain.state import (
    Context,
    GasMeter,
    InfiniteGasMeter,
    KVStore,
    OutOfGas,
    put_json,
)
from celestia_app_tpu.chain.tx import (
    MsgPayForBlobs,
    MsgRegisterEVMAddress,
    MsgSend,
    MsgSignalVersion,
    MsgTryUpgrade,
    Tx,
    MsgDelegate,
    MsgUndelegate,
    MsgBeginRedelegate,
    MsgCreateValidator,
    MsgSubmitProposal,
    MsgDeposit,
    MsgVote,
    MsgTransfer,
    MsgExec,
    MsgRecvPacket,
    MsgAcknowledgePacket,
    MsgTimeoutPacket,
    MsgUpdateClient,
    decode_tx,
)
from celestia_app_tpu.da import blob as blob_mod
from celestia_app_tpu.da import codec as dacodec
from celestia_app_tpu.da import dah as dah_mod
from celestia_app_tpu.da import edscache as edscache_mod
from celestia_app_tpu.da import square as square_mod
from celestia_app_tpu.da.square import PfbEntry


@dataclasses.dataclass
class ProposalResult:
    block: Block
    square: square_mod.Square
    # the scheme's commitments object: a DataAvailabilityHeader under
    # the default codec, a da/cmt.CmtCommitments under cmt-ldpc
    dah: object


class App:
    def __init__(
        self,
        chain_id: str = "celestia-tpu-1",
        app_version: int = 1,
        engine: str = "auto",  # "device" | "host" | "auto" | "mesh"
        min_gas_price: float = appconsts.DEFAULT_MIN_GAS_PRICE,
        v2_upgrade_height: int | None = None,
        upgrade_height_delay: int | None = None,
        data_dir: str | None = None,
        invariant_check_period: int = 0,  # crisis: 0 = only at genesis/on demand
        da_scheme: str = "rs2d-nmt",  # DA commitment scheme (da/codec.py)
        # proof packs (das/packs.py): newest-N packs kept on disk
        # (0 = keep all, None = packs disabled); needs a data_dir
        pack_keep: int | None = None,
        # mesh plane: raise the versioned hard cap on the square size so
        # k=256/512 squares are admitted end to end (the gov param still
        # gates below it). CONSENSUS-CRITICAL like upgrade_height_delay:
        # every validator must carry the same value (home config key
        # `max_square_size`, never an env var). None = reference cap.
        max_square_size: int | None = None,
    ):
        self.invariant_check_period = invariant_check_period
        self.traces = telemetry.TraceTables()  # per-node trace tables (§5.1)
        self.absent_validators: set[bytes] = set()
        self.chain_id = chain_id
        self.app_version = app_version
        self.engine = engine
        # the codec plane: which construction data_hash commits under —
        # a consensus parameter (every validator of a chain must run the
        # same scheme; ProcessProposal rejects mismatched headers)
        self.codec = dacodec.get(da_scheme)
        # node-local (operator-set) min gas price; served by the gRPC node
        # Config route the reference's QueryMinimumGasPrice reads first
        self.min_gas_price = min_gas_price
        self.max_square_size = max_square_size
        if max_square_size is not None and max_square_size != \
                appconsts.square_size_upper_bound(app_version):
            if max_square_size < 1 \
                    or (max_square_size & (max_square_size - 1)) or \
                    max_square_size > appconsts.MAX_EXTENDED_SQUARE_WIDTH // 2:
                raise ValueError(
                    f"max_square_size must be a power of two <= "
                    f"{appconsts.MAX_EXTENDED_SQUARE_WIDTH // 2}, "
                    f"got {max_square_size}")
            # loud, same policy as upgrade_height_delay: the square-size
            # cap feeds ProcessProposal's accept/reject — divergent caps
            # fork the network at the first big block
            obs.get_logger("chain.app").warning(
                "max_square_size override active; every validator must "
                "be provisioned identically or the network forks at the "
                "first block exceeding the reference cap",
                chain_id=chain_id, max_square_size=max_square_size,
                reference=appconsts.square_size_upper_bound(app_version),
            )
        self.v2_upgrade_height = v2_upgrade_height
        self.store = KVStore()
        # durable storage: commits + blocks persist under data_dir; a
        # restarted App resumes at the latest committed height (see load()).
        self.db = storage.ChainDB(data_dir) if data_dir else None
        self.height = 0
        self.last_app_hash = self.store.app_hash()
        self.last_block_hash = b"\x00" * 32
        self.genesis_time: float | None = None
        self.last_block_time: float | None = None

        self.auth = modules.AuthKeeper()
        self.bank = modules.BankKeeper()
        self.blob = modules.BlobKeeper()
        self.mint = modules.MintKeeper()
        self.staking = modules.StakingKeeper(self.bank)
        if (upgrade_height_delay is not None and upgrade_height_delay
                != appconsts.DEFAULT_UPGRADE_HEIGHT_DELAY):
            # loud, per ADVICE r5: a delay override is consensus-critical
            # — every validator in the network must carry the same one
            obs.get_logger("chain.app").warning(
                "upgrade_height_delay override active; every validator "
                "must be provisioned identically or the network forks "
                "at the x/signal flip",
                chain_id=chain_id, delay=upgrade_height_delay,
                default=appconsts.DEFAULT_UPGRADE_HEIGHT_DELAY,
            )
        self.signal = modules.SignalKeeper(
            self.staking, upgrade_height_delay=upgrade_height_delay
        )
        self.minfee = modules.MinFeeKeeper()
        self.blobstream = blobstream_mod.BlobstreamKeeper(self.staking)
        self.staking.hooks.append(self.blobstream)
        # gov param routing: every governable param goes through here;
        # x/paramfilter's blocklist is enforced inside GovKeeper
        def _require(value, kind, lo, hi):
            """Setters validate types/ranges: a passed proposal must never be
            able to write a value that breaks the state machine (the
            paramfilter guards WHICH params change; this guards to WHAT)."""
            if kind is int and (type(value) is not int):
                raise ValueError(f"param value {value!r} must be an integer")
            if kind is float and not isinstance(value, (int, float)) \
                    or isinstance(value, bool):
                raise ValueError(f"param value {value!r} must be numeric")
            if not (lo <= value <= hi):
                raise ValueError(f"param value {value!r} out of range [{lo}, {hi}]")
            return value

        def _blob_param(key, lo, hi):
            def setter(ctx, value):
                params = self.blob.params(ctx)
                params[key] = _require(value, int, lo, hi)
                self.blob.set_params(ctx, params)
            return setter

        def _gov_param(key, lo, hi):
            # periods are stored as whole seconds: ints only, so no float
            # ever reaches the gov/params app-hash preimage
            def setter(ctx, value):
                params = self.gov.params(ctx)
                params[key] = _require(value, int, lo, hi)
                self.gov.set_params(ctx, params)
            return setter

        # gov_max_square_size's validation bound is CONSENSUS-visible
        # (a param-change tx above it fails; divergent bounds would
        # diverge tx results and fork): it must be THIS CHAIN's hard
        # cap — the reference bound (128) unless the chain opted into
        # the mesh plane's max_square_size — never the plumbing-wide
        # MAX_EXTENDED_SQUARE_WIDTH, which admits sizes this chain
        # refuses to build
        gov_square_cap = (
            max_square_size if max_square_size is not None
            else appconsts.square_size_upper_bound(app_version))
        param_router = {
            "blob/gas_per_blob_byte": _blob_param("gas_per_blob_byte", 1, 1 << 20),
            "blob/gov_max_square_size": _blob_param(
                "gov_max_square_size", 1, gov_square_cap
            ),
            # gas prices are sdk.Dec-shaped floats end to end (see the
            # det-float waiver on wire/txpb.py in analyze.toml)
            "minfee/network_min_gas_price": lambda ctx, v:
                self.minfee.set_network_min_gas_price(
                    ctx, _require(v, float, 0.0, 1e12)  # lint: disable=det-float
                ),
            "blobstream/data_commitment_window": lambda ctx, v:
                self.blobstream.set_data_commitment_window(
                    ctx, _require(v, int, 100, 10_000)
                ),
            "gov/min_deposit": lambda ctx, v: _gov_min_deposit(ctx, v),
            "gov/voting_period": _gov_param("voting_period", 1, 10**9),
            "gov/max_deposit_period": _gov_param("max_deposit_period", 1, 10**9),
        }

        def _gov_min_deposit(ctx, v):
            params = self.gov.params(ctx)
            params["min_deposit"] = _require(v, int, 1, 1 << 62)
            self.gov.set_params(ctx, params)
        self.gov = gov_mod.GovKeeper(self.staking, self.bank, param_router)
        def _ica_router(ctx, msg: dict, signer: bytes) -> None:
            """Execute an allowlisted ICA msg with the interchain account as
            the effective signer (ICS-27 host execution)."""
            t = msg.get("type")
            if t == "bank/MsgSend":
                self.bank.send(ctx, signer, bytes.fromhex(msg["to"]),
                               int(msg["amount"]))
            elif t == "staking/MsgDelegate":
                self.staking.delegate(ctx, bytes.fromhex(msg["validator"]),
                                      signer, int(msg["amount"]))
            elif t == "staking/MsgUndelegate":
                self.staking.undelegate(ctx, bytes.fromhex(msg["validator"]),
                                        signer, int(msg["amount"]))
            elif t == "gov/MsgVote":
                self.gov.vote(ctx, int(msg["proposal_id"]), signer,
                              msg["option"])
            else:  # the keeper's allowlist already rejected anything else
                raise ValueError(f"unroutable ICA msg {t!r}")

        self.ibc = ibc_mod.IBCStack(self.bank, ica_router=_ica_router)

        self.distribution = sdk_modules.DistributionKeeper(self.staking, self.bank)
        self.slashing = sdk_modules.SlashingKeeper(self.staking)
        self.authz = sdk_modules.AuthzKeeper()
        self.feegrant = sdk_modules.FeeGrantKeeper()
        self.vesting = sdk_modules.VestingKeeper()
        self.crisis = sdk_modules.CrisisKeeper()
        sdk_modules.register_default_invariants(self.crisis, self)
        self.bank.vesting = self.vesting  # locked funds gate inside bank.send
        self.staking.hooks.append(self.distribution)  # F1 settlement hook

        # versioned module manager (app/module/manager.go analog): each
        # module declares its [From,To] app-version range; Begin/EndBlock
        # and migrations dispatch through it (see finalize_block/_migrate)
        from celestia_app_tpu.chain.module_manager import (
            ModuleManager,
            VersionedModule,
        )

        def _slashing_liveness(ctx):
            # liveness from the last commit: validators in
            # self.absent_validators are treated as not signing (the
            # single-process analog of LastCommitInfo)
            for op, _power in self.staking.validators(ctx):
                self.slashing.handle_signature(
                    ctx, op, signed=op not in self.absent_validators
                )

        mm = ModuleManager()
        mm.register(VersionedModule(
            "mint", 1, appconsts.LATEST_VERSION,
            begin_block=lambda ctx: self.mint.begin_blocker(ctx, self.bank),
        ))
        mm.register(VersionedModule(
            "distribution", 1, appconsts.LATEST_VERSION,
            begin_block=self.distribution.allocate,
        ))
        mm.register(VersionedModule(
            "slashing", 1, appconsts.LATEST_VERSION,
            begin_block=_slashing_liveness,
        ))
        mm.register(VersionedModule(
            "staking", 1, appconsts.LATEST_VERSION,
            end_block=self.staking.end_blocker,
        ))
        mm.register(VersionedModule(
            "gov", 1, appconsts.LATEST_VERSION,
            end_block=self.gov.end_blocker,
        ))
        mm.register(VersionedModule(
            "blobstream", 1, 1,  # v1 only (app/modules.go:171)
            end_block=self.blobstream.end_blocker,
            on_exit=lambda ctx: [
                ctx.store.delete(k)
                for k, _ in list(ctx.store.iterate_prefix(b"blobstream/"))
            ],
        ))
        mm.register(VersionedModule(
            "minfee", 2, appconsts.LATEST_VERSION,  # v2+ (modules.go)
            on_enter=lambda ctx: self.minfee.set_network_min_gas_price(
                ctx, appconsts.DEFAULT_NETWORK_MIN_GAS_PRICE
            ),
        ))
        mm.register(VersionedModule("signal", 2, appconsts.LATEST_VERSION))
        # registration order IS the dispatch order (setModuleOrder analog:
        # one source of truth; use set_begin_order/set_end_order only to
        # diverge from it)
        self.module_manager = mm
        # the admission plane's verified-sig cache: a (pubkey, sig,
        # sign-doc) triple verified once — batched at CheckTx or block
        # prevalidation, or scalar in the ante — is never verified again
        # in any later phase. State-independent, so rollback/load leave it.
        self.sig_cache = admission_mod.VerifiedSigCache()
        # the traffic plane's verified-commitment cache: a blob whose
        # share commitment was computed once — batched at admission
        # prevalidation or per blob in validate_blob_tx — is never
        # recomputed at CheckTx/Prepare/Process/Finalize/replay; every
        # phase still byte-compares the cached TRUE value against the
        # tx's claim, so Byzantine mismatches reject warm or cold.
        # State-independent (pure content hash), like the sig cache.
        self.commitment_cache = admission_mod.VerifiedCommitmentCache()
        # the block plane's extend-once machinery (da/edscache.py):
        # a content-addressed LRU of (EDS, DAH, data root) keyed by the
        # ODS share bytes — prepare, process, finalize/commit, the query
        # router, and the DAS serving plane all read the same entry, so
        # extend+commit dispatches at most once per (node, height).
        # State-independent (pure function of the key): rollback/load
        # leave it, exactly like the sig cache.
        self.eds_cache = edscache_mod.EdsCache()
        # DAS planes (das/server.SampleCore) register seed_cache_entry
        # here (via add_da_seed_listener); commit hands each committed
        # entry over on the warmer's background thread, never under a
        # service/consensus lock
        self.da_seed_listeners: list = []
        self.da_warmer = edscache_mod.ProverWarmer()
        # boundary observatory (obs/xfer.py): cumulative ledger mark at
        # the previous commit — each commit's delta is the ROADMAP-item-2
        # gauge host_bytes_crossed_per_block (surfaced in /metrics and
        # the status reactor block); process-wide totals, so in-process
        # multi-node tests read it on single-node fixtures
        self._xfer_mark = xfer.bytes_crossed()
        self.last_host_bytes_crossed = 0
        # serving plane (das/packs.py): disk-backed nodes precompute a
        # static proof pack per warm height under <home>/packs, pruned
        # keep-newest-N; in-memory nodes serve live assembly only.
        # pack_keep=0 keeps every pack; None disables packs entirely.
        self.pack_store = None
        if self.db is not None and pack_keep is not None:
            from celestia_app_tpu.das import packs as packs_mod

            self.pack_store = packs_mod.PackStore(
                os.path.join(os.path.dirname(os.path.abspath(self.db.dir)),
                             packs_mod.PACK_DIRNAME),
                keep=pack_keep,
            )
        # read plane (das/blob_packs.py): per-namespace blob-read packs
        # under <home>/blobpacks, built at warm time beside the sample
        # packs, same keep-newest-N bound
        self.blob_pack_store = None
        if self.db is not None and pack_keep is not None:
            from celestia_app_tpu.das import blob_packs as blob_packs_mod

            self.blob_pack_store = blob_packs_mod.BlobPackStore(
                os.path.join(os.path.dirname(os.path.abspath(self.db.dir)),
                             blob_packs_mod.BLOB_PACK_DIRNAME),
                keep=pack_keep,
            )
        self.ante = ante_mod.AnteHandler(
            self.auth, self.bank, self.blob, self.minfee, min_gas_price,
            feegrant=self.feegrant, ibc=self.ibc,
            sig_cache=self.sig_cache,
        )
        # committed-state snapshots for load_height rollback (app/app.go:592);
        # when a ChainDB is attached the window lives on disk instead
        self._history: dict[int, dict] = {}
        # baseapp checkState: a cache branch over committed state that
        # accumulates CheckTx effects; reset at every commit
        self._check_state = None
        # bumped on every rollback/resume so read-side caches (QueryRouter)
        # can invalidate without re-reading disk
        self.state_generation = 0

    # ------------------------------------------------------------------
    # pipeline selection
    # ------------------------------------------------------------------

    def enable_store_trace(self, path: str) -> None:
        """Commit-multistore tracer analog (ref app/app.go:194
        SetCommitMultiStoreTracer + cmd/root.go:243 --trace): every
        committed store write/delete appends a JSON line
        {op, key, len, height} to `path`. Line-buffered so a crash loses
        at most the current line."""
        import json as json_mod

        self._trace_f = open(path, "a", buffering=1)

        def tracer(op: str, key: bytes, vlen: int) -> None:
            executing = getattr(self, "_executing_height", None)
            self._trace_f.write(json_mod.dumps({
                "op": op, "key": key.hex(), "len": vlen,
                "height": executing if executing is not None else self.height,
            }) + "\n")

        self.store.tracer = tracer

    def close(self) -> None:
        """Release durable-storage handles (the native engine holds a
        writer flock; an App replaced in-process — reborn-validator tests,
        rollback tooling — must release it before a successor opens)."""
        if self.db is not None:
            self.db.close()
        f = getattr(self, "_trace_f", None)
        if f is not None:
            self.store.tracer = None
            self._trace_f = None
            try:
                f.close()
            except OSError:
                pass

    def _data_root(self, square: square_mod.Square):
        """(commitments, data_root) for a square — through the
        extend-once cache: the first caller for a given (scheme, ODS)
        content pays the real encode dispatch (da/edscache.compute_entry
        routed through the codec plane: the 2D-RS+NMT pipeline or the
        CMT layer build, device when possible, the bit-identical host
        path otherwise); every later phase of the lifecycle —
        ProcessProposal re-validating what PrepareProposal built, a
        proposer re-validating its own gossip, the query router, the DAS
        server — hits the same entry. The commitments object is the
        scheme's (a DataAvailabilityHeader or CmtCommitments); its
        ``hash()`` is the data root either way."""
        scheme = self.codec.name
        ods = dah_mod.shares_to_ods(square.share_bytes())
        key = edscache_mod.cache_key(ods, scheme)
        entry = self.eds_cache.get(key)
        if entry is None:
            # one span covers the fused device program: RS extension + NMT
            # axis roots + data root land in a single dispatch (da/eds.py),
            # so finer stage attribution needs /debug/profile, not spans
            with obs.span("da.extend_shares", k=square.size,
                          engine=self.engine, scheme=scheme,
                          stages="extend+nmt+root"):
                entry = self.eds_cache.put(
                    key,
                    edscache_mod.compute_entry(ods, self.engine, scheme),
                )
        return entry.dah, entry.data_root

    # ------------------------------------------------------------------
    # genesis
    # ------------------------------------------------------------------

    def init_chain(self, genesis: dict) -> None:
        """genesis = {accounts: [{address(hex), balance, sequence?}],
        validators: [{operator(hex), power}], time_unix, params...}.
        Documents produced by export_genesis additionally carry
        ``raw_modules`` (verbatim module state: delegations, params, grants,
        attestations, ...), which replaces the fresh-validator setup and
        restores account sequences so old-chain txs cannot replay."""
        ctx = self._deliver_ctx(InfiniteGasMeter())
        # a missing genesis time must NOT fall back to the wall clock:
        # every validator initializing the same genesis doc must compute
        # identical state (the analyzer's det-wallclock rule)
        self.genesis_time = genesis.get("time_unix", 0)
        if "raw_modules" in genesis:
            # verbatim module restore FIRST — auth/ (account numbers,
            # pubkeys, sequences, the next-number counter) must be in place
            # before ensure_account runs, or fresh numbers would collide
            # with restored ones
            for khex, vhex in genesis["raw_modules"].items():
                ctx.store.set(bytes.fromhex(khex), bytes.fromhex(vhex))
            # height-anchored module state (blobstream ranges, unbonding
            # heights) stays consistent by resuming the height counter
            self.height = genesis.get("exported_height", 0)
        for acc in genesis.get("accounts", []):
            addr = bytes.fromhex(acc["address"])
            record = self.auth.ensure_account(ctx, addr)
            self.bank.mint(ctx, addr, acc["balance"])
            seq = acc.get("sequence", 0)
            if seq and record["sequence"] < seq:
                # hand-authored genesis without raw_modules can still pin
                # sequences (anti-replay); verbatim auth restore wins if both
                record["sequence"] = seq
                put_json(ctx, self.auth.PREFIX + addr, record)
        if "raw_modules" not in genesis:
            for val in genesis.get("validators", []):
                self.staking.set_validator(
                    ctx, bytes.fromhex(val["operator"]), val["power"]
                )
            if "gov_max_square_size" in genesis:
                p = self.blob.params(ctx)
                p["gov_max_square_size"] = genesis["gov_max_square_size"]
                self.blob.set_params(ctx, p)
        ctx.store.write()
        # genesis invariant assertion (crisis module's init-genesis check)
        check_ctx = self._ctx(self.store, InfiniteGasMeter(), check=False)
        self.crisis.assert_invariants(check_ctx)
        self.last_app_hash = self.store.app_hash()

    # ------------------------------------------------------------------
    # helpers
    # ------------------------------------------------------------------

    def add_da_seed_listener(self, fn) -> None:
        """Register a commit-seed listener (idempotent: re-attaching the
        same bound method — a double attach_das_core — must not
        double-seed every commit). A service being replaced over a
        long-lived App must deregister its old core via
        remove_da_seed_listener (the services do, in shutdown())."""
        if fn not in self.da_seed_listeners:
            self.da_seed_listeners.append(fn)

    def remove_da_seed_listener(self, fn) -> None:
        """Deregister a commit-seed listener; absent entries are a no-op
        (shutdown paths must be idempotent)."""
        try:
            self.da_seed_listeners.remove(fn)
        except ValueError:
            pass

    def _chain_time(self) -> float:
        """Deterministic time anchor for contexts not given an explicit
        block time (check-tx, queries, simulation): the last committed
        block's header time, before any block the genesis time. A wall-
        clock read here would let two nodes disagree on check/query
        results for the same state (det-wallclock)."""
        if self.last_block_time is not None:
            return self.last_block_time
        return self.genesis_time or 0

    def _ctx(self, store, gas_meter, *, check: bool, height=None, t=None) -> Context:
        return Context(
            store,
            gas_meter,
            height if height is not None else self.height + 1,
            t if t is not None else self._chain_time(),
            self.chain_id,
            self.app_version,
            is_check_tx=check,
        )

    def _deliver_ctx(self, gas_meter, height=None, t=None) -> Context:
        return self._ctx(self.store.branch(), gas_meter, check=False, height=height, t=t)

    def max_effective_square_size(self, ctx: Context) -> int:
        """min(gov param, hard cap) — app/square_size.go:9-23. The hard
        cap is the versioned reference bound unless the mesh plane's
        consensus-critical ``max_square_size`` override raises it (the
        k=256/512 admission path; see __init__)."""
        hard = (self.max_square_size if self.max_square_size is not None
                else appconsts.square_size_upper_bound(self.app_version))
        return min(self.blob.params(ctx)["gov_max_square_size"], hard)

    # ------------------------------------------------------------------
    # CheckTx (mempool admission)
    # ------------------------------------------------------------------

    def check_tx(self, raw: bytes) -> TxResult:
        """Mempool admission against a PERSISTENT check state (baseapp's
        checkState, reset on every commit): successive txs from one account
        observe each other's sequence bumps and fee deductions, so a client
        can queue several txs between blocks (app/check_tx.go semantics)."""
        if self._check_state is None:
            self._check_state = self.store.branch()
        ctx = self._ctx(self._check_state.branch(), GasMeter(1 << 40), check=True)
        threshold = appconsts.subtree_root_threshold(self.app_version)
        try:
            btx = blob_mod.try_unmarshal_blob_tx(raw)  # single parse
            if btx is not None:
                tx, _ = validate_blob_tx(btx, threshold,
                                         cache=self.commitment_cache)
            else:
                tx = decode_tx(raw)
                if any(isinstance(m, MsgPayForBlobs) for m in tx.body.msgs):
                    raise BlobTxError("MsgPayForBlobs without blobs (ErrNoBlobs)")
            gas = GasMeter(tx.body.gas_limit)
            ctx.gas_meter = gas
            self.ante.run(ctx, tx)
            ctx.store.write()  # admitted: later CheckTx sees the state
            return TxResult(0, "", tx.body.gas_limit, gas.consumed, ctx.events)
        except (ante_mod.AnteError, BlobTxError, OutOfGas, ValueError) as e:
            return TxResult(1, str(e), 0, ctx.gas_meter.consumed, [])

    # ------------------------------------------------------------------
    # PrepareProposal (proposer)
    # ------------------------------------------------------------------

    def prepare_proposal(
        self, raw_txs: list[bytes], proposer: bytes = b"", t: float | None = None
    ) -> ProposalResult:
        _t0 = telemetry.start_timer()
        # the PROPOSER's wall clock is the protocol's source of header
        # time (Tendermint BFT-time analog); every other node consumes
        # block.header.time_unix verbatim
        t = t if t is not None else time_mod.time()  # lint: disable=det-wallclock,det-reach
        height = self.height + 1
        # root span of the block lifecycle: the trace id derives from
        # (chain_id, height), so followers and DAS light nodes stamp the
        # SAME id with no coordination (docs/DESIGN.md observability)
        with obs.span(
            "prepare_proposal", traces=self.traces,
            trace_id=obs.trace_id_for(self.chain_id, height),
            height=height, n_candidates=len(raw_txs),
        ) as sp:
            out = self._prepare_inner(raw_txs, proposer, t, height, sp)
        telemetry.measure_since("prepare_proposal", _t0)
        return out

    def _prepare_inner(self, raw_txs: list[bytes], proposer: bytes,
                       t: float, height: int, sp) -> ProposalResult:
        threshold = appconsts.subtree_root_threshold(self.app_version)

        # Split first; ante-filter ALL normal txs before ANY blob tx, exactly
        # mirroring FilterTxs (validate_txs.go:32-98). This ordering is what
        # makes ProcessProposal's replay (block order: normal then blob)
        # observe identical sequence numbers.
        normal_candidates: list[bytes] = []
        blob_candidates: list[tuple[bytes, PfbEntry]] = []
        for raw in raw_txs:
            try:
                btx = blob_mod.try_unmarshal_blob_tx(raw)  # single parse
            except ValueError:
                continue
            if btx is not None:
                try:
                    validate_blob_tx(btx, threshold,
                                     cache=self.commitment_cache)
                    blob_candidates.append((raw, PfbEntry(btx.tx, btx.blobs)))
                except (BlobTxError, ValueError):
                    continue
            else:
                try:
                    tx = decode_tx(raw)
                except ValueError:
                    continue
                if any(isinstance(m, MsgPayForBlobs) for m in tx.body.msgs):
                    continue  # PFB without blobs never enters a block
                normal_candidates.append(raw)

        def ante_filter(
            normals: list[bytes], blobs: list[tuple[bytes, PfbEntry]]
        ) -> tuple[list[bytes], list[tuple[bytes, PfbEntry]]]:
            ctx = self._ctx(
                self.store.branch(), InfiniteGasMeter(), check=False,
                height=height, t=t,
            )
            kept_n, kept_b = [], []
            for raw in normals:
                tx = decode_tx(raw)
                per_tx = ctx.branch()
                per_tx.gas_meter = GasMeter(tx.body.gas_limit)
                try:
                    self.ante.run(per_tx, tx)
                    per_tx.store.write()
                    kept_n.append(raw)
                except (ante_mod.AnteError, OutOfGas, ValueError):
                    continue
            for raw, entry in blobs:
                tx = decode_tx(entry.tx)
                per_tx = ctx.branch()
                per_tx.gas_meter = GasMeter(tx.body.gas_limit)
                try:
                    self.ante.run(per_tx, tx)
                    per_tx.store.write()
                    kept_b.append((raw, entry))
                except (ante_mod.AnteError, OutOfGas, ValueError):
                    continue
            return kept_n, kept_b

        normal_txs, kept_blobs = ante_filter(normal_candidates, blob_candidates)
        max_sq = self.max_effective_square_size(
            self._ctx(self.store.branch(), InfiniteGasMeter(), check=False)
        )
        # square.build may drop txs; admission (sequence chain) depends on the
        # final tx set, so re-filter and rebuild until a fixed point.
        with obs.span("square.build"):
            while True:
                square = square_mod.build(
                    normal_txs, [e for _, e in kept_blobs], max_sq, threshold
                )
                kept_tx_set = set(square.txs)
                kept_pfb_set = {e.tx for e in square.pfbs}
                next_normals = [r for r in normal_txs if r in kept_tx_set]
                next_blobs = [(r, e) for r, e in kept_blobs
                              if e.tx in kept_pfb_set]
                if (len(next_normals) == len(normal_txs)
                        and len(next_blobs) == len(kept_blobs)):
                    break
                normal_txs, kept_blobs = ante_filter(next_normals, next_blobs)
        kept_blob_raws = [r for r, _ in kept_blobs]
        d, root = self._data_root(square)

        header = Header(
            chain_id=self.chain_id,
            height=height,
            time_unix=t,
            data_hash=root,
            square_size=square.size,
            app_hash=self.last_app_hash,
            proposer=proposer,
            app_version=self.app_version,
            last_block_hash=self.last_block_hash,
            validators_hash=self._validators_hash(),
            da_scheme=self.codec.scheme_id,
        )
        block = Block(header=header, txs=tuple(square.txs + kept_blob_raws))
        sp.set(n_txs=len(block.txs), square_size=square.size)
        return ProposalResult(block=block, square=square, dah=d)

    def _validators_hash(self) -> bytes:
        """Commitment to the current (operator, power) set — the header's
        ValidatorsHash analog light clients verify certificates against."""
        from celestia_app_tpu.chain.block import validators_hash_of

        ctx = self._ctx(self.store.branch(), InfiniteGasMeter(), check=False)
        return validators_hash_of(self.staking.validators(ctx))

    # ------------------------------------------------------------------
    # ProcessProposal (every validator)
    # ------------------------------------------------------------------

    def process_proposal(self, block: Block) -> bool:
        """True = accept. Any validation failure or internal panic rejects
        (process_proposal.go:29-35 defer/recover)."""
        _t0 = telemetry.start_timer()
        try:
            with obs.span(
                "process_proposal", traces=self.traces,
                trace_id=obs.trace_id_for(self.chain_id,
                                          block.header.height),
                height=block.header.height, n_txs=len(block.txs),
            ):
                self._process_proposal_inner(block)
            telemetry.incr("process_proposal.accepted")
            return True
        except Exception:
            telemetry.incr("process_proposal.rejected")
            return False
        finally:
            telemetry.measure_since("process_proposal", _t0)

    def _process_proposal_inner(self, block: Block) -> None:
        threshold = appconsts.subtree_root_threshold(self.app_version)
        h = block.header
        if h.chain_id != self.chain_id or h.height != self.height + 1:
            raise ValueError("wrong chain or height")
        if h.app_version != self.app_version:
            raise ValueError("app version mismatch")
        if h.app_hash != self.last_app_hash:
            raise ValueError("app hash mismatch")
        if h.validators_hash != self._validators_hash():
            # the proposer must commit to the SAME valset every honest
            # validator derives from state — a forged commitment would let
            # light clients be pointed at a fake set
            raise ValueError("validators hash mismatch")
        if h.da_scheme != self.codec.scheme_id:
            # the DA scheme is a consensus parameter: a proposer running
            # a different codec would commit a data root this node can
            # neither recompute nor sample — reject before paying for
            # the (wrong-scheme) encode below
            raise ValueError(
                f"DA scheme mismatch: header {h.da_scheme}, "
                f"node runs {self.codec.scheme_id} ({self.codec.name})")

        ctx = self._ctx(
            self.store.branch(), InfiniteGasMeter(), check=False,
            height=h.height, t=h.time_unix,
        )
        # admission plane, phase 1: verify the whole block's signatures
        # in one batched dispatch; the per-tx ante runs below then hit
        # the verified-sig cache (CheckTx-admitted txs are already in it
        # and are not re-verified here at all). commitments=False: the
        # resolve_commitments pass below is THE one keyed trip through
        # the commitment cache for this block — running the prevalidate
        # half too would sha256 every blob's bytes twice
        admission_mod.prevalidate(self, block.txs, commitments=False)
        normal_txs: list[bytes] = []
        pfb_entries: list[PfbEntry] = []
        # Batch all blob commitments of the block in one device pass
        # (da/commitment_device.py) instead of per-blob host recomputation.
        parsed: dict[int, object] = {}
        all_blobs: list = []
        seen_blob_scan = False
        for i, raw in enumerate(block.txs):
            try:
                btx = blob_mod.try_unmarshal_blob_tx(raw)  # single parse
            except ValueError as e:
                raise ValueError(f"undecodable blob tx: {e}") from None
            if btx is not None:
                seen_blob_scan = True
                parsed[i] = btx
                all_blobs.extend(btx.blobs)
            elif seen_blob_scan:
                # cheap reject before paying the device commitment batch
                raise ValueError("normal tx after blob tx (ordering violation)")
        # traffic plane: commitments resolve through the verified-
        # commitment cache — CheckTx-admitted blobs (and the proposer's
        # own PrepareProposal pass) cost lookups here; only a cold
        # follower pays one batched compute, which then fills the cache
        # for finalize/replay/its own later proposals.
        all_commitments = resolve_commitments(all_blobs, threshold,
                                              engine=self.engine,
                                              cache=self.commitment_cache)
        cursor = 0
        for i, raw in enumerate(block.txs):
            if i in parsed:
                btx = parsed[i]
                n = len(btx.blobs)
                tx, _ = validate_blob_tx(
                    btx, threshold, all_commitments[cursor : cursor + n]
                )
                cursor += n
                # the full ante chain runs for blob txs too — sig, fee funds,
                # sequence (process_proposal.go:100-117); block order (normal
                # before blob) matches PrepareProposal's filter order, so the
                # sequence chain observed here is identical.
                per_tx = ctx.branch()
                per_tx.gas_meter = GasMeter(tx.body.gas_limit)
                self.ante.run(per_tx, tx)
                per_tx.store.write()
                pfb_entries.append(PfbEntry(btx.tx, btx.blobs))
            else:
                # normal-after-blob ordering is enforced by the pre-scan above
                tx = decode_tx(raw)  # v2+: undecodable tx rejects the block
                if any(isinstance(m, MsgPayForBlobs) for m in tx.body.msgs):
                    raise ValueError("PFB message in non-blob tx")
                per_tx = ctx.branch()
                per_tx.gas_meter = GasMeter(tx.body.gas_limit)
                self.ante.run(per_tx, tx)
                per_tx.store.write()
                normal_txs.append(raw)

        square = square_mod.construct(
            normal_txs, pfb_entries, self.max_effective_square_size(ctx), threshold
        )
        if square.size != h.square_size:
            raise ValueError(
                f"square size mismatch: computed {square.size}, header {h.square_size}"
            )
        _, root = self._data_root(square)
        if root != h.data_hash:
            raise ValueError("data root mismatch")

    # ------------------------------------------------------------------
    # FinalizeBlock + Commit
    # ------------------------------------------------------------------

    def finalize_block(self, block: Block) -> list[TxResult]:
        with obs.span(
            "finalize_block", traces=self.traces,
            trace_id=obs.trace_id_for(self.chain_id, block.header.height),
            height=block.header.height, n_txs=len(block.txs),
        ):
            return self._finalize_inner(block)

    def _finalize_inner(self, block: Block) -> list[TxResult]:
        h = block.header
        ctx = self._deliver_ctx(InfiniteGasMeter(), height=h.height, t=h.time_unix)

        # BeginBlock via the versioned module manager (mint first, then
        # distribution, then slashing liveness — app/modules.go order);
        # only modules whose [From,To] range covers the current app
        # version run (app/module/manager.go dispatch)
        self.module_manager.begin_block(ctx, self.app_version)
        self.absent_validators = set()

        results: list[TxResult] = []
        for raw in block.txs:
            results.append(self._deliver_tx(ctx, raw))

        # EndBlock: upgrades
        self._end_blocker(ctx, h.height)
        if self.invariant_check_period and h.height % self.invariant_check_period == 0:
            self.crisis.assert_invariants(ctx)

        # the branch flush below is where the store tracer fires;
        # self.height still holds the PREVIOUS height until commit(), so
        # tell the tracer which block these writes belong to
        self._executing_height = h.height
        try:
            ctx.store.write()
        finally:
            self._executing_height = None
        return results

    def _deliver_tx(self, block_ctx: Context, raw: bytes) -> TxResult:
        try:
            btx = blob_mod.try_unmarshal_blob_tx(raw)  # single parse
        except ValueError as e:
            return TxResult(1, f"undecodable blob tx: {e}", 0, 0, [])
        raw_tx = btx.tx if btx is not None else raw  # strip blobs
        try:
            tx = decode_tx(raw_tx)
        except ValueError as e:
            return TxResult(1, f"undecodable tx: {e}", 0, 0, [])
        gas = GasMeter(tx.body.gas_limit)
        tx_ctx = block_ctx.branch()
        tx_ctx.gas_meter = gas
        try:
            self.ante.run(tx_ctx, tx)
            for m in tx.body.msgs:
                self._dispatch(tx_ctx, m)
            tx_ctx.store.write()
            return TxResult(0, "", tx.body.gas_limit, gas.consumed, tx_ctx.events)
        except (ante_mod.AnteError, OutOfGas, ValueError, KeyError,
                TypeError, IndexError, AttributeError) as e:
            # baseapp's runTx panic recovery: ANY malformed msg payload
            # (e.g. relay JSON missing fields -> KeyError, or JSON of the
            # wrong shape -> AttributeError on .get/.items) becomes a
            # failed tx, never a deterministic crash of every validator.
            # failed txs keep their fee + sequence bump (cosmos semantics):
            # re-run just the ante effects on a fresh branch
            fee_ctx = block_ctx.branch()
            fee_ctx.gas_meter = GasMeter(tx.body.gas_limit)
            try:
                self.ante.run(fee_ctx, tx)
                fee_ctx.store.write()
            except Exception:
                # an unre-runnable ante means the failed tx keeps neither
                # fee nor sequence bump — count it, it undercharges
                telemetry.incr("app.fee_reapply_errors")
            return TxResult(1, str(e), tx.body.gas_limit, gas.consumed, [])

    def simulate_tx(self, raw: bytes) -> TxResult:
        """Dry-run a tx against current committed state and report the gas
        it consumes (the reference's /cosmos.tx.v1beta1.Service/Simulate,
        which pkg/user/tx_client.go:320-330 uses for estimateGas). The ante
        runs in simulate mode — no signature, fee, or sequence requirements
        — msgs dispatch on a DISCARDED branch, and gas metering is real."""
        try:
            btx = blob_mod.try_unmarshal_blob_tx(raw)
        except ValueError as e:
            return TxResult(1, f"undecodable blob tx: {e}", 0, 0, [])
        raw_tx = btx.tx if btx is not None else raw
        try:
            tx = decode_tx(raw_tx)
        except ValueError as e:
            return TxResult(1, f"undecodable tx: {e}", 0, 0, [])
        ctx = self._ctx(self.store.branch(), GasMeter(1 << 40), check=False)
        try:
            self.ante.run(ctx, tx, simulate=True)
            for m in tx.body.msgs:
                self._dispatch(ctx, m)
        except (ante_mod.AnteError, OutOfGas, ValueError, KeyError,
                TypeError, IndexError) as e:
            return TxResult(1, str(e), 0, ctx.gas_meter.consumed, [])
        # branch is dropped: simulation never mutates state
        return TxResult(0, "", 0, ctx.gas_meter.consumed, ctx.events)

    def _dispatch(self, ctx: Context, msg) -> None:
        if isinstance(msg, MsgSend):
            self.bank.send(ctx, msg.from_addr, msg.to_addr, msg.amount)
        elif isinstance(msg, MsgPayForBlobs):
            self.blob.pay_for_blobs(ctx, msg)
        elif isinstance(msg, MsgSignalVersion):
            self.signal.signal_version(ctx, msg.validator, msg.version)
        elif isinstance(msg, MsgTryUpgrade):
            self.signal.try_upgrade(ctx)
        elif isinstance(msg, MsgRegisterEVMAddress):
            if self.app_version != 1:
                raise ValueError("blobstream disabled after v1")
            self.blobstream.register_evm_address(ctx, msg.validator, msg.evm_address)
        elif isinstance(msg, MsgDelegate):
            self.staking.delegate(ctx, msg.validator, msg.delegator, msg.amount)
        elif isinstance(msg, MsgUndelegate):
            self.staking.undelegate(ctx, msg.validator, msg.delegator, msg.amount)
        elif isinstance(msg, MsgBeginRedelegate):
            self.staking.redelegate(
                ctx, msg.src_validator, msg.dst_validator, msg.delegator, msg.amount
            )
        elif isinstance(msg, MsgCreateValidator):
            if msg.pubkey:
                # the consensus pubkey must derive the operator address, or
                # anyone could register a key they don't hold for an
                # address they do (votes would verify against the wrong
                # identity)
                from celestia_app_tpu.chain.crypto import PublicKey

                if PublicKey(msg.pubkey).address() != msg.operator:
                    raise ValueError(
                        "consensus pubkey does not derive operator address"
                    )
            self.staking.create_validator(
                ctx, msg.operator, msg.self_stake, pubkey=msg.pubkey
            )
        elif isinstance(msg, MsgSubmitProposal):
            import json as json_mod

            self.gov.submit_proposal(
                ctx,
                msg.proposer,
                json_mod.loads(msg.changes_json),
                msg.initial_deposit,
                msg.title,
            )
        elif isinstance(msg, MsgDeposit):
            self.gov.deposit(ctx, msg.proposal_id, msg.depositor, msg.amount)
        elif isinstance(msg, MsgVote):
            self.gov.vote(ctx, msg.proposal_id, msg.voter, msg.option)
        elif isinstance(msg, MsgTransfer):
            self.ibc.transfer.send_transfer(
                ctx, msg.source_channel, msg.sender, msg.receiver,
                msg.denom, msg.amount,
                timeout_height=msg.timeout_height,
            )
        elif isinstance(msg, MsgUpdateClient):
            # client-root recording as a consensus tx: replicated client
            # state is what makes the proof-gated relay txs below evaluate
            # identically on every validator
            import json as json_mod

            from celestia_app_tpu.chain import consensus as consensus_mod

            header = cert = new_validators = new_powers = None
            if msg.header_json:
                header = consensus_mod.header_from_json(
                    json_mod.loads(msg.header_json)
                )
            if msg.cert_json:
                cert = consensus_mod.cert_from_json(
                    json_mod.loads(msg.cert_json)
                )
            if msg.valset_json:
                vs = json_mod.loads(msg.valset_json)
                if not isinstance(vs, dict):
                    raise ValueError("valset_json must be an object")
                ops = vs.get("operators", {})
                pows = vs.get("powers", {})
                if not isinstance(ops, dict) or not isinstance(pows, dict):
                    raise ValueError(
                        "valset operators/powers must be objects"
                    )
                new_validators = {
                    bytes.fromhex(k): bytes.fromhex(v)
                    for k, v in ops.items()
                }
                new_powers = {
                    bytes.fromhex(k): int(v) for k, v in pows.items()
                }
            self.ibc.clients.update_client(
                # empty root decodes as b"" — normalize to None so the
                # keeper's "trusting update needs a root" guard applies
                ctx, msg.client_id, msg.height, msg.root or None,
                header=header, cert=cert,
                new_validators=new_validators, new_powers=new_powers,
                # the tx signer: trusting clients only accept their
                # pinned authorized relayer on this path (ibc.py)
                tx_relayer=msg.relayer,
            )
            ctx.emit_event("ibc.update_client", client_id=msg.client_id,
                           height=msg.height)
        elif isinstance(msg, MsgRecvPacket):
            # consensus-routed relay (ibc-go MsgRecvPacket): packet
            # application is part of the block, so every validator applies
            # it identically and WAL replay reproduces it
            import json as json_mod

            packet = json_mod.loads(msg.packet_json)
            proof = json_mod.loads(msg.proof_json) if msg.proof_json else None
            ack = self.ibc.recv_packet(
                ctx, packet, proof,
                msg.proof_height if proof is not None else None,
            )
            ctx.emit_event(
                "ibc.recv_packet",
                sequence=packet.get("sequence"),
                ok="error" not in ack,
            )
        elif isinstance(msg, MsgAcknowledgePacket):
            import json as json_mod

            self.ibc.acknowledge_packet(
                ctx, json_mod.loads(msg.packet_json),
                json_mod.loads(msg.ack_json),
                json_mod.loads(msg.proof_json) if msg.proof_json else None,
                msg.proof_height if msg.proof_json else None,
            )
        elif isinstance(msg, MsgTimeoutPacket):
            import json as json_mod

            self.ibc.timeout_packet(
                ctx, json_mod.loads(msg.packet_json),
                json_mod.loads(msg.proof_json) if msg.proof_json else None,
                msg.proof_height if msg.proof_json else None,
            )
        elif isinstance(msg, MsgExec):
            # x/authz: every inner message's native signer must have granted
            # the tx signer (grantee) authorization for that msg type
            if not msg.inner:
                raise ValueError("MsgExec with no inner messages")
            for inner in msg.inner:
                if isinstance(inner, MsgExec):
                    raise ValueError("nested MsgExec is not allowed")
                if isinstance(inner, MsgPayForBlobs):
                    raise ValueError("MsgPayForBlobs cannot be nested in MsgExec")
                granter = ante_mod.msg_signer(inner)
                if granter is None:
                    raise ValueError("inner message has no signer")
                if granter != msg.grantee and not self.authz.has_authorization(
                    ctx, granter, msg.grantee, inner.TYPE
                ):
                    raise ValueError(
                        f"no authorization for {inner.TYPE} from {granter.hex()}"
                    )
                self._dispatch(ctx, inner)
        else:
            raise ValueError(f"unroutable message {type(msg).__name__}")

    def _end_blocker(self, ctx: Context, height: int) -> None:
        # staking unbonding queue matures, then gov proposals resolve, then
        # blobstream attestations (module EndBlocker order app/modules.go;
        # blobstream's [1,1] range retires it at v2, app/modules.go:171) —
        # all dispatched by the versioned module manager
        self.module_manager.end_block(ctx, self.app_version)
        # height-based v1 -> v2 (app/app.go:458-470)
        if (
            self.app_version == 1
            and self.v2_upgrade_height is not None
            and height >= self.v2_upgrade_height
        ):
            self._migrate(ctx, 2)
            return
        # signal-based v2+ (app/app.go:472-478)
        if self.app_version >= 2:
            target = self.signal.should_upgrade(ctx)
            if target is not None:
                self.signal.clear_upgrade(ctx)
                self._migrate(ctx, target)

    def _migrate(self, ctx: Context, new_version: int) -> None:
        """Store migrations on upgrade (app/app.go:484-508 analog): the
        module manager runs on_exit for modules leaving their version range
        (blobstream store teardown) and on_enter for those arriving
        (minfee param seeding)."""
        self.module_manager.migrate(ctx, self.app_version, new_version)
        self.app_version = new_version

    SNAPSHOT_KEEP = 100  # bounded rollback window (reference keeps pruned IAVL versions)

    def commit(self, block: Block) -> bytes:
        with obs.span(
            "commit", traces=self.traces,
            trace_id=obs.trace_id_for(self.chain_id, block.header.height),
            height=block.header.height,
        ):
            return self._commit_inner(block)

    def _commit_inner(self, block: Block) -> bytes:
        t0 = telemetry.start_timer()
        # root BEFORE height: lockless readers pairing (height,
        # last_app_hash) — ChainHandle.status_pair — can then never
        # observe a height whose root is still the previous block's;
        # the benign inverse (old height, new root) retries or, for the
        # trusting relayer, records a binding its own fresh proofs verify
        # against
        self.last_app_hash = self.store.app_hash()
        self.height = block.header.height
        self.last_block_hash = block.header.hash()
        self.last_block_time = block.header.time_unix
        meta = self._commit_meta()
        if self.db is not None:
            # durable commit: state + block hit disk atomically before the
            # commit is acknowledged (a killed process resumes here)
            self.db.save_block(block)  # block first: LATEST implies block exists
            self.db.save_commit(self.height, self.store, meta)
        else:
            self.store.drain_changes()  # keep the change log bounded
            self._history[self.height] = {
                "store": self.store.snapshot(),
                "app_version": self.app_version,
                "last_app_hash": self.last_app_hash,
                "last_block_hash": self.last_block_hash,
                "last_block_time": self.last_block_time,
            }
            for h in [
                h for h in self._history if h <= self.height - self.SNAPSHOT_KEEP
            ]:
                del self._history[h]
        self._check_state = None  # baseapp resetState on commit
        telemetry.measure_since("commit", t0)
        # boundary observatory: bytes that crossed the host<->device
        # boundary since the previous commit — THE gauge ROADMAP item 2
        # (zero-copy blob path) optimizes against. Process-wide ledger
        # totals, so on a host-engine node this reads 0 and on a multi-
        # node in-process net the proposer's commit attributes the
        # whole net's traffic (documented in FORMATS; devnets are
        # per-process, where the attribution is exact).
        crossed = xfer.bytes_crossed()
        self.last_host_bytes_crossed = crossed - self._xfer_mark
        self._xfer_mark = crossed
        telemetry.gauge("xfer.host_bytes_crossed_per_block",
                        self.last_host_bytes_crossed)
        # BlockSummary trace row (celestia-core pkg/trace analog, §5.1):
        # what the e2e benchmark tooling scrapes per block. PER-NODE table
        # (self.traces): multi-node in-process networks must not interleave
        self.traces.write(
            "block_summary",
            height=self.height,
            time_unix=block.header.time_unix,
            n_txs=len(block.txs),
            block_bytes=sum(len(t) for t in block.txs),
            square_size=block.header.square_size,
            data_hash=block.header.data_hash.hex(),
            app_hash=self.last_app_hash.hex(),
            app_version=self.app_version,
        )
        # block plane: hand the committed entry to the DAS serving plane
        # and pre-build its provers on the warmer's background thread —
        # scheduling here is O(1) (slot swap + maybe a thread spawn); the
        # heavy level passes and seed fan-out run OUTSIDE whatever
        # service/consensus lock wraps this commit, so the first light-
        # client sample after commit is pure index arithmetic. A miss
        # (e.g. WAL replay, which never ran ProcessProposal) just means
        # the DAS plane warms lazily on first demand instead.
        entry = self.eds_cache.lookup_root(block.header.data_hash)
        if entry is not None:
            self.da_warmer.schedule(
                self.height, entry, self.da_seed_listeners,
                engine=self.engine, traces=self.traces,
                chain_id=self.chain_id, pack_store=self.pack_store,
                blob_pack_store=self.blob_pack_store,
            )
        return self.last_app_hash

    def _commit_meta(self) -> dict:
        """The identity document persisted beside every durable commit."""
        return {
            "app_version": self.app_version,
            "last_app_hash": self.last_app_hash.hex(),
            "last_block_hash": self.last_block_hash.hex(),
            "chain_id": self.chain_id,
            "genesis_time": self.genesis_time,
            "last_block_time": self.last_block_time,
        }

    def persist_identity(self) -> None:
        """Re-point the durable LATEST at the current in-memory identity and
        discard the abandoned fork above it (rollback semantics: the
        reference deletes store versions above the target)."""
        if self.db is None:
            raise ValueError("no data_dir attached")
        self.db.save_commit(
            self.height, self.store, self._commit_meta(), force_full=True
        )
        self.db.delete_above(self.height)

    def load(self, height: int | None = None) -> None:
        """Resume from the durable store (reference LoadLatestVersion /
        LoadHeight, app/app.go:427-435): restores state, chain identity,
        and app version from disk."""
        if self.db is None:
            raise ValueError("no data_dir attached")
        try:
            h, store_data, meta = self.db.load_commit(height)
        except FileNotFoundError:
            raise ValueError(
                f"no committed state for height {height} (missing or pruned)"
            ) from None
        self.store.restore(store_data)
        self.height = h
        self.app_version = meta["app_version"]
        self.last_app_hash = bytes.fromhex(meta["last_app_hash"])
        self.last_block_hash = bytes.fromhex(meta["last_block_hash"])
        self.chain_id = meta["chain_id"]
        self.genesis_time = meta["genesis_time"]
        # restore the deterministic time anchor: the meta carries it
        # (prune-proof, no block decode); metas written before the
        # anchor existed fall back to the block, then to genesis time
        self.last_block_time = meta.get("last_block_time")
        if self.last_block_time is None and h > 0:
            try:
                self.last_block_time = \
                    self.db.load_block(h).header.time_unix
            except FileNotFoundError:
                self.last_block_time = None
        self._check_state = None  # stale mempool overlay dies with the old timeline
        self.state_generation += 1

    def load_height(self, height: int) -> None:
        """Rollback to a committed height (reference LoadHeight): restores the
        store AND the version/hash identity so re-execution matches the
        original chain."""
        if self.db is not None:
            self.load(height)
            return
        snap = self._history.get(height)
        if snap is None:
            raise ValueError(f"no snapshot for height {height}")
        self.store.restore(snap["store"])
        self.height = height
        self.app_version = snap["app_version"]
        self.last_app_hash = snap["last_app_hash"]
        self.last_block_hash = snap["last_block_hash"]
        self.last_block_time = snap["last_block_time"]
        self._check_state = None
        self.state_generation += 1

    # module prefixes whose state is not derivable from balances and must be
    # carried verbatim by an export (delegations, unbonding queues, params,
    # reward indices, grants, attestations, signing info, channels, ...)
    EXPORT_PREFIXES = (
        b"auth/", b"staking/", b"dist/", b"gov/", b"blob/", b"minfee/",
        b"vesting/", b"feegrant/", b"authz/", b"slashing/", b"signal/",
        b"blobstream/", b"ibc/", b"mint/",
    )

    def export_genesis(self) -> dict:
        """ExportAppStateAndValidators (reference app/export.go): a genesis
        document that reproduces the committed state.

        Balances come from the BANK records (every funded address, including
        module pools and addresses that never signed); auth records (numbers,
        pubkeys, sequences — anti-replay), delegations, unbonding queues,
        governed params, reward indices, grants, and attestations ride
        verbatim in ``raw_modules``. On import the height counter resumes at
        exported_height so height-anchored state (blobstream windows,
        unbonding heights) stays consistent (the reference's export.go
        initial-height handling)."""
        ctx = self._ctx(self.store, InfiniteGasMeter(), check=False)
        accounts = []
        for k, _v in ctx.store.iterate_prefix(b"bank/bal/"):
            addr = k[len(b"bank/bal/"):]
            acc = self.auth.account(ctx, addr)
            accounts.append({
                "address": addr.hex(),
                "balance": self.bank.balance(ctx, addr),
                "sequence": acc["sequence"] if acc else 0,
            })
        raw_modules = {}
        for prefix in self.EXPORT_PREFIXES:
            for k, v in ctx.store.iterate_prefix(prefix):
                raw_modules[k.hex()] = v.hex()
        validators = [
            {"operator": op.hex(), "power": power}
            for op, power in self.staking.validators(ctx)
        ]
        return {
            "chain_id": self.chain_id,
            "app_version": self.app_version,
            "exported_height": self.height,
            "time_unix": self.genesis_time,
            "accounts": accounts,
            "validators": validators,  # informational; raw_modules carries state
            "raw_modules": raw_modules,
        }

    def relay_recv_packet(
        self,
        packet: dict,
        proof: dict | None = None,
        proof_height: int | None = None,
    ) -> dict:
        """Core-relay boundary: deliver an inbound IBC packet (the reference
        receives these as relayer-submitted MsgRecvPacket through consensus;
        the single-process node applies them directly to committed state).
        Channels bound to a client REQUIRE a commitment proof against a
        tracked counterparty root (ibc-go VerifyPacketCommitment)."""
        ctx = self._deliver_ctx(InfiniteGasMeter())
        ack = self.ibc.recv_packet(ctx, packet, proof, proof_height)
        ctx.store.write()
        return ack

    def relay_acknowledge(
        self, packet: dict, ack: dict,
        proof: dict | None = None, proof_height: int | None = None,
    ) -> None:
        ctx = self._deliver_ctx(InfiniteGasMeter())
        self.ibc.acknowledge_packet(ctx, packet, ack, proof, proof_height)
        ctx.store.write()

    def relay_timeout(
        self, packet: dict,
        proof: dict | None = None, proof_height: int | None = None,
    ) -> None:
        ctx = self._deliver_ctx(InfiniteGasMeter())
        self.ibc.timeout_packet(ctx, packet, proof, proof_height)
        ctx.store.write()

    # convenience: one full consensus round in-process
    def produce_block(self, raw_txs: list[bytes], t: float | None = None) -> tuple[Block, list[TxResult]]:
        prop = self.prepare_proposal(raw_txs, t=t)
        assert self.process_proposal(prop.block), "own proposal rejected"
        results = self.finalize_block(prop.block)
        self.commit(prop.block)
        return prop.block, results
