"""Stateless BlobTx validation: the mempool/proposal admission gate.

Reference parity: x/blob/types/blob_tx.go:37-108 `ValidateBlobTx` — the tx
must decode to exactly one MsgPayForBlobs whose per-blob namespace, size,
share version, and recomputed share commitment all match the attached blobs.
Called from CheckTx (app/check_tx.go:43) and ProcessProposal
(app/process_proposal.go:107), i.e. the reference recomputes commitments on
every admission. This repo instead routes every phase through the App's
`VerifiedCommitmentCache` (chain/admission.py, the traffic plane): the
admission batch (or the first per-blob host compute) fills it, and every
later phase consumes the cached bytes — the byte-compare against the tx's
CLAIMED commitment still runs on every path, so a mismatching (Byzantine)
tx is rejected identically warm or cold.
"""

from __future__ import annotations

from celestia_app_tpu.chain.tx import MsgPayForBlobs, Tx, decode_tx
from celestia_app_tpu.da import commitment as commitment_mod
from celestia_app_tpu.da.blob import BlobTx
from celestia_app_tpu.utils import telemetry


class BlobTxError(Exception):
    pass


def batch_commitments(blobs: list, subtree_root_threshold: int,
                      engine: str = "auto") -> list[bytes]:
    """Commitments for many blobs at once: device-batched when the workload
    is big enough to amortize a dispatch (BASELINE config 3), host
    otherwise. `engine` is the owning App's compute engine — a host-engine
    validator must NEVER touch the jax backend here: with the accelerator
    relay down, backend init does not fail, it HANGS, wedging consensus
    the first time a block carries >= 4 blobs."""
    # "mesh" is device-class: the mesh plane shards the EDS pipeline,
    # and its commitment batches take the same single-dispatch path
    if engine in ("device", "auto", "mesh") and len(blobs) >= 4:
        try:
            from celestia_app_tpu.da import commitment_device

            return commitment_device.commitments_device(
                blobs, subtree_root_threshold
            )
        except Exception:
            if engine == "device":
                raise
    return commitment_mod.create_commitments(blobs, subtree_root_threshold)


def resolve_commitments(blobs: list, subtree_root_threshold: int,
                        engine: str = "auto", cache=None) -> list[bytes]:
    """Commitments for `blobs` through the verified-commitment cache:
    cached blobs cost a lookup (`commitment.cache_hits`), the uncached
    remainder is computed in ONE batch (`commitment.recomputes`, one per
    computed blob) and cached for every later phase. With no cache this
    is exactly `batch_commitments`. The admitted-path telemetry pin
    (tests/test_traffic.py) rides on this split: a block whose txs were
    admitted at CheckTx resolves every commitment by lookup."""
    if cache is None:
        telemetry.incr("commitment.recomputes", by=len(blobs))
        return batch_commitments(blobs, subtree_root_threshold, engine)
    out: list[bytes | None] = []
    missing: list = []
    missing_at: list[int] = []
    keys: list[bytes] = []
    for i, blob in enumerate(blobs):
        key = cache.key(blob.namespace.raw, blob.share_version, blob.data,
                        subtree_root_threshold)
        got = cache.hit(key)
        out.append(got)
        if got is None:
            missing.append(blob)
            missing_at.append(i)
            keys.append(key)
    if missing:
        telemetry.incr("commitment.recomputes", by=len(missing))
        computed = batch_commitments(missing, subtree_root_threshold, engine)
        for i, key, commitment in zip(missing_at, keys, computed):
            out[i] = commitment
            cache.put(key, commitment)
    return out


def validate_blob_tx(
    btx: BlobTx,
    subtree_root_threshold: int,
    commitments: list[bytes] | None = None,
    cache=None,
) -> tuple[Tx, MsgPayForBlobs]:
    """Validate and return the decoded signed tx + its PFB message.

    ``commitments`` optionally supplies this tx's precomputed blob
    commitments (from resolve_commitments over the whole block) so
    ProcessProposal doesn't recompute per blob on the host; ``cache``
    is the owning App's VerifiedCommitmentCache — a hit replaces the
    per-blob host recompute, a miss computes and fills it.
    """
    if not btx.blobs:
        raise BlobTxError("blob tx contains no blobs")
    try:
        tx = decode_tx(btx.tx)
    except ValueError as e:
        raise BlobTxError(f"undecodable tx in blob tx: {e}") from None

    pfbs = [m for m in tx.body.msgs if isinstance(m, MsgPayForBlobs)]
    if len(pfbs) != 1 or len(tx.body.msgs) != 1:
        raise BlobTxError("blob tx must contain exactly one MsgPayForBlobs")
    msg = pfbs[0]
    msg.validate_basic()

    if len(btx.blobs) != len(msg.namespaces):
        raise BlobTxError(
            f"blob count mismatch: {len(btx.blobs)} attached, {len(msg.namespaces)} in msg"
        )
    for i, blob in enumerate(btx.blobs):
        blob.validate()
        if blob.namespace.raw != msg.namespaces[i]:
            raise BlobTxError(f"blob {i} namespace does not match msg")
        if len(blob.data) != msg.blob_sizes[i]:
            raise BlobTxError(
                f"blob {i} size mismatch: {len(blob.data)} != {msg.blob_sizes[i]}"
            )
        if blob.share_version != msg.share_versions[i]:
            raise BlobTxError(f"blob {i} share version mismatch")
        if commitments is not None:
            want = commitments[i]
        else:
            want = None
            key = None
            if cache is not None:
                key = cache.key(blob.namespace.raw, blob.share_version,
                                blob.data, subtree_root_threshold)
                want = cache.hit(key)
            if want is None:
                telemetry.incr("commitment.recomputes")
                want = commitment_mod.create_commitment(
                    blob, subtree_root_threshold)
                if cache is not None:
                    cache.put(key, want)
        if want != msg.share_commitments[i]:
            raise BlobTxError(f"blob {i} share commitment mismatch")
    return tx, msg
