"""Merkleized key-value state with cache layering and gas metering.

Reference parity: the cosmos-sdk commit multistore + CacheKV branching
(baseapp's checkState/deliverState split, app/app.go:427-435) and the SDK gas
meter. The store here is a single flat map with per-module key prefixes; the
app hash is the RFC-6962 Merkle root over sorted (key, value) leaf hashes,
recomputed per commit with a dirty-subtree shortcut left for later rounds.
Commit history is kept so `load_height` (app/app.go:592 LoadHeight) and
state-sync-style snapshots can roll back / export.
"""

from __future__ import annotations

import hashlib

from celestia_app_tpu.utils import merkle_host


def put_json(ctx_or_none, key: bytes, obj, *, store=None) -> None:
    """Canonical-JSON store write (sorted keys, no whitespace). EVERY module
    must encode through here: the byte encoding feeds the app hash, so a
    divergent copy would silently fork consensus state."""
    import json

    target = store if store is not None else ctx_or_none.store
    target.set(key, json.dumps(obj, sort_keys=True, separators=(",", ":")).encode())


def get_json(ctx_or_none, key: bytes, *, store=None):
    import json

    target = store if store is not None else ctx_or_none.store
    raw = target.get(key)
    return None if raw is None else json.loads(raw)


class OutOfGas(Exception):
    pass


class GasMeter:
    def __init__(self, limit: int):
        self.limit = limit
        self.consumed = 0

    def consume(self, amount: int, descriptor: str = "") -> None:
        self.consumed += amount
        if self.consumed > self.limit:
            raise OutOfGas(
                f"out of gas: {descriptor}: consumed {self.consumed} > limit {self.limit}"
            )

    def remaining(self) -> int:
        return max(0, self.limit - self.consumed)


class InfiniteGasMeter(GasMeter):
    def __init__(self):
        super().__init__(1 << 62)


class KVStore:
    """Flat committed store; branch() yields a cache layer for tx execution."""

    def __init__(self, data: dict[bytes, bytes] | None = None):
        self._data: dict[bytes, bytes] = dict(data or {})

    def get(self, key: bytes) -> bytes | None:
        return self._data.get(key)

    def set(self, key: bytes, value: bytes) -> None:
        self._data[key] = value

    def delete(self, key: bytes) -> None:
        self._data.pop(key, None)

    def iterate_prefix(self, prefix: bytes):
        for k in sorted(self._data):
            if k.startswith(prefix):
                yield k, self._data[k]

    def branch(self) -> "CacheStore":
        return CacheStore(self)

    def snapshot(self) -> dict[bytes, bytes]:
        return dict(self._data)

    def restore(self, snap: dict[bytes, bytes]) -> None:
        self._data = dict(snap)

    def app_hash(self) -> bytes:
        leaves = [
            hashlib.sha256(k + b"\x00" + v).digest()
            for k, v in sorted(self._data.items())
        ]
        return merkle_host.hash_from_leaves(leaves)


class CacheStore(KVStore):
    """Copy-on-write layer over a parent store; write() flushes down."""

    def __init__(self, parent: KVStore):
        super().__init__()
        self.parent = parent
        self._deleted: set[bytes] = set()

    def get(self, key: bytes) -> bytes | None:
        if key in self._deleted:
            return None
        if key in self._data:
            return self._data[key]
        return self.parent.get(key)

    def set(self, key: bytes, value: bytes) -> None:
        self._deleted.discard(key)
        self._data[key] = value

    def delete(self, key: bytes) -> None:
        self._data.pop(key, None)
        self._deleted.add(key)

    def iterate_prefix(self, prefix: bytes):
        merged: dict[bytes, bytes] = {}
        for k, v in self.parent.iterate_prefix(prefix):
            if k not in self._deleted:
                merged[k] = v
        for k, v in self._data.items():
            if k.startswith(prefix):
                merged[k] = v
        for k in sorted(merged):
            yield k, merged[k]

    def write(self) -> None:
        for k in self._deleted:
            self.parent.delete(k)
        for k, v in self._data.items():
            self.parent.set(k, v)
        self._data.clear()
        self._deleted.clear()


class Context:
    """Execution context: a store branch, gas meter, block info, events."""

    def __init__(
        self,
        store: KVStore,
        gas_meter: GasMeter,
        height: int,
        time_unix: float,
        chain_id: str,
        app_version: int,
        is_check_tx: bool = False,
    ):
        self.store = store
        self.gas_meter = gas_meter
        self.height = height
        self.time_unix = time_unix
        self.chain_id = chain_id
        self.app_version = app_version
        self.is_check_tx = is_check_tx
        self.events: list[dict] = []

    def emit_event(self, type_: str, **attrs) -> None:
        self.events.append({"type": type_, **attrs})

    def branch(self) -> "Context":
        ctx = Context(
            self.store.branch(),
            self.gas_meter,
            self.height,
            self.time_unix,
            self.chain_id,
            self.app_version,
            self.is_check_tx,
        )
        return ctx
