"""Merkleized key-value state with cache layering and gas metering.

Reference parity: the cosmos-sdk commit multistore + CacheKV branching
(baseapp's checkState/deliverState split, app/app.go:427-435) and the SDK gas
meter. The store here is a single flat map with per-module key prefixes.

App hash (INCREMENTAL — the IAVL-commit-cost analog, app/app.go:427-435):
keys hash into 65,536 buckets by the first two bytes of sha256(key); each
bucket's hash is the RFC-6962 Merkle root over its sorted (key, value) leaf
hashes; the app hash is the root of the fixed-depth-16 binary Merkle tree
over all bucket hashes. Buckets and tree nodes are cached sparsely (only
non-default entries stored), and writes mark buckets dirty, so commit cost
is O(dirty keys × (bucket size + 16)) hash ops — independent of total state
size. A 1M-key store re-commits a few touched keys in well under a
millisecond, where the previous whole-store recompute was O(n).

Commit history is kept so `load_height` (app/app.go:592 LoadHeight) and
state-sync-style snapshots can roll back / export. Writes are also recorded
as a change log (`drain_changes`) so durable storage can persist per-commit
deltas instead of full snapshots (chain/storage.py).
"""

from __future__ import annotations

import hashlib

from celestia_app_tpu.utils import merkle_host

_N_BUCKETS = 1 << 16
_TREE_DEPTH = 16  # binary levels above the bucket layer


def _default_level_hashes() -> list[bytes]:
    """default[l] = hash of an all-empty subtree whose leaves are empty
    buckets, for l = 16 (bucket layer) .. 0 (root)."""
    out = [b""] * (_TREE_DEPTH + 1)
    out[_TREE_DEPTH] = hashlib.sha256(b"").digest()  # RFC-6962 empty root
    for level in range(_TREE_DEPTH - 1, -1, -1):
        child = out[level + 1]
        out[level] = hashlib.sha256(b"\x01" + child + child).digest()
    return out


_DEFAULTS = _default_level_hashes()


def canonical_json(obj) -> bytes:
    """THE canonical encoding (sorted keys, no whitespace): the bytes that
    feed the app hash AND that cross-chain proofs verify against. Any
    consumer re-deriving these bytes must call this, never json.dumps
    directly — a divergent copy silently forks consensus state."""
    import json

    return json.dumps(obj, sort_keys=True, separators=(",", ":")).encode()


def put_json(ctx_or_none, key: bytes, obj, *, store=None) -> None:
    """Canonical-JSON store write. EVERY module must encode through here."""
    target = store if store is not None else ctx_or_none.store
    target.set(key, canonical_json(obj))


def get_json(ctx_or_none, key: bytes, *, store=None):
    import json

    target = store if store is not None else ctx_or_none.store
    raw = target.get(key)
    return None if raw is None else json.loads(raw)


class OutOfGas(Exception):
    pass


class GasMeter:
    def __init__(self, limit: int):
        self.limit = limit
        self.consumed = 0

    def consume(self, amount: int, descriptor: str = "") -> None:
        self.consumed += amount
        if self.consumed > self.limit:
            raise OutOfGas(
                f"out of gas: {descriptor}: consumed {self.consumed} > limit {self.limit}"
            )

    def remaining(self) -> int:
        return max(0, self.limit - self.consumed)


class InfiniteGasMeter(GasMeter):
    def __init__(self):
        super().__init__(1 << 62)


class KVStore:
    """Flat committed store; branch() yields a cache layer for tx execution.

    Carries the incremental app-hash tree (see module docstring) and a
    change log for delta persistence."""

    def __init__(self, data: dict[bytes, bytes] | None = None):
        self._data: dict[bytes, bytes] = dict(data or {})
        self._key_bucket: dict[bytes, int] = {}  # sha256-prefix cache
        self._bucket_keys: dict[int, set[bytes]] | None = None  # lazy index
        self._bucket_hash: dict[int, bytes] = {}  # non-empty buckets only
        self._tree: list[dict[int, bytes]] = [dict() for _ in range(_TREE_DEPTH)]
        self._dirty: set[int] = set()
        self._tree_valid = False
        self._changes: dict[bytes, bytes | None] = {}
        # optional operation tracer (the commit-multistore tracer analog,
        # ref app/app.go:194 SetCommitMultiStoreTracer): called as
        # tracer(op, key, value_len) for every committed write/delete
        self.tracer = None

    def _bucket_of(self, key: bytes) -> int:
        b = self._key_bucket.get(key)
        if b is None:
            d = hashlib.sha256(key).digest()
            b = (d[0] << 8) | d[1]
            self._key_bucket[key] = b
        return b

    def _index(self) -> dict[int, set[bytes]]:
        if self._bucket_keys is None:
            idx: dict[int, set[bytes]] = {}
            for k in self._data:
                idx.setdefault(self._bucket_of(k), set()).add(k)
            self._bucket_keys = idx
        return self._bucket_keys

    def get(self, key: bytes) -> bytes | None:
        return self._data.get(key)

    def set(self, key: bytes, value: bytes) -> None:
        self._data[key] = value
        self._changes[key] = value
        b = self._bucket_of(key)
        if self._bucket_keys is not None:
            self._bucket_keys.setdefault(b, set()).add(key)
        self._dirty.add(b)
        if self.tracer is not None:
            self.tracer("write", key, len(value))

    def delete(self, key: bytes) -> None:
        if self._data.pop(key, None) is not None:
            self._changes[key] = None
            b = self._bucket_of(key)
            if self._bucket_keys is not None:
                ks = self._bucket_keys.get(b)
                if ks is not None:
                    ks.discard(key)
            self._dirty.add(b)
            if self.tracer is not None:
                self.tracer("delete", key, 0)

    def iterate_prefix(self, prefix: bytes):
        for k in sorted(self._data):
            if k.startswith(prefix):
                yield k, self._data[k]

    def branch(self) -> "CacheStore":
        return CacheStore(self)

    def snapshot(self) -> dict[bytes, bytes]:
        return dict(self._data)

    def restore(self, snap: dict[bytes, bytes]) -> None:
        self._data = dict(snap)
        self._bucket_keys = None
        self._bucket_hash = {}
        self._tree = [dict() for _ in range(_TREE_DEPTH)]
        self._dirty = set()
        self._tree_valid = False
        self._changes = {}

    # -- change log (delta persistence) ---------------------------------

    def drain_changes(self) -> dict[bytes, bytes | None]:
        """Writes (value) and deletions (None) since the last drain."""
        out = self._changes
        self._changes = {}
        return out

    # -- incremental app hash -------------------------------------------

    def _rehash_bucket(self, b: int) -> None:
        keys = self._index().get(b)
        if not keys:
            self._bucket_hash.pop(b, None)
            return
        leaves = [
            hashlib.sha256(k + b"\x00" + self._data[k]).digest()
            for k in sorted(keys)
        ]
        self._bucket_hash[b] = merkle_host.hash_from_leaves(leaves)

    def _node(self, level: int, i: int) -> bytes:
        if level == _TREE_DEPTH:
            return self._bucket_hash.get(i, _DEFAULTS[_TREE_DEPTH])
        return self._tree[level].get(i, _DEFAULTS[level])

    def app_hash(self) -> bytes:
        if not self._tree_valid:
            # full (re)build: hash every non-empty bucket once
            self._bucket_hash = {}
            self._tree = [dict() for _ in range(_TREE_DEPTH)]
            dirty = set(self._index().keys())
            self._tree_valid = True
        else:
            dirty = self._dirty
        for b in dirty:
            self._rehash_bucket(b)
        # ancestor updates LEVEL BY LEVEL (a node's two children must both be
        # final before the parent hashes them — per-bucket path walks would
        # read stale siblings when dirty buckets share ancestors)
        touched = {b for b in dirty}
        for level in range(_TREE_DEPTH - 1, -1, -1):
            parents = {i >> 1 for i in touched}
            for i in parents:
                left = self._node(level + 1, 2 * i)
                right = self._node(level + 1, 2 * i + 1)
                if left == _DEFAULTS[level + 1] and right == _DEFAULTS[level + 1]:
                    self._tree[level].pop(i, None)
                else:
                    self._tree[level][i] = hashlib.sha256(
                        b"\x01" + left + right
                    ).digest()
            touched = parents
        self._dirty = set()
        return self._node(0, 0)

    # -- membership/absence proofs (IBC light-client verification) --------

    def prove_absence(self, key: bytes) -> dict:
        """Proof that `key` is NOT in the store under app_hash(): the full
        content of the key's bucket (buckets are small — sha-sharded to
        1/65536 of the keyspace) plus the bucket-to-root path. The ibc-go
        receipt-absence analog that gates timeouts."""
        if key in self._data:
            raise KeyError(f"key {key!r} exists — use prove()")
        self.app_hash()
        b = self._bucket_of(key)
        keys = sorted(self._index().get(b, ()))
        tree_path = []
        i = b
        for level in range(_TREE_DEPTH, 0, -1):
            tree_path.append(self._node(level, i ^ 1).hex())
            i >>= 1
        return {
            "bucket": b,
            "entries": [
                [k2.hex(), self._data[k2].hex()] for k2 in keys
            ],
            "tree_path": tree_path,
        }

    def prove(self, key: bytes) -> dict:
        """Merkle membership proof of (key, value) against app_hash().

        Two segments: an RFC-6962 audit path inside the key's bucket, then
        the 16 sibling hashes from the bucket to the root. Verified by
        :func:`verify_membership` with nothing but the root — this is what
        an IBC counterparty checks instead of trusting the relayer
        (ibc-go VerifyPacketCommitment analog)."""
        if key not in self._data:
            raise KeyError(f"no value for key {key!r}")
        self.app_hash()  # ensure the tree is current
        b = self._bucket_of(key)
        keys = sorted(self._index()[b])
        leaf_index = keys.index(key)
        leaves = [
            hashlib.sha256(k2 + b"\x00" + self._data[k2]).digest()
            for k2 in keys
        ]
        _root, proofs = merkle_host.proofs_from_leaves(leaves)
        tree_path = []
        i = b
        for level in range(_TREE_DEPTH, 0, -1):
            tree_path.append(self._node(level, i ^ 1).hex())
            i >>= 1
        return {
            "bucket": b,
            "leaf_index": leaf_index,
            "bucket_size": len(leaves),
            "bucket_path": [h.hex() for h in proofs[leaf_index].aunts],
            "tree_path": tree_path,
        }


def _bucket_of_key(key: bytes) -> int:
    d = hashlib.sha256(key).digest()
    return (d[0] << 8) | d[1]


def verify_membership(root: bytes, key: bytes, value: bytes, proof: dict) -> bool:
    """Check a :meth:`KVStore.prove` proof against an app hash. Pure
    function of the proof — safe to run against a counterparty's root."""
    try:
        d = hashlib.sha256(key).digest()
        if ((d[0] << 8) | d[1]) != proof["bucket"]:
            return False
        if not (0 <= proof["leaf_index"] < proof["bucket_size"]):
            return False
        leaf = hashlib.sha256(key + b"\x00" + value).digest()
        bucket_hash = merkle_host._compute_from_aunts(
            proof["leaf_index"],
            proof["bucket_size"],
            merkle_host.leaf_hash(leaf),
            [bytes.fromhex(h) for h in proof["bucket_path"]],
        )
        node = bucket_hash
        i = proof["bucket"]
        if len(proof["tree_path"]) != _TREE_DEPTH:
            return False
        for sib_hex in proof["tree_path"]:
            sib = bytes.fromhex(sib_hex)
            if i & 1:
                node = hashlib.sha256(b"\x01" + sib + node).digest()
            else:
                node = hashlib.sha256(b"\x01" + node + sib).digest()
            i >>= 1
        return node == root
    except (KeyError, ValueError, IndexError, TypeError):
        return False


def verify_absence(root: bytes, key: bytes, proof: dict) -> bool:
    """Check a :meth:`KVStore.prove_absence` proof: the key's bucket is
    fully disclosed, does not contain the key, and hashes to the root."""
    try:
        if _bucket_of_key(key) != proof["bucket"]:
            return False
        entries = [
            (bytes.fromhex(k), bytes.fromhex(v)) for k, v in proof["entries"]
        ]
        if any(k == key for k, _v in entries):
            return False
        if entries != sorted(entries):  # canonical order: no hidden slots
            return False
        # every disclosed entry must genuinely live in this bucket
        if any(_bucket_of_key(k) != proof["bucket"] for k, _v in entries):
            return False
        leaves = [
            hashlib.sha256(k + b"\x00" + v).digest() for k, v in entries
        ]
        node = merkle_host.hash_from_leaves(leaves)
        i = proof["bucket"]
        if len(proof["tree_path"]) != _TREE_DEPTH:
            return False
        for sib_hex in proof["tree_path"]:
            sib = bytes.fromhex(sib_hex)
            if i & 1:
                node = hashlib.sha256(b"\x01" + sib + node).digest()
            else:
                node = hashlib.sha256(b"\x01" + node + sib).digest()
            i >>= 1
        return node == root
    except (KeyError, ValueError, IndexError, TypeError):
        return False


class CacheStore(KVStore):
    """Copy-on-write layer over a parent store; write() flushes down."""

    def __init__(self, parent: KVStore):
        super().__init__()
        self.parent = parent
        self._deleted: set[bytes] = set()

    def get(self, key: bytes) -> bytes | None:
        if key in self._deleted:
            return None
        if key in self._data:
            return self._data[key]
        return self.parent.get(key)

    def set(self, key: bytes, value: bytes) -> None:
        self._deleted.discard(key)
        self._data[key] = value

    def delete(self, key: bytes) -> None:
        self._data.pop(key, None)
        self._deleted.add(key)

    def iterate_prefix(self, prefix: bytes):
        merged: dict[bytes, bytes] = {}
        for k, v in self.parent.iterate_prefix(prefix):
            if k not in self._deleted:
                merged[k] = v
        for k, v in self._data.items():
            if k.startswith(prefix):
                merged[k] = v
        for k in sorted(merged):
            yield k, merged[k]

    def write(self) -> None:
        for k in self._deleted:
            self.parent.delete(k)
        for k, v in self._data.items():
            self.parent.set(k, v)
        self._data.clear()
        self._deleted.clear()


class Context:
    """Execution context: a store branch, gas meter, block info, events."""

    def __init__(
        self,
        store: KVStore,
        gas_meter: GasMeter,
        height: int,
        time_unix: float,
        chain_id: str,
        app_version: int,
        is_check_tx: bool = False,
    ):
        self.store = store
        self.gas_meter = gas_meter
        self.height = height
        self.time_unix = time_unix
        self.chain_id = chain_id
        self.app_version = app_version
        self.is_check_tx = is_check_tx
        self.events: list[dict] = []

    def emit_event(self, type_: str, **attrs) -> None:
        self.events.append({"type": type_, **attrs})

    def branch(self) -> "Context":
        ctx = Context(
            self.store.branch(),
            self.gas_meter,
            self.height,
            self.time_unix,
            self.chain_id,
            self.app_version,
            self.is_check_tx,
        )
        return ctx
