"""Batched block production: plan B squares, extend them in ONE dispatch.

BENCH_HW_r4 measured the device sustaining ~90 extend+commits/s while
the per-block produce loop shipped 3.1 — the gap is one dispatch and one
full-EDS host fetch per block. This module closes it for the produce
side:

- ``plan_block_squares`` speculatively partitions a priority-ordered
  candidate tx list into the next ``n_blocks`` block layouts by running
  the SAME deterministic greedy accounting the proposer runs
  (da/square.build): block i's square is built from the txs blocks
  0..i-1 did not admit. When the ante admits every candidate (the normal
  case — pool txs already passed CheckTx), each planned square is
  byte-identical to the one ``prepare_proposal`` will construct.

- ``warm_block_batch`` groups the planned squares by size and extends
  each group in ONE batched dispatch
  (parallel/mesh_engine.compute_entries_batched: the mesh's sharded
  pipeline when active for the size, the single-chip vmapped program
  otherwise), inserting DEVICE-RESIDENT entries into the app's
  content-addressed EDS cache. The subsequent per-block produce rounds
  hit those entries, so the extend→commit→prover-warm chain hands device
  arrays — never bytes — between stages, and ``da.extend_runs`` stays at
  exactly one per height (paid inside the batch).

The batch is a PREFETCH, not a consensus change: every committed block
still goes through the unchanged prepare→process→finalize→commit path,
so batched and per-block production commit identical block and app
hashes by construction (pinned in tests/test_mesh_plane.py). A plan the
ante later disagrees with (a candidate turned invalid between planning
and proposing) merely misses the cache and pays a normal per-block
extend, counted ``producer.plan_misses``.

Wired in behind knobs: ``Node.produce_blocks_batched``, the cli
``start`` loop's ``produce_batch`` home-config key, and the reactor
proposer's ``ReactorConfig.produce_batch`` prewarm (docs/FORMATS.md
§18.1).
"""

from __future__ import annotations

from celestia_app_tpu import appconsts
from celestia_app_tpu.chain.state import InfiniteGasMeter
from celestia_app_tpu.da import blob as blob_mod
from celestia_app_tpu.da import dah as dah_mod
from celestia_app_tpu.da import square as square_mod
from celestia_app_tpu.da.square import PfbEntry
from celestia_app_tpu.utils import telemetry


def plan_block_squares(app, raw_txs: list[bytes],
                       n_blocks: int) -> list[square_mod.Square]:
    """Partition ``raw_txs`` (priority order, a mempool reap) into up to
    ``n_blocks`` consecutive speculative block layouts. Deterministic,
    state-read-only (square-size params); undecodable candidates are
    skipped exactly as admission would drop them. Stops early when the
    candidates run out — trailing empty blocks are not planned (an empty
    square is one cached entry for ALL empty heights anyway)."""
    threshold = appconsts.subtree_root_threshold(app.app_version)
    ctx = app._ctx(app.store.branch(), InfiniteGasMeter(), check=False)
    max_sq = app.max_effective_square_size(ctx)

    normals: list[bytes] = []
    pfbs: list[tuple[bytes, PfbEntry]] = []
    for raw in raw_txs:
        try:
            btx = blob_mod.try_unmarshal_blob_tx(raw)
        except ValueError:
            continue  # admission would reject it; keep the plan aligned
        if btx is not None:
            pfbs.append((raw, PfbEntry(btx.tx, btx.blobs)))
        else:
            normals.append(raw)

    plans: list[square_mod.Square] = []
    for _ in range(max(0, n_blocks)):
        if not normals and not pfbs:
            break
        sq = square_mod.build(normals, [e for _, e in pfbs], max_sq,
                              threshold)
        plans.append(sq)
        kept_n = set(sq.txs)
        kept_p = {e.tx for e in sq.pfbs}
        normals = [r for r in normals if r not in kept_n]
        pfbs = [(r, e) for r, e in pfbs if e.tx not in kept_p]
    return plans


def warm_block_batch(app, plans: list[square_mod.Square]) -> int:
    """Extend every planned square in as few dispatches as sizes allow
    (one per size bucket) and seed the app's EDS cache with the
    resulting device-resident entries. Returns how many entries were
    inserted. Only the default codec has a batched device program; other
    schemes skip (their per-block encode path is unchanged)."""
    from celestia_app_tpu.da import edscache as edscache_mod
    from celestia_app_tpu.parallel import mesh_engine

    if getattr(app, "engine", "auto") == "host":
        # a host-engine node must NEVER import-and-dispatch jax (the
        # relay-down hang class: backend init HANGS, and the produce
        # loop's try/except cannot catch a hang) — the knob is simply
        # inert there; per-block host extends continue unchanged
        return 0
    if getattr(app, "codec", None) is not None \
            and app.codec.name != "rs2d-nmt":
        return 0
    import numpy as np

    by_k: dict[int, list] = {}
    for sq in plans:
        ods = dah_mod.shares_to_ods(sq.share_bytes())
        key = edscache_mod.cache_key(ods)
        if app.eds_cache.get(key) is not None:
            telemetry.incr("producer.plan_cached")
            continue  # an identical square is already resident
        by_k.setdefault(sq.size, []).append((key, ods))

    inserted = 0
    for k, group in sorted(by_k.items()):
        # dedup within the group (two planned empty/equal squares are
        # one content-addressed entry)
        seen: dict[bytes, object] = {}
        for key, ods in group:
            seen.setdefault(key, ods)
        batch = np.stack(list(seen.values()))
        entries = mesh_engine.compute_entries_batched(
            batch, engine=getattr(app, "engine", "auto"))
        for key, entry in zip(seen.keys(), entries):
            app.eds_cache.put(key, entry)
            inserted += 1
    telemetry.incr("producer.blocks_planned", len(plans))
    return inserted
