"""x/blobstream analog: EVM-bridge attestations (app version 1 only).

Reference parity (SURVEY.md §2.1 "x/blobstream"):
- EndBlocker order — valset request first, then data commitments, then pruning
  (x/blobstream/abci.go:29-35).
- Valset attestations on: no previous valset, a validator starting to unbond
  this block, or a normalized bridge-power change > 5%
  (x/blobstream/abci.go:85-137, SignificantPowerDifferenceThreshold 0.05).
- Data-commitment attestations covering [begin, end) ranges every
  DataCommitmentWindow blocks (default 400, minimum 100), with a catch-up loop
  (x/blobstream/abci.go:37-82, keeper/keeper_data_commitment.go:15-42,
  types/genesis.go:18,29).
- Pruning of attestations older than 3 weeks, advancing the earliest-available
  nonce (x/blobstream/abci.go:141-198, AttestationExpiryTime).
- MsgRegisterEVMAddress with EVM-address uniqueness; a default EVM address is
  derived from the validator operator address on creation
  (keeper/hooks.go:45-60, keeper/msg_server.go, types/types.go:13-15).
- Staking hook: a validator starting to unbond records the height so one
  valset request covers all unbonds in the block (keeper/hooks.go:24-40).
- Bridge powers normalized so member power / u32_max == cosmos power share
  (types/validator.go:101-140 PowerDiff docs); members sorted by power desc,
  EVM-address hex asc as tiebreak (types/validator.go:85-99).

The data-commitment *root* (what orchestrators sign and the EVM contract
stores) is the RFC-6962 Merkle root over `abi.encode(height, dataRoot)`-style
tuples — here 32-byte big-endian height ‖ 32-byte data root, mirroring
DataRootTuple in the Blobstream contract used by x/blobstream/client/verify.go.
`verify_share_inclusion` chains share proof → data root → tuple root exactly
like the reference's verify CLI.
"""

from __future__ import annotations

import dataclasses
import json

from celestia_app_tpu.chain.state import Context
from celestia_app_tpu.utils import merkle_host

U32_MAX = 0xFFFFFFFF
SIGNIFICANT_POWER_DIFF = 0.05  # abci.go SignificantPowerDifferenceThreshold
ATTESTATION_EXPIRY_SECONDS = 3 * 7 * 24 * 3600  # abci.go AttestationExpiryTime
DEFAULT_DATA_COMMITMENT_WINDOW = 400  # types/genesis.go:29
MINIMUM_DATA_COMMITMENT_WINDOW = 100  # types/genesis.go:18
# celestia-core consts.DataCommitmentBlocksLimit (pkg/appconsts/global_consts.go:81-86)
DATA_COMMITMENT_BLOCKS_LIMIT = 10_000


@dataclasses.dataclass(frozen=True)
class BridgeValidator:
    """Normalized bridge member: power/U32_MAX == share of cosmos power."""

    power: int  # u32-normalized
    evm_address: bytes  # 20 bytes


@dataclasses.dataclass(frozen=True)
class Valset:
    nonce: int
    members: tuple[BridgeValidator, ...]
    height: int
    time_unix: int  # whole seconds: attestations live in consensus state


@dataclasses.dataclass(frozen=True)
class DataCommitment:
    nonce: int
    begin_block: int  # inclusive
    end_block: int  # exclusive
    time_unix: int  # whole seconds (see Valset)


def _att_to_json(att) -> dict:
    if isinstance(att, Valset):
        return {
            "type": "valset",
            "nonce": att.nonce,
            "members": [
                {"power": m.power, "evm_address": m.evm_address.hex()}
                for m in att.members
            ],
            "height": att.height,
            "time_unix": att.time_unix,
        }
    return {
        "type": "data_commitment",
        "nonce": att.nonce,
        "begin_block": att.begin_block,
        "end_block": att.end_block,
        "time_unix": att.time_unix,
    }


def _att_from_json(obj: dict):
    if obj["type"] == "valset":
        return Valset(
            nonce=obj["nonce"],
            members=tuple(
                BridgeValidator(m["power"], bytes.fromhex(m["evm_address"]))
                for m in obj["members"]
            ),
            height=obj["height"],
            time_unix=obj["time_unix"],
        )
    return DataCommitment(
        nonce=obj["nonce"],
        begin_block=obj["begin_block"],
        end_block=obj["end_block"],
        time_unix=obj["time_unix"],
    )


def default_evm_address(operator: bytes) -> bytes:
    """types/types.go:13-15 — the operator address bytes as an EVM address."""
    return operator[-20:].rjust(20, b"\x00")


class BlobstreamKeeper:
    PREFIX = b"blobstream/"
    LATEST_NONCE = b"blobstream/latest_nonce"
    EARLIEST_NONCE = b"blobstream/earliest_nonce"
    LATEST_VALSET_NONCE = b"blobstream/latest_valset_nonce"
    LATEST_DC_NONCE = b"blobstream/latest_dc_nonce"
    ATT = b"blobstream/att/"
    EVM = b"blobstream/evm/"
    EVM_BY_ADDR = b"blobstream/evm_by_addr/"
    UNBONDING_HEIGHT = b"blobstream/unbonding_height"
    PARAMS = b"blobstream/params"

    def __init__(self, staking):
        self.staking = staking

    # -- params --------------------------------------------------------

    def data_commitment_window(self, ctx: Context) -> int:
        raw = ctx.store.get(self.PARAMS)
        if raw is None:
            return DEFAULT_DATA_COMMITMENT_WINDOW
        return json.loads(raw)["data_commitment_window"]

    def set_data_commitment_window(self, ctx: Context, window: int) -> None:
        if window < MINIMUM_DATA_COMMITMENT_WINDOW:
            raise ValueError(
                f"data commitment window {window} < minimum "
                f"{MINIMUM_DATA_COMMITMENT_WINDOW}"
            )
        if window > DATA_COMMITMENT_BLOCKS_LIMIT:
            raise ValueError(
                f"data commitment window {window} > blocks limit "
                f"{DATA_COMMITMENT_BLOCKS_LIMIT}"
            )
        ctx.store.set(
            self.PARAMS, json.dumps({"data_commitment_window": window}).encode()
        )

    # -- EVM address registry ------------------------------------------

    def evm_address(self, ctx: Context, operator: bytes) -> bytes | None:
        return ctx.store.get(self.EVM + operator)

    def is_evm_address_unique(self, ctx: Context, evm: bytes) -> bool:
        return ctx.store.get(self.EVM_BY_ADDR + evm) is None

    def set_evm_address(self, ctx: Context, operator: bytes, evm: bytes) -> None:
        if len(evm) != 20:
            raise ValueError("EVM address must be 20 bytes")
        old = ctx.store.get(self.EVM + operator)
        if old is not None:
            ctx.store.delete(self.EVM_BY_ADDR + old)
        ctx.store.set(self.EVM + operator, evm)
        ctx.store.set(self.EVM_BY_ADDR + evm, operator)

    def register_evm_address(self, ctx: Context, operator: bytes, evm: bytes) -> None:
        """MsgRegisterEVMAddress handler (keeper/msg_server.go)."""
        if self.staking.validator_power(ctx, operator) == 0:
            raise ValueError("EVM address registration from unknown validator")
        if not self.is_evm_address_unique(ctx, evm):
            raise ValueError("EVM address already registered")
        self.set_evm_address(ctx, operator, evm)
        ctx.emit_event(
            "blobstream.register_evm_address",
            validator=operator.hex(),
            evm_address=evm.hex(),
        )

    # -- staking hooks (keeper/hooks.go) --------------------------------

    def after_validator_created(self, ctx: Context, operator: bytes) -> None:
        if ctx.app_version > 1:
            return
        evm = default_evm_address(operator)
        if not self.is_evm_address_unique(ctx, evm):
            raise ValueError(
                "default EVM address collision; use a different operator address"
            )
        self.set_evm_address(ctx, operator, evm)

    def after_validator_begin_unbonding(self, ctx: Context) -> None:
        if ctx.app_version > 1:
            return
        ctx.store.set(
            self.UNBONDING_HEIGHT, ctx.height.to_bytes(8, "big")
        )

    def latest_unbonding_height(self, ctx: Context) -> int:
        raw = ctx.store.get(self.UNBONDING_HEIGHT)
        return 0 if raw is None else int.from_bytes(raw, "big")

    # -- attestation store ---------------------------------------------

    def latest_attestation_nonce(self, ctx: Context) -> int | None:
        raw = ctx.store.get(self.LATEST_NONCE)
        return None if raw is None else int.from_bytes(raw, "big")

    def earliest_available_nonce(self, ctx: Context) -> int | None:
        raw = ctx.store.get(self.EARLIEST_NONCE)
        return None if raw is None else int.from_bytes(raw, "big")

    def attestation_by_nonce(self, ctx: Context, nonce: int):
        raw = ctx.store.get(self.ATT + nonce.to_bytes(8, "big"))
        return None if raw is None else _att_from_json(json.loads(raw))

    def set_attestation_request(self, ctx: Context, att) -> None:
        nonce = att.nonce
        ctx.store.set(
            self.ATT + nonce.to_bytes(8, "big"),
            json.dumps(_att_to_json(att), sort_keys=True).encode(),
        )
        ctx.store.set(self.LATEST_NONCE, nonce.to_bytes(8, "big"))
        if self.earliest_available_nonce(ctx) is None:
            ctx.store.set(self.EARLIEST_NONCE, nonce.to_bytes(8, "big"))
        # O(1) lookups for the per-block EndBlocker (the reference keeper
        # likewise tracks the latest nonce instead of rescanning)
        kind_key = (
            self.LATEST_VALSET_NONCE
            if isinstance(att, Valset)
            else self.LATEST_DC_NONCE
        )
        ctx.store.set(kind_key, nonce.to_bytes(8, "big"))
        ctx.emit_event(
            "blobstream.attestation_request",
            nonce=nonce,
            kind=_att_to_json(att)["type"],
        )

    def _next_nonce(self, ctx: Context) -> int:
        latest = self.latest_attestation_nonce(ctx)
        return 1 if latest is None else latest + 1

    # -- valsets --------------------------------------------------------

    def current_valset(self, ctx: Context) -> Valset:
        """Normalized bridge valset (keeper/keeper_valset.go GetCurrentValset)."""
        vals = self.staking.validators(ctx)
        total = sum(p for _, p in vals)
        if total == 0:
            raise ValueError("no bonded validators")
        members = []
        for operator, power in vals:
            evm = self.evm_address(ctx, operator) or default_evm_address(operator)
            members.append(BridgeValidator(power * U32_MAX // total, evm))
        # power desc, EVM hex asc tiebreak (types/validator.go:85-99)
        members.sort(key=lambda m: (-m.power, m.evm_address.hex()))
        return Valset(
            nonce=self._next_nonce(ctx),
            members=tuple(members),
            height=ctx.height,
            time_unix=int(ctx.time_unix),
        )

    def _latest_of_kind(self, ctx: Context, kind_key: bytes):
        raw = ctx.store.get(kind_key)
        earliest = self.earliest_available_nonce(ctx)
        if raw is None or earliest is None:
            return None
        nonce = int.from_bytes(raw, "big")
        if nonce < earliest:
            return None  # pruned
        return self.attestation_by_nonce(ctx, nonce)

    def latest_valset(self, ctx: Context) -> Valset | None:
        return self._latest_of_kind(ctx, self.LATEST_VALSET_NONCE)

    def latest_data_commitment(self, ctx: Context) -> DataCommitment | None:
        return self._latest_of_kind(ctx, self.LATEST_DC_NONCE)

    def data_commitment_for_height(self, ctx: Context, height: int) -> DataCommitment:
        """Attestation whose [begin, end) range covers `height`
        (keeper_data_commitment.go GetDataCommitmentForHeight)."""
        latest = self.latest_data_commitment(ctx)
        if latest is None or latest.end_block <= height:
            raise ValueError(f"no data commitment generated for height {height}")
        earliest = self.earliest_available_nonce(ctx)
        for nonce in range(self.latest_attestation_nonce(ctx), earliest - 1, -1):
            att = self.attestation_by_nonce(ctx, nonce)
            if (
                isinstance(att, DataCommitment)
                and att.begin_block <= height < att.end_block
            ):
                return att
        raise ValueError(f"data commitment for height {height} pruned or missing")

    # -- end blocker ----------------------------------------------------

    @staticmethod
    def power_diff(a: Valset, b: Valset) -> float:
        """Sum of absolute normalized power changes / U32_MAX
        (types/validator.go:118-140)."""
        powers: dict[bytes, int] = {m.evm_address: m.power for m in a.members}
        for m in b.members:
            powers[m.evm_address] = powers.get(m.evm_address, 0) - m.power
        return sum(abs(v) for v in powers.values()) / U32_MAX

    def end_blocker(self, ctx: Context) -> None:
        """abci.go:29-35 — valset first, then data commitments, then pruning."""
        self._handle_valset_request(ctx)
        self._handle_data_commitment_request(ctx)
        self._prune_attestations(ctx)

    def _handle_valset_request(self, ctx: Context) -> None:
        latest = self.latest_valset(ctx)
        unbonding_now = self.latest_unbonding_height(ctx) == ctx.height
        try:
            current = self.current_valset(ctx)
        except ValueError:
            return  # no bonded validators (abci.go:101-108)
        significant = (
            latest is not None
            and self.power_diff(current, latest) > SIGNIFICANT_POWER_DIFF
        )
        if latest is None or unbonding_now or significant:
            self.set_attestation_request(ctx, current)

    def _handle_data_commitment_request(self, ctx: Context) -> None:
        window = self.data_commitment_window(ctx)
        while True:
            latest = self.latest_data_commitment(ctx)
            if latest is not None:
                # abci.go:63 — next range [end, end+window) is created one
                # block after it completes: height - end_block >= window
                if ctx.height - latest.end_block >= window:
                    self.set_attestation_request(
                        ctx,
                        DataCommitment(
                            nonce=self._next_nonce(ctx),
                            begin_block=latest.end_block,
                            end_block=latest.end_block + window,
                            time_unix=int(ctx.time_unix),
                        ),
                    )
                else:
                    break
            else:
                if ctx.height >= window:
                    # first range is [1, window+1) (keeper_data_commitment.go:35-38)
                    self.set_attestation_request(
                        ctx,
                        DataCommitment(
                            nonce=self._next_nonce(ctx),
                            begin_block=1,
                            end_block=window + 1,
                            time_unix=int(ctx.time_unix),
                        ),
                    )
                else:
                    break

    def _prune_attestations(self, ctx: Context) -> None:
        latest = self.latest_attestation_nonce(ctx)
        earliest = self.earliest_available_nonce(ctx)
        if latest is None or earliest is None:
            return
        new_earliest = earliest
        while new_earliest < latest:
            att = self.attestation_by_nonce(ctx, new_earliest)
            if att is None:
                return
            if att.time_unix + ATTESTATION_EXPIRY_SECONDS > ctx.time_unix:
                break
            ctx.store.delete(self.ATT + new_earliest.to_bytes(8, "big"))
            new_earliest += 1
        if new_earliest > earliest:
            ctx.store.set(self.EARLIEST_NONCE, new_earliest.to_bytes(8, "big"))


# ---------------------------------------------------------------------------
# Data-commitment roots + client-side verification (x/blobstream/client/verify.go)
# ---------------------------------------------------------------------------


def encode_data_root_tuple(height: int, data_root: bytes) -> bytes:
    """DataRootTuple as the Blobstream EVM contract encodes it: 32-byte
    big-endian height ‖ 32-byte data root."""
    if len(data_root) != 32:
        raise ValueError("data root must be 32 bytes")
    return height.to_bytes(32, "big") + data_root


def data_commitment_root(
    commitment: DataCommitment, data_roots: dict[int, bytes]
) -> bytes:
    """Merkle root the orchestrators attest to for a commitment's range."""
    leaves = [
        encode_data_root_tuple(h, data_roots[h])
        for h in range(commitment.begin_block, commitment.end_block)
    ]
    return merkle_host.hash_from_leaves(leaves)


def data_root_tuple_proof(
    commitment: DataCommitment, data_roots: dict[int, bytes], height: int
) -> merkle_host.Proof:
    """Inclusion proof of one height's tuple in the commitment root."""
    if not commitment.begin_block <= height < commitment.end_block:
        raise ValueError("height outside commitment range")
    leaves = [
        encode_data_root_tuple(h, data_roots[h])
        for h in range(commitment.begin_block, commitment.end_block)
    ]
    _, proofs = merkle_host.proofs_from_leaves(leaves)
    return proofs[height - commitment.begin_block]


def verify_data_root_inclusion(
    height: int,
    data_root: bytes,
    commitment_root: bytes,
    proof: merkle_host.Proof,
) -> bool:
    """client/verify.go VerifyDataRootInclusion: tuple → commitment root."""
    return proof.verify(commitment_root, encode_data_root_tuple(height, data_root))
