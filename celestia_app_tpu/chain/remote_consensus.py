"""Socket-side consensus orchestration: the round schedule over HTTP peers.

Reference parity: in celestia-core, the consensus reactor's round state
machine drives proposals and votes across TCP to validator processes
(SURVEY §5.8). Here the same two-phase schedule (propose → prevote →
polka/lock → precommit → commit) that LocalNetwork runs in-process is
driven over per-validator HTTP services (service/validator_server.py) —
every proposal, vote, certificate, and snapshot chunk crosses a real
socket, each validator process signs and verifies locally, and the
orchestrator is an untrusted scheduler (nodes refuse certs that fail
their own verification).

Failure model: a dead peer (connection refused / timeout) is simply absent
from the round — its vote is missing, its state falls behind. If >2/3 of
power remains, heights keep committing; the returned peer catches up via
WAL replay on restart plus `/consensus/sync` (verified state-sync) and
rejoins the schedule.
"""

from __future__ import annotations

import urllib.error

from celestia_app_tpu.chain import consensus as c
from celestia_app_tpu.net.transport import PeerClient, TransportConfig


class PeerDown(Exception):
    pass


class RemoteValidator:
    """HTTP handle to one validator process (the reactor's peer)."""

    def __init__(self, url: str, timeout: float = 60.0,
                 client: PeerClient | None = None):
        self.url = url.rstrip("/")
        self.timeout = timeout
        # hardened transport, one attempt per _call (the round schedule
        # is the retry policy here — a peer absent from a phase is simply
        # absent); the breaker makes a long-dead peer cost BreakerOpen
        # speed per phase instead of a connect timeout, and its half-open
        # probe readmits it when it returns (the documented rejoin path)
        self.client = client or PeerClient(
            TransportConfig(timeout=timeout, retries=1),
            name="orchestrator",
        )

    def _call(self, method: str, path: str, payload: dict | None = None,
              timeout: float | None = None) -> dict:
        try:
            return self.client.request(
                self.url, path,
                (payload or {}) if method != "GET" else None,
                timeout=timeout if timeout is not None else self.timeout,
            )
        except urllib.error.HTTPError as e:
            body = e.read().decode(errors="replace")
            raise ValueError(f"{path} -> {e.code}: {body[:300]}") from None
        except (OSError, TimeoutError) as e:
            raise PeerDown(f"{self.url}{path}: {e}") from None

    def status(self, timeout: float | None = None) -> dict:
        return self._call("GET", "/consensus/status", timeout=timeout)

    def broadcast_tx(self, raw: bytes) -> dict:
        import base64

        return self._call("POST", "/broadcast_tx",
                          {"tx": base64.b64encode(raw).decode()})

    def propose(self, t: float, timeout: float | None = None) -> dict:
        return self._call("POST", "/consensus/propose", {"time": t},
                          timeout=timeout)["block"]

    def prevote(self, block_json: dict, round_: int = 0,
                timeout: float | None = None) -> c.Vote:
        out = self._call("POST", "/consensus/prevote",
                         {"block": block_json, "round": round_},
                         timeout=timeout)
        return c.vote_from_json(out["vote"])

    def precommit(self, block_json: dict | None, polka: bool,
                  prevotes: list[dict], round_: int,
                  timeout: float | None = None) -> c.Vote:
        out = self._call("POST", "/consensus/precommit", {
            "block": block_json, "polka": polka,
            "prevotes": prevotes, "round": round_,
        }, timeout=timeout)
        return c.vote_from_json(out["vote"])

    def commit(self, block_json: dict, cert: c.CommitCertificate,
               evidence=(), timeout: float | None = None) -> dict:
        return self._call("POST", "/consensus/commit", {
            "block": block_json,
            "cert": c.cert_to_json(cert),
            "evidence": [c.evidence_to_json(e) for e in evidence],
        }, timeout=timeout)

    def sync_from(self, peer_url: str) -> dict:
        return self._call("POST", "/consensus/sync", {"peer": peer_url})


class SocketNetwork:
    """The round scheduler over RemoteValidator peers.

    Validator identity (address, pubkey, power) comes from the genesis doc
    — the same source every validator process trusts — so the orchestrator
    can pre-verify certificates before fan-out, but final authority stays
    with each node's own `verify_certificate`."""

    def __init__(self, peers: list[RemoteValidator], genesis: dict,
                 chain_id: str):
        self.chain_id = chain_id
        self.genesis = genesis
        self.pubkeys = {
            bytes.fromhex(v["operator"]): bytes.fromhex(v["pubkey"])
            for v in genesis.get("validators", [])
            if "pubkey" in v
        }
        self.powers = {
            bytes.fromhex(v["operator"]): int(v["power"])
            for v in genesis.get("validators", [])
        }
        # deterministic proposer rotation: peers sorted by their validator
        # address, exactly as LocalNetwork sorts its nodes — every process
        # self-reports the address it signs with at handshake time. A peer
        # that is DOWN at construction keeps the documented failure-model
        # semantics (absent, can rejoin) instead of killing the scheduler:
        # it sorts after the live ones, keyed by URL (deterministic for
        # this orchestrator instance; rotation is orchestrator-local).
        def sort_key(p: RemoteValidator) -> tuple[int, str]:
            try:
                st = p.status(timeout=self.TIMEOUT_STATUS_S)
                return (0, st["address"])
            except (PeerDown, ValueError, KeyError):
                # unreachable, erroring, or malformed — same absent
                # semantics produce_height applies per phase
                return (1, p.url)

        self.peers = sorted(peers, key=sort_key)
        self._round = 0
        self._vote_pool: list[c.Vote] = []

    EVIDENCE_MAX_AGE = 10
    # Wall-clock phase timeouts, escalating per failed round — Tendermint's
    # timeout cascade shape (TimeoutPropose + delta/round,
    # consensus_consts.go), with windows sized LARGER than the reference's
    # 10s/500ms because a first propose/prevote may pay a cold jit compile
    # of the extend pipeline (tens of seconds on TPU, minutes on a virtual
    # CPU mesh). A peer that cannot answer inside the window counts as
    # absent for the phase, and the round rotates if quorum is lost.
    TIMEOUT_PROPOSE_S = 120.0
    TIMEOUT_VOTE_S = 120.0
    TIMEOUT_COMMIT_S = 120.0
    TIMEOUT_STATUS_S = 10.0  # liveness checks must fail fast
    TIMEOUT_DELTA_S = 15.0  # added per failed round

    def _phase_timeout(self, base: float) -> float:
        return base + self._round * self.TIMEOUT_DELTA_S

    # -- helpers ---------------------------------------------------------

    def _alive_status(self) -> list[tuple[RemoteValidator, dict]]:
        out = []
        for p in self.peers:
            try:
                out.append((p, p.status(timeout=self.TIMEOUT_STATUS_S)))
            except PeerDown:
                continue
        return out

    def broadcast_tx(self, raw: bytes, via: int = 0) -> bool:
        """Fan the tx to every ALIVE peer's mempool; the caller's verdict is
        its submission node's CheckTx (the Tendermint client view)."""
        verdicts = {}
        for i, p in enumerate(self.peers):
            try:
                verdicts[i] = p.broadcast_tx(raw)["code"] == 0
            except PeerDown:
                verdicts[i] = False
        return verdicts.get(via, False)

    def produce_height(self, t: float):
        """One socket-crossing consensus round. Returns (height, app_hash)
        on commit or (None, None) on a failed round (proposer dead, no
        polka, or <2/3 precommit power)."""
        alive = self._alive_status()
        if not alive:
            self._round += 1
            return None, None
        height = max(st["height"] for _, st in alive) + 1
        participants = [
            (p, st) for p, st in alive if st["height"] == height - 1
        ]
        total = sum(self.powers.values())

        proposer_idx = (height + self._round) % len(self.peers)
        proposer = self.peers[proposer_idx]
        try:
            block_json = proposer.propose(
                t, timeout=self._phase_timeout(self.TIMEOUT_PROPOSE_S)
            )
        except (PeerDown, ValueError):
            self._round += 1
            return None, None
        block = c.block_from_json(block_json)
        bh = block.header.hash()

        # prevote phase (over sockets)
        prevotes: list[c.Vote] = []
        vote_timeout = self._phase_timeout(self.TIMEOUT_VOTE_S)
        for p, _st in participants:
            try:
                prevotes.append(p.prevote(block_json, self._round,
                                          timeout=vote_timeout))
            except (PeerDown, ValueError):
                continue
        # prevotes enter the evidence pool too: round-signed votes make
        # same-round prevote duplicates slashable while cross-round
        # re-prevoting stays legal (detect_equivocation's contract)
        self._vote_pool.extend(
            v for v in prevotes if v.block_hash is not None
        )
        prevote_power = sum(
            self.powers.get(v.validator, 0)
            for v in prevotes
            if v.block_hash == bh and v.height == height
            and v.phase == "prevote"
        )
        polka = prevote_power * 3 > total * 2
        prevote_jsons = [c.vote_to_json(v) for v in prevotes]

        # precommit phase: each node re-verifies the polka locally
        precommits: list[c.Vote] = []
        for p, _st in participants:
            try:
                precommits.append(
                    p.precommit(block_json if polka else None, polka,
                                prevote_jsons, self._round,
                                timeout=vote_timeout)
                )
            except (PeerDown, ValueError):
                continue
        self._vote_pool.extend(
            v for v in precommits if v.block_hash is not None
        )
        self._prune_vote_pool(height)

        cert = c.CommitCertificate(height, bh, tuple(precommits),
                                   self._round)
        if not cert.verify(self.chain_id, self.pubkeys, total, self.powers):
            self._round += 1
            return None, None
        self._round = 0

        evidence = tuple(c.detect_equivocation(
            self.chain_id, [self._vote_pool], self.pubkeys
        ))
        if evidence:
            punished = {ev.vote_a.validator for ev in evidence}
            self._vote_pool = [
                v for v in self._vote_pool if v.validator not in punished
            ]

        hashes = {}
        commit_timeout = self._phase_timeout(self.TIMEOUT_COMMIT_S)
        for p, _st in participants:
            try:
                out = p.commit(block_json, cert, evidence,
                               timeout=commit_timeout)
                hashes[out["app_hash"]] = out["height"]
            except (PeerDown, ValueError):
                continue
        if len(hashes) != 1:
            raise AssertionError(
                f"state divergence at height {height}: {sorted(hashes)}"
            )
        return height, next(iter(hashes))

    def _prune_vote_pool(self, current_height: int) -> None:
        floor = current_height - self.EVIDENCE_MAX_AGE
        self._vote_pool = [v for v in self._vote_pool if v.height > floor]
