"""The sync plane: chunked, parallel, resumable state sync + snapshot store.

Reference parity: the Cosmos-SDK snapshot store + celestia-core state sync
(SURVEY L6). A node serving state sync writes interval snapshots to disk
(``<home>/snapshots/<height>/``: deterministic key-ranged chunks + a
manifest committing to every chunk's sha256 and the final app hash) and
serves them FROM DISK — never capture-on-request, never under the service
lock. A joining node discovers manifests across its peers, pulls chunks
concurrently from every healthy peer (chunks are content-addressed by the
manifest, so any peer serving the same snapshot can serve any chunk),
verifies each chunk on arrival, persists progress with the
``das/checkpoint.py`` fsync discipline so a crash mid-restore RESUMES
instead of restarting, and hands the completed chunk set to
``consensus.state_sync_bootstrap`` — whose app-hash-anchored manifest
verification is unchanged and remains the adoption gate.

Layout (shared with ``cli.py snapshot create`` and the ``start`` loop):

    <home>/snapshots/<height>/chunk_000000.json ... manifest.json
    <home>/statesync/<manifest-digest>/          in-progress restore
        manifest.json                            the resume checkpoint
        chunk_000000 ...                         verified chunks (fsync'd)

The manifest is written LAST (and restore state is keyed by the manifest
digest), so a half-written snapshot is never restorable and a restore can
never mix chunks from two different snapshots.

HTTP surface (both the node service and the validator service):

    GET /sync/snapshots           {"snapshots": [manifest, ...]} newest
                                  restorable first
    GET /sync/chunk?height=&index= raw chunk bytes (octet-stream, not b64)

Fault points: ``statesync.mid_restore`` fires after each chunk is durably
persisted (a crash there must resume, re-fetching only missing chunks);
``statesync.pre_adopt`` fires once every chunk is verified, before the
bootstrap adoption. docs/DESIGN.md "The sync plane" and docs/FORMATS.md
§15 are the normative descriptions.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import threading

from celestia_app_tpu import faults
from celestia_app_tpu import obs
from celestia_app_tpu.utils import telemetry

log = obs.get_logger("chain.sync")

SNAPSHOT_DIRNAME = "snapshots"
RESTORE_DIRNAME = "statesync"

# manifest fields a served snapshot must carry to be restorable (the
# consensus.encode_app_snapshot output shape; FORMATS §15.1)
MANIFEST_FIELDS = (
    "height", "app_hash", "app_version", "chain_id", "genesis_time",
    "last_block_hash", "n_chunks", "chunk_hashes",
)


class SyncError(ValueError):
    """Client-side problem on the /sync/* surface (bad params, nothing
    served); the HTTP services map "not served"/"no such" to 404."""


class StateSyncUnavailable(OSError):
    """No peer serves a restorable snapshot above the requested floor."""


def fsync_write(path: str, data: bytes) -> None:
    """Write ``data`` durably: the bytes hit the platters before the call
    returns. THE chunk-file write every chunked plane shares (state-sync
    chunks here, proof-pack chunks in das/packs.py)."""
    with open(path, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())


def atomic_json_write(path: str, doc: dict, indent: int | None = None) -> None:
    """The das/checkpoint.py discipline (tmp + fsync + os.replace) for a
    JSON document: a crash mid-save leaves either the previous file or
    nothing — never a torn manifest."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=indent)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def manifest_digest(manifest: dict) -> str:
    """Content address of a snapshot: sha256 over the canonical (sorted-
    key) JSON encoding of its manifest. Keys restore progress on disk, so
    two peers serving byte-identical snapshots share one restore."""
    return hashlib.sha256(
        json.dumps(manifest, sort_keys=True).encode()
    ).hexdigest()


def _manifest_ok(m) -> bool:
    if not isinstance(m, dict):
        return False
    if any(k not in m for k in MANIFEST_FIELDS):
        return False
    return (isinstance(m["chunk_hashes"], list)
            and len(m["chunk_hashes"]) == m["n_chunks"])


def manifest_scheme(m: dict) -> str:
    """DA scheme a snapshot's chain runs under; absent ⇒ the default
    (pre-codec-plane manifests never carried it — FORMATS §16.1)."""
    return m.get("da_scheme", "rs2d-nmt")


def scheme_of(node_or_app) -> str:
    """The DA scheme a node/app runs under (its configured codec);
    the local-side twin of `manifest_scheme`."""
    app = getattr(node_or_app, "app", node_or_app)
    codec = getattr(app, "codec", None)
    return codec.name if codec is not None else "rs2d-nmt"


def home_for(node_or_app) -> str | None:
    """The --home directory a node's durable state lives under (data is
    ``<home>/data``), or None for an in-memory node."""
    app = getattr(node_or_app, "app", node_or_app)
    db = getattr(app, "db", None)
    if db is None:
        return None
    return os.path.dirname(os.path.abspath(db.dir))


def store_for(node_or_app) -> "SnapshotStore | None":
    home = home_for(node_or_app)
    if home is None:
        return None
    return SnapshotStore(os.path.join(home, SNAPSHOT_DIRNAME))


def write_snapshot_dir(manifest: dict, chunks: list[bytes],
                       out_dir: str) -> None:
    """Persist one snapshot's chunks + manifest into ``out_dir``. The
    manifest is written last and fsync'd, so a crash mid-write leaves a
    dir that is never listed as restorable (and gets pruned)."""
    os.makedirs(out_dir, exist_ok=True)
    for i, chunk in enumerate(chunks):
        fsync_write(os.path.join(out_dir, f"chunk_{i:06d}.json"), chunk)
    atomic_json_write(os.path.join(out_dir, "manifest.json"), manifest,
                      indent=2)


def prune_snapshots(root: str, keep: int) -> None:
    """Keep only the newest ``keep`` RESTORABLE snapshot dirs (the sdk's
    snapshot-keep-recent semantics; 0 = keep everything). A half-written
    dir (no manifest.json — a crash mid-write) is deleted outright and
    never counts toward the kept set."""
    if keep <= 0 or not os.path.isdir(root):
        return
    complete: list[int] = []
    for name in sorted(os.listdir(root)):
        path = os.path.join(root, name)
        if not os.path.isdir(path) or not name.isdigit():
            continue
        if os.path.exists(os.path.join(path, "manifest.json")):
            complete.append(int(name))
        else:
            shutil.rmtree(path, ignore_errors=True)
    for h in sorted(complete, reverse=True)[keep:]:
        shutil.rmtree(os.path.join(root, str(h)), ignore_errors=True)


class SnapshotStore:
    """The on-disk snapshot set one node SERVES (``<home>/snapshots``).
    Read paths touch only the filesystem — serving a manifest or a chunk
    never takes the node's service lock and never captures state."""

    def __init__(self, root: str):
        self.root = root

    def heights(self) -> list[int]:
        """Restorable snapshot heights, newest first."""
        if not os.path.isdir(self.root):
            return []
        out = []
        for name in os.listdir(self.root):
            if not name.isdigit():
                continue
            if os.path.exists(
                os.path.join(self.root, name, "manifest.json")
            ):
                out.append(int(name))
        return sorted(out, reverse=True)

    def manifest(self, height: int) -> dict | None:
        path = os.path.join(self.root, str(height), "manifest.json")
        try:
            with open(path) as f:
                m = json.load(f)
        except (OSError, ValueError):
            return None
        return m if _manifest_ok(m) else None

    def manifests(self) -> list[dict]:
        """Every restorable manifest, newest first — the /sync/snapshots
        body. Unreadable/invalid dirs are skipped (and logged), never
        fatal to serving."""
        out = []
        for h in self.heights():
            m = self.manifest(h)
            if m is None:
                log.warning("unreadable snapshot skipped",
                            root=self.root, height=h)
                telemetry.incr("sync.bad_snapshot_dirs")
                continue
            out.append(m)
        return out

    def newest(self) -> dict | None:
        ms = self.manifests()
        return ms[0] if ms else None

    def chunk(self, height: int, index: int) -> bytes:
        """Raw chunk bytes from disk. Raises SyncError('... not served')
        when the snapshot/chunk does not exist (the services map that to
        404)."""
        m = self.manifest(height)
        if m is None:
            raise SyncError(f"snapshot {height} not served")
        if not 0 <= index < m["n_chunks"]:
            raise SyncError(
                f"chunk index {index} out of range (n_chunks "
                f"{m['n_chunks']})"
            )
        path = os.path.join(self.root, str(height), f"chunk_{index:06d}.json")
        try:
            with open(path, "rb") as f:
                return f.read()
        except OSError:
            raise SyncError(
                f"chunk {height}/{index} not served"
            ) from None

    def chunks(self, height: int) -> list[bytes]:
        m = self.manifest(height)
        if m is None:
            raise SyncError(f"snapshot {height} not served")
        return [self.chunk(height, i) for i in range(m["n_chunks"])]

    def write(self, manifest: dict, chunks: list[bytes]) -> None:
        write_snapshot_dir(
            manifest, chunks,
            os.path.join(self.root, str(manifest["height"])),
        )

    def prune(self, keep: int) -> None:
        prune_snapshots(self.root, keep)


def maybe_snapshot(app, service_lock, store: SnapshotStore | None,
                   interval: int, keep: int, height: int) -> dict | None:
    """The post-commit interval-snapshot hook (default_overrides.go:
    294-297: interval 1500, keep 2). Only the state CAPTURE holds the
    service lock; chunk encoding, disk writes, and pruning run outside
    it. Snapshots are auxiliary: any failure is counted + logged, never
    fatal to the commit path. Returns the manifest written, or None."""
    if store is None or interval <= 0 or height % interval != 0:
        return None
    from celestia_app_tpu.chain import consensus as c

    try:
        with service_lock:
            cap = c.capture_app_snapshot(app)
        manifest, chunks = c.encode_app_snapshot(cap)
        store.write(manifest, chunks)
        store.prune(keep)
        telemetry.incr("sync.snapshots_written")
        return manifest
    except Exception as e:
        telemetry.incr("sync.snapshot_write_errors")
        log.error("interval snapshot failed", height=height, err=e)
        return None


def route_sync(store: SnapshotStore | None, path: str, query: dict):
    """Dispatch a GET /sync/* request (one router shared by the node HTTP
    service and the validator consensus service). Returns a JSON-able
    dict, or raw ``bytes`` for /sync/chunk (the handler sends those as
    application/octet-stream). Raises SyncError on client mistakes; the
    services map messages containing "not served" to 404."""
    if path == "/sync/snapshots":
        if store is None:
            return {"snapshots": []}
        return {"snapshots": store.manifests()}
    if path == "/sync/chunk":
        if store is None:
            raise SyncError("state sync not served")
        try:
            height = int(query.get("height", ["0"])[0])
            index = int(query.get("index", ["0"])[0])
        except (TypeError, ValueError):
            raise SyncError("height and index must be integers") from None
        return store.chunk(height, index)
    raise SyncError(f"no sync route {path}")


# ---------------------------------------------------------------------------
# the joiner: discovery + parallel, resumable chunk fetch
# ---------------------------------------------------------------------------


class StateSyncClient:
    """Fetch one snapshot's chunks from many peers, in parallel, resumably.

    Peers serve manifests at GET /sync/snapshots; the client picks the
    newest height above ``min_height`` (among peers serving the same
    height, the manifest more peers agree on wins — adoption still
    verifies the app hash, so a majority of liars only wastes fetches).
    Chunks land under ``workdir/<manifest-digest>/`` one fsync'd file
    each, so a crashed restore resumes by re-verifying what is already
    on disk and fetching ONLY the missing chunks. A chunk whose sha256
    does not match the manifest is re-fetched from the next peer and the
    serving peer is penalized on the shared PeerClient health score
    (``net.penalize``) — its breaker eventually opens and the fetchers
    skip it entirely.
    """

    def __init__(self, peers: list[str], workdir: str, net=None,
                 workers: int = 4, min_height: int = 0,
                 name: str = "statesync",
                 da_scheme: str = "rs2d-nmt"):
        from celestia_app_tpu.net.transport import PeerClient

        self.peers = [u.rstrip("/") for u in peers if u]
        self.workdir = workdir
        self.net = net if net is not None else PeerClient(name=name)
        self.workers = max(1, int(workers))
        self.min_height = int(min_height)
        # only same-scheme snapshots are restorable (codec plane)
        self.da_scheme = da_scheme
        self._lock = threading.Lock()
        # the shared chunk table the fetcher threads coordinate through
        self._queue: list[int] = []       # guarded-by: _lock
        self._have: set[int] = set()      # guarded-by: _lock
        self._errors: list[str] = []      # guarded-by: _lock
        self.stats = {                    # guarded-by: _lock
            "fetched": 0,     # chunks pulled over the network this run
            "reused": 0,      # chunks already on disk (verified) at start
            "bad_chunks": 0,  # hash-mismatched arrivals (re-fetched)
            "peers": 0,
        }
        self._root: str | None = None  # set by fetch()

    # -- discovery --------------------------------------------------------

    def discover(self) -> tuple[dict, list[str]]:
        """(manifest, serving peers) for the newest restorable snapshot
        above min_height. Raises StateSyncUnavailable when no peer
        serves one."""
        by_key: dict[tuple[int, str], tuple[dict, list[str]]] = {}
        last_err = "no peers"
        for u in self.peers:
            if not self.net.available(u):
                continue
            try:
                doc = self.net.get(u, "/sync/snapshots")
            except (OSError, ValueError) as e:
                last_err = f"{u}: {type(e).__name__}: {e}"
                continue
            for m in (doc.get("snapshots") or []):
                if not _manifest_ok(m):
                    continue
                if manifest_scheme(m) != self.da_scheme:
                    # wrong-scheme snapshots are unrestorable here;
                    # skip at discovery instead of after a full pull
                    # (state_sync_bootstrap would refuse them anyway)
                    continue
                h = int(m["height"])
                if h <= self.min_height:
                    continue
                key = (h, manifest_digest(m))
                if key not in by_key:
                    by_key[key] = (m, [])
                by_key[key][1].append(u)
        if not by_key:
            raise StateSyncUnavailable(
                f"no restorable snapshot above height {self.min_height} "
                f"({last_err})"
            )
        # resume preference: an IN-PROGRESS restore pins its manifest as
        # long as any peer still serves it — on a busy chain the newest
        # snapshot moves every interval, and chasing it would turn every
        # crash into a from-scratch restart (the checkpoint would never
        # be reused). A finished restore removes its workdir (cleanup),
        # so this only ever latches genuinely interrupted syncs.
        in_progress = [
            kv for kv in by_key.items()
            if os.path.exists(os.path.join(self.workdir, kv[0][1],
                                           "manifest.json"))
        ]
        pool = in_progress or list(by_key.items())
        # newest height first; among equals, the most-replicated manifest
        (_h, _d), (manifest, sources) = max(
            pool, key=lambda kv: (kv[0][0], len(kv[1][1])),
        )
        return manifest, sources

    # -- the restore state machine ---------------------------------------

    def fetch(self) -> tuple[dict, list[bytes]]:
        """Discover, then pull every missing chunk concurrently across
        the serving peers; returns (manifest, chunks) ready for
        ``consensus.state_sync_bootstrap``. Fires
        ``statesync.mid_restore`` after each durable chunk write and
        ``statesync.pre_adopt`` once the set is complete."""
        manifest, sources = self.discover()
        digest = manifest_digest(manifest)
        root = os.path.join(self.workdir, digest)
        self._root = root
        os.makedirs(root, exist_ok=True)
        # superseded restores (a crashed sync whose manifest no peer
        # serves any more — discover() would have preferred it otherwise)
        # are dead weight up to a full snapshot each: prune them now that
        # this restore is committed to `digest`
        for name in sorted(os.listdir(self.workdir)):
            if name != digest and os.path.isdir(
                os.path.join(self.workdir, name)
            ):
                shutil.rmtree(os.path.join(self.workdir, name),
                              ignore_errors=True)
        self._save_checkpoint(root, manifest)
        n = int(manifest["n_chunks"])
        height = int(manifest["height"])

        missing = self._scan_existing(root, manifest)
        reused = n - len(missing)
        with self._lock:
            self._queue = list(missing)
            self._have = set(range(n)) - set(missing)
            self.stats["reused"] = reused
            self.stats["peers"] = len(sources)
            self._errors = []
        if reused:
            log.info("state sync resumed from checkpoint",
                     height=height, reused=reused,
                     missing=len(missing))
            telemetry.incr("sync.restores_resumed")

        if missing:
            threads = [
                threading.Thread(
                    target=self._worker,
                    args=(root, manifest, sources, w),
                    daemon=True,
                )
                for w in range(min(self.workers, len(missing)))
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        with self._lock:
            have = len(self._have)
            err = self._errors[-1] if self._errors else None
        if have != n:
            telemetry.incr("sync.restore_failures")
            raise StateSyncUnavailable(
                f"restore incomplete ({have}/{n} chunks): "
                f"{err or 'peers exhausted'}"
            )
        chunks = []
        for i in range(n):
            with open(os.path.join(root, f"chunk_{i:06d}"), "rb") as f:
                chunks.append(f.read())
        # crash point: every chunk durable + verified, snapshot NOT yet
        # adopted — a restart must reuse the full set (fetched == 0)
        action = faults.fire("statesync.pre_adopt", height=height)
        if action in ("drop", "error"):
            raise StateSyncUnavailable(
                "injected fault: statesync.pre_adopt"
            )
        return manifest, chunks

    def cleanup(self) -> None:
        """Drop the restore workdir after a successful adoption."""
        if self._root is not None:
            shutil.rmtree(self._root, ignore_errors=True)

    def _save_checkpoint(self, root: str, manifest: dict) -> None:
        """The resume record (das/checkpoint.py discipline: tmp + fsync +
        replace): the manifest this restore is pinned to. Chunk files are
        their own progress record — content-verified on resume — so the
        checkpoint never over-claims."""
        path = os.path.join(root, "manifest.json")
        if os.path.exists(path):
            return
        atomic_json_write(path, manifest)

    def _scan_existing(self, root: str, manifest: dict) -> list[int]:
        """Resume: verify every chunk file already on disk against the
        manifest hash; corrupt/torn files are dropped and re-fetched.
        Returns the missing indices."""
        missing = []
        for i in range(int(manifest["n_chunks"])):
            path = os.path.join(root, f"chunk_{i:06d}")
            try:
                with open(path, "rb") as f:
                    data = f.read()
            except OSError:
                missing.append(i)
                continue
            if (hashlib.sha256(data).hexdigest()
                    != manifest["chunk_hashes"][i]):
                os.unlink(path)
                missing.append(i)
        return missing

    def _worker(self, root: str, manifest: dict, sources: list[str],
                offset: int) -> None:
        """One fetcher: drain the shared queue, trying peers round-robin
        from a per-worker offset (so concurrent workers spread across the
        peer set), verify-on-arrival, persist durably, fire the
        mid-restore crash point."""
        height = int(manifest["height"])
        while True:
            with self._lock:
                if self._errors:
                    return  # a sibling aborted: stop cleanly
                if not self._queue:
                    return
                index = self._queue.pop(0)
            data = self._fetch_chunk(manifest, sources, index, offset)
            if data is None:
                with self._lock:
                    self._errors.append(
                        f"chunk {index}: no peer served a valid copy"
                    )
                return
            path = os.path.join(root, f"chunk_{index:06d}")
            tmp = f"{path}.tmp.{offset}"
            with open(tmp, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            with self._lock:
                self._have.add(index)
                self.stats["fetched"] += 1
            telemetry.incr("sync.chunks_fetched")
            # crash point: THIS chunk is durable, others may not be — a
            # killed joiner must resume, re-fetching only what's missing
            action = faults.fire("statesync.mid_restore",
                                 height=height, index=index)
            if action in ("drop", "error"):
                with self._lock:
                    self._errors.append(
                        "injected fault: statesync.mid_restore"
                    )
                return

    def _fetch_chunk(self, manifest: dict, sources: list[str], index: int,
                     offset: int) -> bytes | None:
        """Pull one chunk, rotating across the serving peers; a
        hash-mismatched body penalizes the peer on the shared health
        score and falls through to the next one."""
        height = int(manifest["height"])
        want = manifest["chunk_hashes"][index]
        order = sources[offset % len(sources):] + \
            sources[:offset % len(sources)]
        for u in order:
            if not self.net.available(u):
                continue
            try:
                data = self.net.get(
                    u, f"/sync/chunk?height={height}&index={index}",
                    raw=True,
                )
            except (OSError, ValueError) as e:
                # transient peer failure: the transport's health score
                # already recorded it; rotate to the next source
                telemetry.incr("sync.chunk_fetch_errors")
                log.warning("chunk fetch failed", peer=u, index=index,
                            err=e)
                continue
            if hashlib.sha256(data).hexdigest() != want:
                # content-hash mismatch: a corrupt (or lying) peer —
                # count it against its health score so the breaker
                # eventually skips it, and try the next peer
                self.net.penalize(u, f"bad chunk {height}/{index}")
                telemetry.incr("sync.bad_chunks")
                with self._lock:
                    self.stats["bad_chunks"] += 1
                continue
            return data
        return None


def legacy_snapshot_doc(vnode_or_app, store: SnapshotStore | None,
                        service_lock=None, min_height: int = 0) -> dict:
    """The legacy one-shot ``GET /consensus/snapshot`` body, now a thin
    adapter over the chunked plane: the newest restorable DISK snapshot
    when the node serves one (no capture, no lock), else the pre-sync-
    plane capture-on-request (under the service lock) so fresh chains
    and existing callers keep working. ``min_height`` (the
    ``?min_height=`` query) is the PULLER's current height: a node
    already past every disk snapshot gets a capture — the original
    endpoint always served the tip, and a joiner whose height sits
    between the newest snapshot and the tip must not be stranded.
    Deprecated — FORMATS §15.4."""
    import base64

    from celestia_app_tpu.chain import consensus as c

    app = getattr(vnode_or_app, "app", vnode_or_app)
    manifest = store.newest() if store is not None else None
    if manifest is not None and int(manifest["height"]) > min_height:
        chunks = store.chunks(int(manifest["height"]))
    else:
        if service_lock is not None:
            with service_lock:
                cap = c.capture_app_snapshot(app)
        else:
            cap = c.capture_app_snapshot(app)
        manifest, chunks = c.encode_app_snapshot(cap)
    return {
        "manifest": manifest,
        "chunks": [base64.b64encode(ch).decode() for ch in chunks],
    }
