"""Account keys, addresses, signing.

Reference parity: cosmos-sdk secp256k1 account keys (the reference's account
auth) — ECDSA with SHA-256 and deterministic RFC 6979 nonces, emitting
64-byte low-S (r || s) signatures. Addresses are the first 20 bytes of
SHA-256(compressed pubkey) (the reference uses ripemd160(sha256(pk));
ripemd160 is unavailable in this OpenSSL build, and the address derivation
is not consensus-relevant across frameworks).

Two interchangeable backends, chosen at import:

- the `cryptography` (OpenSSL) backend when the package is present;
- a pure-Python secp256k1 fallback otherwise (Jacobian-coordinate point
  arithmetic + faithful RFC 6979), so chain code runs in containers that
  ship no OpenSSL bindings. Both backends produce BYTE-IDENTICAL
  signatures (RFC 6979 is fully deterministic) — a WAL or tx signed under
  one verifies and re-signs identically under the other, which keeps
  app hashes and block data roots reproducible across environments
  (pinned by tests/test_tx.py signature goldens).
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
import hmac

from celestia_app_tpu.utils import telemetry

try:  # OpenSSL-backed fast path
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.hazmat.primitives.asymmetric.utils import (
        Prehashed,
        decode_dss_signature,
    )

    _HAVE_OPENSSL = True
except ImportError:  # pure-Python fallback (see _Point below)
    _HAVE_OPENSSL = False

ADDRESS_LEN = 20
# secp256k1 domain parameters (SEC 2): y^2 = x^3 + 7 over F_p.
_P = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEFFFFFC2F
_N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
_GX = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
_GY = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8
if _HAVE_OPENSSL:
    _CURVE = ec.SECP256K1()


def _sha(b: bytes) -> bytes:
    return hashlib.sha256(b).digest()


# ---------------------------------------------------------------------------
# Pure-Python secp256k1 (fallback backend)
# ---------------------------------------------------------------------------
# Jacobian coordinates: one modular inverse per scalar multiplication
# instead of one per point add — the difference between usable and
# unusable in pure Python. Only the operations this module needs (scalar
# mult, add, compress/decompress) are implemented; no generality sought.


def _inv(a: int, m: int) -> int:
    return pow(a, -1, m)


def _jac_double(p):
    x, y, z = p
    if not y:
        return (0, 0, 0)
    ysq = y * y % _P
    s = 4 * x * ysq % _P
    m = 3 * x * x % _P  # a = 0 for secp256k1
    nx = (m * m - 2 * s) % _P
    ny = (m * (s - nx) - 8 * ysq * ysq) % _P
    nz = 2 * y * z % _P
    return (nx, ny, nz)


def _jac_add(p, q):
    if not p[1]:
        return q
    if not q[1]:
        return p
    u1 = p[0] * q[2] * q[2] % _P
    u2 = q[0] * p[2] * p[2] % _P
    s1 = p[1] * q[2] ** 3 % _P
    s2 = q[1] * p[2] ** 3 % _P
    if u1 == u2:
        if s1 != s2:
            return (0, 0, 0)  # P + (-P)
        return _jac_double(p)
    h = (u2 - u1) % _P
    r = (s2 - s1) % _P
    h2 = h * h % _P
    h3 = h * h2 % _P
    u1h2 = u1 * h2 % _P
    nx = (r * r - h3 - 2 * u1h2) % _P
    ny = (r * (u1h2 - nx) - s1 * h3) % _P
    nz = h * p[2] * q[2] % _P
    return (nx, ny, nz)


def _jac_mult(p, d: int):
    r = (0, 0, 0)
    a = p
    while d:
        if d & 1:
            r = _jac_add(r, a)
        a = _jac_double(a)
        d >>= 1
    return r


def _to_affine(p):
    if not p[1]:
        return None  # point at infinity
    zinv = _inv(p[2], _P)
    z2 = zinv * zinv % _P
    return (p[0] * z2 % _P, p[1] * z2 * zinv % _P)


_G = (_GX, _GY, 1)


@functools.lru_cache(maxsize=8192)
def _decompress(compressed: bytes):
    """(x, y) from a 33-byte SEC1 compressed point; None if invalid.

    LRU-cached on the compressed encoding: repeat senders (the normal
    case for per-account nonce chains under load) skip the modular
    square root on every tx. Invalid encodings cache as None, so a
    malformed-key flood costs one sqrt attempt per distinct key at most,
    and the bound keeps an adversary from growing the table."""
    if len(compressed) != 33 or compressed[0] not in (2, 3):
        return None
    x = int.from_bytes(compressed[1:], "big")
    if x >= _P:
        return None
    y2 = (pow(x, 3, _P) + 7) % _P
    y = pow(y2, (_P + 1) // 4, _P)  # p ≡ 3 (mod 4)
    if y * y % _P != y2:
        return None  # x is not on the curve
    if (y & 1) != (compressed[0] & 1):
        y = _P - y
    return (x, y)


def _compress(x: int, y: int) -> bytes:
    return bytes([2 | (y & 1)]) + x.to_bytes(32, "big")


def _rfc6979_k(x: int, h1: bytes) -> int:
    """RFC 6979 §3.2 deterministic nonce (HMAC-SHA256, qlen = hlen = 256)
    — bit-for-bit what the OpenSSL backend's deterministic_signing does,
    so both backends emit identical signatures."""
    xb = x.to_bytes(32, "big")
    hb = (int.from_bytes(h1, "big") % _N).to_bytes(32, "big")
    v = b"\x01" * 32
    k = b"\x00" * 32
    k = hmac.new(k, v + b"\x00" + xb + hb, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    k = hmac.new(k, v + b"\x01" + xb + hb, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    while True:
        v = hmac.new(k, v, hashlib.sha256).digest()
        kand = int.from_bytes(v, "big")
        if 1 <= kand < _N:
            return kand
        k = hmac.new(k, v + b"\x00", hashlib.sha256).digest()
        v = hmac.new(k, v, hashlib.sha256).digest()


def _py_sign(x: int, message: bytes) -> bytes:
    z = int.from_bytes(_sha(message), "big") % _N
    h1 = _sha(message)
    while True:
        k = _rfc6979_k(x, h1)
        pt = _to_affine(_jac_mult(_G, k))
        r = pt[0] % _N
        if not r:
            h1 = _sha(h1)  # unreachable in practice; restart nonce stream
            continue
        s = _inv(k, _N) * (z + r * x) % _N
        if not s:
            h1 = _sha(h1)
            continue
        if s > _N // 2:
            s = _N - s
        return r.to_bytes(32, "big") + s.to_bytes(32, "big")


def _py_verify(compressed: bytes, signature: bytes, message: bytes) -> bool:
    return _py_verify_digest(compressed, signature, _sha(message))


def _py_verify_digest(compressed: bytes, signature: bytes,
                      digest: bytes) -> bool:
    q = _decompress(compressed)
    if q is None:
        return False
    r = int.from_bytes(signature[:32], "big")
    s = int.from_bytes(signature[32:], "big")
    if not (1 <= r < _N and 1 <= s < _N):
        return False
    z = int.from_bytes(digest, "big") % _N
    w = _inv(s, _N)
    u1 = z * w % _N
    u2 = r * w % _N
    pt = _to_affine(_jac_add(_jac_mult(_G, u1),
                             _jac_mult((q[0], q[1], 1), u2)))
    if pt is None:
        return False
    return pt[0] % _N == r


# ---------------------------------------------------------------------------
# Public API (backend-independent)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=1 << 16)
def _verify_memo(compressed: bytes, signature: bytes,
                 digest: bytes) -> int:
    """The one ECDSA verification body, memoized; 1 valid / 0 invalid /
    -1 exploded (callers count the telemetry per CALL, so a flood of
    replayed malformed triples still shows in /metrics).

    Verification is a pure function of (pubkey, sha256(message),
    signature), and the same triple is verified many times across one
    process: every validator re-checks the same certificate votes in
    cert.verify and _present_set_from_cert, and a light-node FLEET (the
    scenario plane runs hundreds in one process) verifies the identical
    certificates per node. The memo collapses those to one curve
    operation per unique triple — an adversary gains nothing (a
    0/-1 verdict is cached as hard as a 1). Keys hold the 32-byte
    DIGEST, never message bytes (the admission plane's VerifiedSigCache
    made the same choice for the same reason: the tx path verifies
    multi-MB sign docs, and raw-bytes keys would let 65536 entries pin
    gigabytes); ~130 B/entry, bounded LRU."""
    if not _HAVE_OPENSSL:
        try:
            return 1 if _py_verify_digest(compressed, signature,
                                          digest) else 0
        except Exception:
            # malformed point/signature: unique explosions counted here,
            # per-CALL flood visibility counted by the caller
            telemetry.incr("crypto.verify_errors_unique")
            return -1
    try:
        pub = ec.EllipticCurvePublicKey.from_encoded_point(
            _CURVE, compressed)
        r = int.from_bytes(signature[:32], "big")
        s = int.from_bytes(signature[32:], "big")
        from cryptography.hazmat.primitives.asymmetric.utils import (
            encode_dss_signature,
        )

        der = encode_dss_signature(r, s)
        pub.verify(der, digest, ec.ECDSA(Prehashed(hashes.SHA256())))
        return 1
    except Exception:
        # the OpenSSL backend signals an invalid signature by raising
        # too — kept in the counted class, exactly as before the memo
        telemetry.incr("crypto.verify_errors_unique")
        return -1


@dataclasses.dataclass(frozen=True)
class PublicKey:
    compressed: bytes  # 33-byte SEC1 compressed point

    def address(self) -> bytes:
        return _sha(self.compressed)[:ADDRESS_LEN]

    def verify(self, signature: bytes, message: bytes) -> bool:
        """Verify a 64-byte (r || s) signature over sha256(message)."""
        if len(signature) != 64:
            return False
        s = int.from_bytes(signature[32:], "big")
        if s > _N // 2:
            return False  # reject high-S: tx bytes must not be malleable
        code = _verify_memo(self.compressed, signature, _sha(message))
        if code < 0:
            # counted per call (not per unique triple): a flood of
            # exploding verifies must show in /metrics even when the
            # memo answers it
            telemetry.incr("crypto.verify_errors")
            return False
        return code == 1


@dataclasses.dataclass(frozen=True)
class PrivateKey:
    scalar: int

    @classmethod
    def from_seed(cls, seed: bytes) -> "PrivateKey":
        """Deterministic key derivation from a seed (test fixtures, wallets)."""
        d = int.from_bytes(_sha(b"celestia_tpu/key" + seed), "big") % (_N - 1) + 1
        return cls(d)

    @classmethod
    def generate(cls) -> "PrivateKey":
        import secrets

        # key GENERATION is operator-side entropy, never consensus
        return cls(secrets.randbelow(_N - 1) + 1)  # lint: disable=det-rng

    def _key(self):
        return ec.derive_private_key(self.scalar, _CURVE)

    def public_key(self) -> PublicKey:
        if not _HAVE_OPENSSL:
            x, y = _to_affine(_jac_mult(_G, self.scalar))
            return PublicKey(_compress(x, y))
        pub = self._key().public_key()
        compressed = pub.public_bytes(
            serialization.Encoding.X962, serialization.PublicFormat.CompressedPoint
        )
        return PublicKey(compressed)

    def sign(self, message: bytes) -> bytes:
        """64-byte (r || s) low-S signature over sha256(message).

        Deterministic per RFC 6979 (HMAC-SHA256 nonce), like the reference's
        cosmos-sdk secp256k1 signer (btcec) — identical inputs produce
        identical tx bytes, which keeps block data roots reproducible and
        signatures non-malleable.
        """
        if not _HAVE_OPENSSL:
            return _py_sign(self.scalar, message)
        der = self._key().sign(
            _sha(message),
            ec.ECDSA(Prehashed(hashes.SHA256()), deterministic_signing=True),
        )
        r, s = decode_dss_signature(der)
        if s > _N // 2:
            s = _N - s
        return r.to_bytes(32, "big") + s.to_bytes(32, "big")


def address_str(addr: bytes) -> str:
    return "tia1" + addr.hex()
