"""Account keys, addresses, signing.

Reference parity: cosmos-sdk secp256k1 account keys (the reference's account
auth) — here via the `cryptography` library's SECP256K1 ECDSA with SHA-256,
with deterministic low-level DER unwrapping to 64-byte (r || s) signatures.
Addresses are the first 20 bytes of SHA-256(compressed pubkey) (the reference
uses ripemd160(sha256(pk)); ripemd160 is unavailable in this OpenSSL build,
and the address derivation is not consensus-relevant across frameworks).
"""

from __future__ import annotations

import dataclasses
import hashlib

from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.hazmat.primitives.asymmetric.utils import (
    Prehashed,
    decode_dss_signature,
    encode_dss_signature,
)

ADDRESS_LEN = 20
_CURVE = ec.SECP256K1()
# secp256k1 group order, for low-S normalization (signature malleability).
_N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141


def _sha(b: bytes) -> bytes:
    return hashlib.sha256(b).digest()


@dataclasses.dataclass(frozen=True)
class PublicKey:
    compressed: bytes  # 33-byte SEC1 compressed point

    def address(self) -> bytes:
        return _sha(self.compressed)[:ADDRESS_LEN]

    def verify(self, signature: bytes, message: bytes) -> bool:
        """Verify a 64-byte (r || s) signature over sha256(message)."""
        if len(signature) != 64:
            return False
        try:
            pub = ec.EllipticCurvePublicKey.from_encoded_point(_CURVE, self.compressed)
            r = int.from_bytes(signature[:32], "big")
            s = int.from_bytes(signature[32:], "big")
            if s > _N // 2:
                return False  # reject high-S: tx bytes must not be malleable
            der = encode_dss_signature(r, s)
            pub.verify(der, _sha(message), ec.ECDSA(Prehashed(hashes.SHA256())))
            return True
        except Exception:
            return False


@dataclasses.dataclass(frozen=True)
class PrivateKey:
    scalar: int

    @classmethod
    def from_seed(cls, seed: bytes) -> "PrivateKey":
        """Deterministic key derivation from a seed (test fixtures, wallets)."""
        d = int.from_bytes(_sha(b"celestia_tpu/key" + seed), "big") % (_N - 1) + 1
        return cls(d)

    @classmethod
    def generate(cls) -> "PrivateKey":
        import secrets

        return cls(secrets.randbelow(_N - 1) + 1)

    def _key(self) -> ec.EllipticCurvePrivateKey:
        return ec.derive_private_key(self.scalar, _CURVE)

    def public_key(self) -> PublicKey:
        pub = self._key().public_key()
        compressed = pub.public_bytes(
            serialization.Encoding.X962, serialization.PublicFormat.CompressedPoint
        )
        return PublicKey(compressed)

    def sign(self, message: bytes) -> bytes:
        """64-byte (r || s) low-S signature over sha256(message).

        Deterministic per RFC 6979 (HMAC-SHA256 nonce), like the reference's
        cosmos-sdk secp256k1 signer (btcec) — identical inputs produce
        identical tx bytes, which keeps block data roots reproducible and
        signatures non-malleable. OpenSSL's randomized-nonce ECDSA is kept
        for verification only.
        """
        der = self._key().sign(
            _sha(message),
            ec.ECDSA(Prehashed(hashes.SHA256()), deterministic_signing=True),
        )
        r, s = decode_dss_signature(der)
        if s > _N // 2:
            s = _N - s
        return r.to_bytes(32, "big") + s.to_bytes(32, "big")


def address_str(addr: bytes) -> str:
    return "tia1" + addr.hex()
