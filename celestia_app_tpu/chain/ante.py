"""The ante handler chain: every tx admission/execution gate, in order.

Reference parity: the decorator list maps 1:1 to app/ante/ante.go:15-82's
chain, in the reference's order. Decorators with no analog in this
framework are documented no-ops, not silently dropped:

   1. HandlePanicDecorator        — lives in the app shell (ProcessProposal
                                    catches all panics; deliver wraps)
   2. MsgVersioningGateKeeper     — step (2) below
   3. SetUpContextDecorator       — Context/GasMeter creation by the caller
   4. ExtensionOptionsDecorator   — NO-OP: the tx codecs cannot express
                                    extension options, so none can arrive
   5. ValidateBasicDecorator      — step (1)
   6. TxTimeoutHeightDecorator    — step (1), timeout_height
   7. ValidateMemoDecorator       — step (1b), max 256 memo chars
   8. ConsumeGasForTxSizeDecorator— step (3), 10 gas/byte
   9. DeductFeeDecorator          — step (4): gas-price floor (network min
                                    from x/minfee at v2+) + feegrant-aware
                                    deduction (app/ante/fee_checker.go)
  10. SetPubKeyDecorator          — step (5), set_pubkey on first use
  11. ValidateSigCountDecorator   — step (5a), sig count <= 7 (sdk default;
                                    this framework's txs carry exactly one)
  12. SigGasConsumeDecorator      — step (5b), 1000 gas per secp256k1 sig
  13. SigVerificationDecorator    — step (5c), sign-doc binding + sequence
  14. MinGasPFBDecorator          — step (7) (x/blob/ante/ante.go:15-52)
  15. MaxTotalBlobSizeDecorator   — step (7a), v1 + CheckTx only
                                    (x/blob/ante/max_total_blob_size_ante.go)
  16. BlobShareDecorator          — step (7b), v2+ (blob_share_decorator.go)
  17. GovProposalDecorator        — step (8), proposal must carry >= 1
                                    change (app/ante/gov.go)
  18. IncrementSequenceDecorator  — step (6)
  19. RedundantRelayDecorator     — step (9), CheckTx only: a relay tx whose
                                    every packet msg is already processed is
                                    rejected before it wastes block space
                                    (ibc-go core/ante)
"""

from __future__ import annotations

import dataclasses

from celestia_app_tpu import appconsts
from celestia_app_tpu.chain import modules
from celestia_app_tpu.chain.state import Context
from celestia_app_tpu.utils import telemetry
from celestia_app_tpu.chain.tx import (
    MsgBeginRedelegate,
    MsgCreateValidator,
    MsgDelegate,
    MsgDeposit,
    MsgExec,
    MsgPayForBlobs,
    MsgRegisterEVMAddress,
    MsgSend,
    MsgSignalVersion,
    MsgSubmitProposal,
    MsgTryUpgrade,
    MsgTransfer,
    MsgUndelegate,
    MsgVote,
    MsgRecvPacket,
    MsgAcknowledgePacket,
    MsgTimeoutPacket,
    MsgUpdateClient,
    Tx,
)
from celestia_app_tpu.chain.crypto import PublicKey
from celestia_app_tpu.da import shares as shares_mod


class AnteError(Exception):
    pass


MAX_MEMO_CHARACTERS = 256  # sdk auth params default (ValidateMemoDecorator)
TX_SIG_LIMIT = 7  # sdk auth params default (ValidateSigCountDecorator)
SIG_VERIFY_COST_SECP256K1 = 1000  # DefaultSigVerificationGasConsumer


# Msg acceptance by app version (app/module configurator GetAcceptedMessages:
# signal msgs exist from v2; blobstream registration only at v1).
MSG_VERSIONS: dict[str, tuple[int, int]] = {
    MsgSend.TYPE: (1, 99),
    MsgPayForBlobs.TYPE: (1, 99),
    MsgRegisterEVMAddress.TYPE: (1, 1),
    MsgSignalVersion.TYPE: (2, 99),
    MsgTryUpgrade.TYPE: (2, 99),
    MsgDelegate.TYPE: (1, 99),
    MsgUndelegate.TYPE: (1, 99),
    MsgBeginRedelegate.TYPE: (1, 99),
    MsgCreateValidator.TYPE: (1, 99),
    MsgSubmitProposal.TYPE: (1, 99),
    MsgDeposit.TYPE: (1, 99),
    MsgVote.TYPE: (1, 99),
    MsgTransfer.TYPE: (1, 99),
    MsgExec.TYPE: (1, 99),
    MsgRecvPacket.TYPE: (1, 99),
    MsgAcknowledgePacket.TYPE: (1, 99),
    MsgTimeoutPacket.TYPE: (1, 99),
    MsgUpdateClient.TYPE: (1, 99),
}


def msg_signer(m) -> bytes | None:
    """The message's native signer address (authz exec checks the inner
    message's signer against its grant)."""
    if isinstance(m, MsgSend):
        return m.from_addr
    if isinstance(m, MsgPayForBlobs):
        return m.signer
    if isinstance(m, MsgSignalVersion):
        return m.validator
    if isinstance(m, MsgTryUpgrade):
        return m.signer
    if isinstance(m, MsgRegisterEVMAddress):
        return m.validator
    if isinstance(m, (MsgDelegate, MsgUndelegate, MsgBeginRedelegate)):
        return m.delegator
    if isinstance(m, MsgCreateValidator):
        return m.operator
    if isinstance(m, MsgSubmitProposal):
        return m.proposer
    if isinstance(m, MsgDeposit):
        return m.depositor
    if isinstance(m, MsgVote):
        return m.voter
    if isinstance(m, MsgTransfer):
        return m.sender
    if isinstance(m, MsgExec):
        return m.grantee
    if isinstance(m, (MsgRecvPacket, MsgAcknowledgePacket, MsgTimeoutPacket,
                      MsgUpdateClient)):
        return m.relayer
    return None


@dataclasses.dataclass
class AnteHandler:
    auth: modules.AuthKeeper
    bank: modules.BankKeeper
    blob: modules.BlobKeeper
    minfee: modules.MinFeeKeeper
    min_gas_price: float = appconsts.DEFAULT_MIN_GAS_PRICE
    feegrant: object | None = None  # FeeGrantKeeper when enabled
    ibc: object | None = None  # IBCStack for the redundant-relay decorator
    # the App's VerifiedSigCache (chain/admission.py): step 5c consults it
    # before paying a scalar verification, and records its own successes,
    # so a signature checked ONCE (batched or scalar, any phase) is never
    # re-verified at proposal, delivery, or replay time
    sig_cache: object | None = None

    def __post_init__(self):
        # node-local floor, parsed once (it is fixed for the handler's life)
        self._min_gas_price_atto = appconsts.gas_price_to_atto(self.min_gas_price)

    def run(self, ctx: Context, tx: Tx, simulate: bool = False) -> None:
        """Raises AnteError when the tx must be rejected; consumes gas."""
        body = tx.body
        # protobuf txs carry no chain_id/account_number in their bytes —
        # both bind through the SIGN_MODE_DIRECT sign doc, verified below
        # with ctx values (the SDK's SigVerificationDecorator pattern)
        is_proto = getattr(tx, "wire_format", "native") == "proto"
        # 1. basic validation
        if not body.msgs:
            raise AnteError("empty tx")
        if body.gas_limit <= 0:
            raise AnteError("zero gas limit")
        if not is_proto and body.chain_id != ctx.chain_id:
            raise AnteError(f"wrong chain id {body.chain_id!r}")
        if body.timeout_height and ctx.height > body.timeout_height:
            raise AnteError("tx timed out")
        # 1b. memo length (ValidateMemoDecorator, sdk MaxMemoCharacters)
        if len(body.memo) > MAX_MEMO_CHARACTERS:
            raise AnteError(
                f"memo length {len(body.memo)} exceeds {MAX_MEMO_CHARACTERS}"
            )

        # 2. version gatekeeper (circuit breaker). Walks authz-nested
        # messages too — the reference's MsgVersioningGateKeeper inspects
        # MsgExec contents (app/ante/msg_gatekeeper.go), so wrapping a gated
        # message in MsgExec must not smuggle it past the breaker.
        def _gate(msgs, nested: bool):
            for m in msgs:
                lo, hi = MSG_VERSIONS.get(m.TYPE, (99, 99))
                if not (lo <= ctx.app_version <= hi):
                    raise AnteError(
                        f"message {m.TYPE} not accepted at app version "
                        f"{ctx.app_version}"
                    )
                if isinstance(m, MsgExec):
                    if nested:
                        raise AnteError("nested MsgExec is not allowed")
                    _gate(m.inner, nested=True)
                elif nested and isinstance(m, MsgPayForBlobs):
                    # a PFB must ride in a BlobTx with its blobs; authz
                    # wrapping would break the DA pairing invariant
                    raise AnteError("MsgPayForBlobs cannot be nested in MsgExec")

        _gate(body.msgs, nested=False)

        # 3. tx size gas
        size = len(tx.encode())
        ctx.gas_meter.consume(
            size * appconsts.versioned(ctx.app_version).tx_size_cost_per_byte,
            "tx size",
        )

        # 4. fee check + deduction. The comparison is exact integer
        # cross-multiplication in atto units — no float ever decides
        # admission (fee_checker.go uses sdk.Dec the same way).
        if ctx.is_check_tx:
            floor_atto = self._min_gas_price_atto
            if ctx.app_version >= 2:
                floor_atto = max(
                    floor_atto, self.minfee.network_min_gas_price_atto(ctx)
                )
        else:
            # at delivery only the network floor binds (fee_checker.go)
            floor_atto = (
                self.minfee.network_min_gas_price_atto(ctx)
                if ctx.app_version >= 2
                else 0
            )
        if not simulate and body.fee * appconsts.ATTO < body.gas_limit * floor_atto:
            # simulation probes carry placeholder fees (the SDK's simulate
            # mode skips the min-gas-price adequacy check the same way)
            raise AnteError(
                # display-only divisions: the gate above compares ints
                f"insufficient gas price: {body.fee / body.gas_limit:.9f} "  # lint: disable=det-float
                f"< min {floor_atto / appconsts.ATTO:.9f}"  # lint: disable=det-float
            )

        signer = self._signer(body)
        if not simulate:
            payer = signer
            if body.fee_granter:
                # feegrant DeductFeeDecorator: the granter pays if a live
                # allowance covers the fee
                if self.feegrant is None:
                    raise AnteError("fee grants are not enabled")
                try:
                    self.feegrant.use_grant(ctx, body.fee_granter, signer, body.fee)
                except ValueError as e:
                    raise AnteError(f"fee grant: {e}") from None
                payer = body.fee_granter
            try:
                self.bank.send(ctx, payer, modules.FEE_COLLECTOR, body.fee)
            except ValueError as e:
                raise AnteError(f"cannot pay fee: {e}") from None

        # 5a. sig count bound (ValidateSigCountDecorator): tx codecs carry
        # exactly one signature, kept under the sdk limit of 7
        sig_count = 1
        if sig_count > TX_SIG_LIMIT:
            raise AnteError("too many signatures")
        # 5b. signature-verification gas (SigGasConsumeDecorator): charged
        # in simulate mode too — the sdk simulates with a stand-in sig so
        # gas estimates cover the real verification cost
        ctx.gas_meter.consume(
            sig_count * SIG_VERIFY_COST_SECP256K1, "sig verification"
        )

        # 5c. signature verification
        if not simulate:
            if PublicKey(tx.pubkey).address() != signer:
                raise AnteError("pubkey does not match signer address")
            acc = self.auth.ensure_account(ctx, signer)
            if not is_proto and acc["number"] != body.account_number:
                raise AnteError(
                    f"account number mismatch: got {body.account_number}, want {acc['number']}"
                )
            if acc["sequence"] != body.sequence:
                raise AnteError(
                    f"account sequence mismatch, expected {acc['sequence']}, got {body.sequence}"
                )
            # the sign doc covers chain id + account number (proto), so a
            # tx signed for another chain or account number fails here.
            # The verified-sig cache (admission plane) keys on the EXACT
            # doc bytes: a hit can only skip a verification that would
            # have returned True on identical inputs.
            doc = (tx.sign_doc(ctx.chain_id, acc["number"]) if is_proto
                   else tx.sign_doc())
            cache = self.sig_cache
            key = None
            if cache is not None:
                key = cache.key(tx.pubkey, tx.signature, doc)
            if key is None or not cache.hit(key):
                # verify over the doc already in hand (verify_signature
                # would re-serialize the identical bytes)
                if not PublicKey(tx.pubkey).verify(tx.signature, doc):
                    raise AnteError("signature verification failed")
                telemetry.incr("admission.sig_scalar_verified")
                if cache is not None:
                    cache.put(key)
            self.auth.set_pubkey(ctx, signer, tx.pubkey)

        # 7. blob decorators
        for m in body.msgs:
            if isinstance(m, MsgPayForBlobs):
                self._check_pfb(ctx, m, body)

        # 8. gov proposal decorator (app/ante/gov.go): a MsgSubmitProposal
        # with zero proposal messages is dead weight — reject at admission
        import json as _json

        for m in body.msgs:
            if isinstance(m, MsgSubmitProposal):
                try:
                    changes = _json.loads(m.changes_json)
                except ValueError:
                    # the reference's decorator only counts messages; payload
                    # VALIDITY is delivery's concern (a malformed proposal
                    # fails its own tx there, never the chain)
                    continue
                if isinstance(changes, list) and not changes:
                    raise AnteError(
                        "must include at least one message in proposal"
                    )

        if not simulate:
            # 6/18. sequence increment (IncrementSequenceDecorator sits
            # near the end of the reference chain, after all validity gates)
            self.auth.increment_sequence(ctx, signer)

        # 9. redundant-relay decorator (ibc-go core/ante, CheckTx only): a
        # tx made ENTIRELY of already-processed relay msgs burns mempool
        # and block space with no effect — drop it at admission. Deliver
        # keeps the keeper-level no-op semantics (racing relayers are
        # normal; only the mempool gate rejects).
        if ctx.is_check_tx and self.ibc is not None:
            self._check_redundant_relay(ctx, body)

    def _check_redundant_relay(self, ctx: Context, body) -> None:
        relay_msgs = [
            m for m in body.msgs
            if isinstance(m, (MsgRecvPacket, MsgAcknowledgePacket,
                              MsgTimeoutPacket))
        ]
        if not relay_msgs or len(relay_msgs) != len(body.msgs):
            return  # mixed or non-relay txs pass through (ibc-go semantics)
        import json as _json

        channels = self.ibc.channels
        for m in relay_msgs:
            try:
                packet = _json.loads(m.packet_json)
                if isinstance(m, MsgRecvPacket):
                    if channels.get_ack(ctx, packet) is None:
                        return  # at least one msg still does work
                else:
                    # ack/timeout settle OUR commitment; absence == settled
                    key = channels.COMMIT + (
                        f"{packet['source_port']}/{packet['source_channel']}/"
                        f"{packet['sequence']}".encode()
                    )
                    if ctx.store.get(key) is not None:
                        return
            except (ValueError, KeyError, TypeError, AttributeError):
                # malformed packet: not redundant — the relay handler will
                # fail THIS tx with a real error message downstream
                return
        raise AnteError("redundant relay: every packet msg already processed")

    def _signer(self, body) -> bytes:
        addrs = {msg_signer(m) for m in body.msgs}
        addrs.discard(None)
        if len(addrs) != 1:
            raise AnteError(f"tx must have exactly one signer, got {len(addrs)}")
        return next(iter(addrs))

    def _check_pfb(self, ctx: Context, msg: MsgPayForBlobs, body) -> None:
        # MinGasPFBDecorator: enough gas for the blob bytes
        params = self.blob.params(ctx)
        needed = self.blob.gas_to_consume(msg.blob_sizes, params["gas_per_blob_byte"])
        if body.gas_limit < needed:
            raise AnteError(
                f"gas limit {body.gas_limit} below blob gas requirement {needed}"
            )
        max_sq = min(
            params["gov_max_square_size"],
            appconsts.square_size_upper_bound(ctx.app_version),
        )
        if ctx.app_version == 1:
            # MaxTotalBlobSizeDecorator (v1 + CheckTx only, max_total_blob_
            # size_ante.go:26-33): total blob BYTES must fit the bytes
            # available to sparse shares in the max square, less the one
            # share the PFB tx itself occupies
            if ctx.is_check_tx:
                blob_shares = max_sq * max_sq - 1
                available = (
                    appconsts.FIRST_SPARSE_SHARE_CONTENT_SIZE
                    + (blob_shares - 1)
                    * appconsts.CONTINUATION_SPARSE_SHARE_CONTENT_SIZE
                )
                total = sum(msg.blob_sizes)
                if total > available:
                    raise AnteError(
                        f"total blob size {total} exceeds max {available}"
                    )
            return
        # BlobShareDecorator (v2+, blob_share_decorator.go:28-63): blobs
        # must fit the governed square, counted in shares
        total_shares = sum(
            shares_mod.sparse_shares_needed(s) for s in msg.blob_sizes
        )
        if total_shares > max_sq * max_sq:
            raise AnteError(
                f"blob shares {total_shares} exceed square capacity {max_sq * max_sq}"
            )
