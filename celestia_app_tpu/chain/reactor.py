"""Autonomous consensus reactor: each validator drives its OWN rounds.

Reference parity: celestia-core's consensus reactor (SURVEY §5.8) — every
validator process runs the Tendermint round state machine itself and
exchanges proposals/votes with peers over the network; there is no
coordinator. This module is that state machine for this framework's
validator processes: a background thread per process stepping

    propose -> prevote -> (polka? lock) -> precommit -> commit

with wall-clock phase timeouts escalating per failed round, deterministic
proposer rotation, peer-to-peer flooding of proposals and votes over the
validator HTTP services (service/validator_server.py /gossip/* routes),
and commit-certificate assembly from each node's own received votes.

Determinism of commit info (the one subtlety vs the orchestrated
SocketNetwork): every node assembles a DIFFERENT >2/3 certificate from
gossip, but liveness accounting and evidence must be identical across the
network or app hashes diverge. Tendermint solves this by putting
LastCommitInfo and evidence IN the block; here the signed Proposal
envelope (chain/consensus.py Proposal) carries the height-1 certificate
and the evidence list, and apply() consumes THOSE for absence accounting
(absent_cert=) while storing the locally-assembled cert for the height.

Trust model: all inbound gossip is verified locally — proposal signatures
against the expected proposer for (height, round), vote signatures
against genesis pubkeys, certificates against the node's own staking
powers — a byzantine peer can at most waste inbox space. Vote signatures
commit to (chain_id, height, ROUND, hash, phase) — Tendermint's
CanonicalVote fields (celestia-core types/vote.go) — so a relayed
old-round vote cannot be replayed into a newer round, certificates are
round-scoped (Commit.round), and per-round attribution is exact; the
unlock-on-higher-polka rule this enables keeps a locked validator live
when the network polkas a different block in a later round.

Catch-up (the sync plane, chain/sync.py + docs/DESIGN.md): a node that
misses the commit gossip for its next height pulls peers' commit
records in batched windows (GET /gossip/commits, per-height
/gossip/commit_at as the fallback) and replays them through the same
verification live gossip gets — verified blocksync, any gap depth, with
the next window prefetched while the current one verifies. Chunked,
parallel, resumable state sync (GET /sync/snapshots + /sync/chunk,
app-hash-anchored adoption) covers gaps beyond cfg.statesync_gap or
records no peer can serve; the node also WRITES interval snapshots for
peers to join from (cfg.snapshot_interval).
"""

from __future__ import annotations

import dataclasses
import json
import threading

from celestia_app_tpu import obs
from celestia_app_tpu.chain import consensus as c
from celestia_app_tpu.chain.state import Context, InfiniteGasMeter
from celestia_app_tpu.net.transport import PeerClient, TransportConfig
from celestia_app_tpu.utils import telemetry

log = obs.get_logger("chain.reactor")


@dataclasses.dataclass
class ReactorConfig:
    """Phase timeouts (seconds). The COLD defaults cover a first proposal
    paying a jit compile on device engines; once this node commits its
    first height (the compile cache is hot), timeouts auto-scale down to
    the warm values — the reference's mainnet shape is TimeoutPropose
    10 s / TimeoutCommit 11 s (pkg/appconsts/consensus_consts.go:6-13),
    and the warm defaults match it. Steady-state block interval is
    therefore bounded by warm_propose + warm_prevote + warm_precommit +
    block_interval in the worst (full-timeout) round, and by gossip
    latency (~tens of ms on a devnet) when all validators are live."""

    timeout_propose: float = 30.0
    timeout_prevote: float = 20.0
    timeout_precommit: float = 20.0
    # post-first-commit shape (reference parity)
    warm_propose: float = 10.0
    warm_prevote: float = 5.0
    warm_precommit: float = 5.0
    timeout_delta: float = 5.0  # added per failed round
    block_interval: float = 0.05  # pause between committed heights
    poll: float = 0.02  # inbox poll granularity
    gossip_timeout: float = 5.0  # per-peer HTTP send timeout
    recent_commits: int = 8  # commit records served to laggards
    sync_grace: float = 5.0  # how long "peer ahead" persists before sync
    # artificial per-message send latency (seconds): software-level network
    # condition injection, the role BitTwister plays in the reference's e2e
    # benchmarks (test/e2e/benchmark/benchmark.go:110-117 injects 70 ms)
    gossip_delay: float = 0.0
    # verified blocksync (celestia-core blocksync analog): commit records
    # are persisted per height and served to laggards from disk, so a
    # node down ANY number of heights replays block-by-block with cert
    # verification against its own then-current valset. Per reactor step
    # at most `blocksync_batch` heights replay (keeps the loop
    # responsive); for a gap wider than `statesync_gap` a snapshot is
    # attempted ONCE per catch-up episode first (replay continues either
    # way). The record store keeps `commit_records_keep` heights — a
    # laggard farther back than any peer's window state-syncs instead.
    blocksync_batch: int = 64
    statesync_gap: int = 512
    commit_records_keep: int = 10_000
    # the sync plane (chain/sync.py): pipelined blocksync pulls commit
    # records in `blocksync_batch`-height windows via GET /gossip/commits
    # and prefetches window N+1 on a background thread while window N
    # runs the unchanged per-height verification — the replay loop is
    # verification-bound, not RTT-bound. `blocksync_serve_bytes` caps one
    # served range response; `blocksync_pipeline=False` keeps the
    # per-height round-trip loop (the differential baseline bench.py
    # --sync measures against).
    blocksync_pipeline: bool = True
    blocksync_serve_bytes: int = 2 << 20
    # chunked state sync: parallel chunk fetchers per restore, and the
    # interval snapshots this node WRITES for peers to join from
    # (default_overrides.go:294-297 interval 1500 keep 2; 0 disables).
    # Snapshots land under <home>/snapshots via chain/sync.SnapshotStore;
    # in-memory nodes (no data_dir) never write them.
    statesync_workers: int = 4
    snapshot_interval: int = 1500
    snapshot_keep: int = 2
    # shared-transport hardening (net/transport.py): gossip is fire-and-
    # forget so sends make ONE attempt (the pull paths recover anything
    # that matters); `breaker_failures` consecutive failures open the
    # peer's circuit and sends are SKIPPED (not retried every tick) until
    # a half-open probe after `breaker_reset` seconds succeeds
    net_retries: int = 1
    breaker_failures: int = 3
    breaker_reset: float = 2.5
    # the mesh plane's produce→commit batching (chain/producer.py): a
    # proposer with produce_batch > 1 speculatively plans that many
    # upcoming proposal squares from its mempool and batch-extends them
    # in ONE device dispatch BEFORE taking the service lock to propose,
    # seeding the EDS cache with device-resident entries. Consensus
    # bytes are unchanged (the batch is a prefetch); fed from the home
    # config `produce_batch` key (cli.py). 1 = off.
    produce_batch: int = 1


class ConsensusReactor:
    """The per-validator round state machine (one thread per process)."""

    def __init__(self, vnode, peer_urls: list[str], service_lock,
                 config: ReactorConfig | None = None,
                 self_url: str = "", clock=None):
        from celestia_app_tpu.utils import clock as clock_mod

        self.vnode = vnode
        self.peers = [u.rstrip("/") for u in peer_urls]
        self.service_lock = service_lock
        self.cfg = config or ReactorConfig()
        # THE reactor time source (utils/clock.py): every poll/backoff/
        # deadline/timestamp below reads it — SystemClock by default
        # (production behavior pinned unchanged), a VirtualClock when a
        # scheduler drives this reactor on simulated time. Handed down to
        # the transport so breaker timers and retry backoffs ride the
        # same timeline.
        self.clock = clock if clock is not None else clock_mod.SYSTEM
        # peer-visible URL of THIS node: rides SeenTx announces so the
        # receiver knows whom to WantTx-pull the content from
        self.self_url = self_url.rstrip("/")
        # rotation order: operator addresses of the CURRENT staked set
        # (sorted), refreshed from state at every commit — a runtime
        # MsgCreateValidator(pubkey=...) joins the schedule the height
        # after it commits, Tendermint's valset-update flow. Genesis
        # pubkeys seed the set; every process derives the identical
        # schedule from its own state, no exchange needed.
        self.rotation = sorted(self.vnode.validator_pubkeys.keys())
        self._pubkey_cache = dict(self.vnode.validator_pubkeys)
        if not self.rotation:
            raise ValueError(
                "autonomous consensus needs genesis validator pubkeys"
            )
        # THE peer transport for everything this reactor sends or pulls:
        # gossip floods, WantTx pulls, status probes, blocksync record
        # fetches, state sync. One instance so breaker/health state is
        # per-PEER across all of them — a peer that hard-fails gossip is
        # also skipped by the pull paths until its half-open probe clears.
        self.net = PeerClient(
            TransportConfig(
                timeout=self.cfg.gossip_timeout,
                retries=self.cfg.net_retries,
                failure_threshold=self.cfg.breaker_failures,
                reset_timeout=self.cfg.breaker_reset,
            ),
            name=vnode.name,
            clock=self.clock,
        )
        self.round = 0
        self.step = "idle"
        self.loop_errors = 0  # counted, surfaced in /consensus/status
        # sync-plane failure counters (surfaced in /consensus/status's
        # reactor block + telemetry): a dead snapshot peer or a failing
        # record fetch must be VISIBLE, not silently swallowed into the
        # catch-up loop's return False
        self.statesync_errors = 0
        self.blocksync_fetch_errors = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # inbox (guarded by _msg_lock; handlers must never block on the
        # service lock, or a slow propose would starve vote intake)
        self._msg_lock = threading.Lock()
        self._proposals: dict[tuple[int, int], c.Proposal] = {}
        self._votes: dict[tuple[int, int, str], dict[bytes, c.Vote]] = {}
        # next height at which the proposer re-plans a produce batch
        # (mesh plane; one plan per produce_batch window)
        self._prewarm_after = 0
        self._pending_commits: list[dict] = []
        self._vote_pool: list[c.Vote] = []  # precommits, for evidence
        self._recent: dict[int, dict] = {}  # height -> gossiped commit doc
        self._ahead: tuple[int, str, float] | None = None  # (h, peer, t)
        self.height_view = self.vnode.app.height + 1  # for status only
        self.app_hashes: dict[int, str] = {}  # height -> hex (divergence checks)
        self._senders: dict[str, object] = {}  # peer url -> send queue
        # the mempool reactor's want/have protocol state (SeenTx/WantTx/Tx
        # — mempool/gossip.py); replaces the blind tx flood
        from celestia_app_tpu.mempool.gossip import MempoolGossip

        self.mempool_gossip = MempoolGossip(
            self.vnode.pool, self.peers, self.self_url
        )
        self._pending_txs: list[tuple[bytes, str]] = []  # direct deliveries
        self._pending_wants: list[tuple[bytes, str]] = []  # (hash, provider)
        # powers snapshot from just BEFORE our latest commit: the set that
        # signed that height's certificate (validators for height H come
        # from state after H-1). Verifying a height-1 cert against POST-
        # apply powers would mis-count when that block slashed a signer.
        self._last_powers: tuple[int, dict[bytes, int]] | None = None
        # proposers we have seen a valid proposal (or applied commit)
        # from: the warm propose-timeout applies only to them — a
        # never-seen proposer may be paying its cold jit compile, the
        # exact case the cold default exists for
        self._seen_proposers: set[bytes] = set()
        # snapshot-first attempted this catch-up episode? (reset when
        # caught up; replay continues regardless of the attempt)
        self._statesync_tried: bool = False
        # proposers whose last expected proposal timed out while they
        # were in _seen_proposers: they get ONE cold-window retry (a
        # restarted validator repays its jit compile); a second timeout
        # means dead, back to warm windows so rotation stays fast
        self._cold_retry: set[bytes] = set()
        # pipelined blocksync: the one-slot prefetch window — while the
        # reactor verifies/applies batch N, a background thread fills
        # this slot with batch N+1 (chain/sync plane)
        self._prefetch_lock = threading.Lock()
        self._prefetched: tuple[int, list[dict]] | None = None  # guarded-by: _prefetch_lock
        self._prefetch_thread: threading.Thread | None = None  # guarded-by: _prefetch_lock
        # the snapshot set THIS node serves for chunked state sync
        # (<home>/snapshots; None for in-memory nodes) — written at
        # cfg.snapshot_interval commits, outside the writer lock
        from celestia_app_tpu.chain import sync as sync_mod

        self.snapshot_store = sync_mod.store_for(self.vnode)

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        with self.service_lock:
            self._refresh_valset()  # a resumed node's set may differ from genesis
            self._drop_records_above(self.vnode.app.height)
        self._start_senders()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)

    # -- outbound gossip -------------------------------------------------

    def _gossip(self, path: str, payload: dict) -> None:
        """Fire-and-forget flood to every peer (fully-connected devnet
        topology). One daemon sender per peer drains a queue, so a dead
        peer costs ONE blocked thread regardless of message rate, and
        messages to a live peer stay ordered. The enqueuer's span context
        rides along (obs.capture), so the cross-thread send is recorded
        as part of the originating round's trace."""
        ctx = obs.capture()
        for u in self.peers:
            try:
                self._senders[u].put_nowait((path, payload, ctx))
            except Exception:
                # queue full (peer long dead): drop — gossip is best-
                # effort; the pull-probe recovers anything that matters
                telemetry.incr("reactor.gossip_dropped")

    def _start_senders(self) -> None:
        """One sender queue+thread per peer, created once at start (the
        peer list is static for a reactor's lifetime)."""
        import queue

        for url in self.peers:
            q = queue.Queue(maxsize=256)
            self._senders[url] = q

            def drain(u: str = url, qq=q) -> None:
                while not self._stop.is_set():
                    try:
                        item = qq.get(timeout=1.0)
                    except queue.Empty:
                        continue
                    path, payload, ctx = item
                    if self.cfg.gossip_delay > 0:  # injected latency
                        self.clock.sleep(self.cfg.gossip_delay)
                    if not self.net.available(u):
                        # circuit open: SKIP the peer instead of paying a
                        # connect timeout per queued message — gossip is
                        # best-effort and the pull probes recover anything
                        # that matters once the breaker half-opens
                        telemetry.incr("net.send_skipped")
                        continue
                    try:
                        # resume the enqueuer's span context so this send
                        # (and the peer's receive, via the trace header
                        # the transport injects) joins the height's trace
                        with obs.resume(ctx, "gossip.send", peer=u,
                                        path=path):
                            self.net.post(u, path, payload)
                    except (OSError, ValueError):
                        # counted, never silent: the transport's per-peer
                        # failure tally (net snapshot) carries the detail
                        telemetry.incr("net.send_failures")

            threading.Thread(target=drain, daemon=True).start()

    # -- inbound gossip (HTTP handler threads; _msg_lock only) -----------

    def on_proposal(self, doc: dict) -> None:
        prop = c.proposal_from_json(doc)
        expected = self.rotation[
            (prop.height + prop.round) % len(self.rotation)
        ]
        if prop.proposer != expected:
            return
        pub = self._pubkey_cache.get(prop.proposer)
        if pub is None or not prop.verify(self.vnode.app.chain_id, pub):
            return
        with self._msg_lock:
            self._proposals.setdefault((prop.height, prop.round), prop)
            self._seen_proposers.add(prop.proposer)  # warm-timeout gate
        telemetry.incr("reactor.gossip.proposals")
        self._note_height(prop.height)

    def on_vote(self, doc: dict) -> None:
        vote = c.vote_from_json(doc["vote"])
        pub = self._pubkey_cache.get(vote.validator)
        if pub is None:
            return
        # the vote's OWN signed round is authoritative — the envelope
        # round is transport metadata a relayer could rewrite
        signed = c.Vote.sign_bytes(
            self.vnode.app.chain_id, vote.height, vote.block_hash,
            vote.phase, vote.round,
        )
        from celestia_app_tpu.chain.crypto import PublicKey

        if not PublicKey(pub).verify(vote.signature, signed):
            return
        with self._msg_lock:
            pool = self._votes.setdefault(
                (vote.height, vote.round, vote.phase), {}
            )
            fresh = vote.validator not in pool
            pool.setdefault(vote.validator, vote)
            if fresh and vote.block_hash is not None:
                # both phases feed the evidence pool: same-round
                # duplicates in either phase are slashable
                self._vote_pool.append(vote)
        telemetry.incr("reactor.gossip.votes")
        self._note_height(vote.height)

    def on_commit(self, doc: dict, peer: str = "") -> None:
        """A peer announces a committed height: queue for the loop (the
        handler must not grab the service lock — apply can take seconds)."""
        with self._msg_lock:
            self._pending_commits.append(doc)
        try:
            self._note_height(int(doc["cert"]["height"]), peer)
        except (KeyError, TypeError, ValueError):
            pass

    def commit_at(self, height: int) -> dict | None:
        with self._msg_lock:
            doc = self._recent.get(height)
        if doc is None:
            # blocksync: any persisted height serves a laggard, not just
            # the in-memory recent window
            doc = self._load_commit_record(height)
        return doc

    def commits_range(self, lo: int, hi: int) -> list[dict]:
        """Batched blocksync serving (GET /gossip/commits?from=&to=):
        consecutive commit records from `lo` up to `hi` inclusive,
        clamped to one cfg.blocksync_batch window and to
        cfg.blocksync_serve_bytes of encoded payload (always at least
        one record when one exists). A gap ends the response — the
        requester falls back to per-height pulls / other peers there."""
        if lo < 1 or hi < lo:
            return []
        hi = min(hi, lo + self.cfg.blocksync_batch - 1)
        out: list[dict] = []
        size = 0
        for h in range(lo, hi + 1):
            doc = self.commit_at(h)
            if doc is None:
                break
            size += len(json.dumps(doc))
            if out and size > self.cfg.blocksync_serve_bytes:
                break
            out.append(doc)
        return out

    # -- mempool gossip: the CAT want/have reactor (mempool/gossip.py) ---
    # SeenTx (32-byte hash announce) replaces the old full-tx flood; a
    # peer that wants the content pulls it (WantTx -> Tx) from an
    # announcer. Per-peer have-sets and redundant-want suppression keep
    # tx payload bytes to ~one transfer per edge that needs it.

    def _announce_tx(self, h: bytes) -> None:
        """SeenTx to every peer not known to have the tx (hash + our URL,
        never the payload); marks the hash processed."""
        with self._msg_lock:
            self.mempool_gossip.first_seen(h)  # idempotent mark
            targets = self.mempool_gossip.announce_targets(h)
        payload = {"hash": h.hex(), "from": self.self_url}
        ctx = obs.capture()  # announces join the round's trace too
        for u in targets:
            try:
                self._senders[u].put_nowait(
                    ("/gossip/seen_tx", payload, ctx)
                )
            except Exception:
                telemetry.incr("reactor.gossip_dropped")  # best-effort

    def gossip_tx(self, raw: bytes) -> None:
        """Announce a locally-admitted tx to peers (mempool reactor out);
        dedup-gated so a duplicate /broadcast_tx does not re-announce."""
        from celestia_app_tpu.mempool.pool import tx_hash

        h = tx_hash(raw)
        with self._msg_lock:
            fresh = not self.mempool_gossip.seen(h)
        if fresh:
            self._announce_tx(h)

    def on_seen_tx(self, doc: dict) -> None:
        """A peer announces it HAS a tx: queue a pull if we want it (the
        handler must not do network I/O or take the writer lock)."""
        h = bytes.fromhex(doc["hash"])
        if len(h) != 32:
            raise ValueError("seen_tx hash must be 32 bytes")
        provider = str(doc.get("from", "")).rstrip("/")
        with self._msg_lock:
            if self.mempool_gossip.on_seen(h, provider) and provider:
                self._pending_wants.append((h, provider))
        telemetry.incr("reactor.gossip.seen_tx")

    def serve_want_tx(self, h: bytes, to_peer: str = "") -> bytes | None:
        """Inbound WantTx pull: deliver the tx bytes from the pool."""
        with self._msg_lock:
            return self.mempool_gossip.serve_want(h, to_peer)

    def on_tx(self, doc: dict) -> None:
        """Direct Tx push (legacy flood delivery, still accepted): queue
        for the reactor loop — like every gossip intake, this handler must
        not touch the writer lock (a delivery during a slow apply() would
        pile up blocked handler threads)."""
        import base64

        raw = base64.b64decode(doc["tx"])
        from celestia_app_tpu.mempool.pool import tx_hash

        h = tx_hash(raw)
        with self._msg_lock:
            if not self.mempool_gossip.first_seen(h):
                return
            self.mempool_gossip.on_delivered(h, raw, "")
            self._pending_txs.append((raw, ""))

    def _pull_tx(self, h: bytes, provider: str) -> bytes | None:
        """WantTx: pull tx content from an announcer; on failure fall
        through that hash's remaining candidate providers."""
        import base64

        url = provider
        while url:
            try:
                doc = self.net.get(url, f"/gossip/want_tx?hash={h.hex()}")
                tx_b64 = doc.get("tx")
                if tx_b64:
                    raw = base64.b64decode(tx_b64)
                    with self._msg_lock:
                        self.mempool_gossip.on_delivered(h, raw, url)
                    return raw
            except (OSError, ValueError):
                pass
            with self._msg_lock:
                url = self.mempool_gossip.pull_failed(h)
        return None

    def _admit_pending_txs(self) -> None:
        """The mempool-reactor loop half: drain queued WantTx pulls and
        direct deliveries, admit through the ONE CAT admission path —
        two-phase: the whole drained queue pays a single stateless
        signature-prevalidation dispatch (admission plane phase 1,
        OUTSIDE the service lock so a first-batch jit compile cannot
        stall the consensus loop) before the per-tx stateful CheckTx —
        and re-announce admitted txs to peers not known to have them."""
        with self._msg_lock:
            wants, self._pending_wants = self._pending_wants, []
            pending, self._pending_txs = self._pending_txs, []
        for h, provider in wants:
            raw = self._pull_tx(h, provider)
            if raw is not None:
                pending.append((raw, provider))
        if not pending:
            return
        from celestia_app_tpu.mempool.pool import tx_hash

        raws = [raw for raw, _src in pending]
        self.vnode.prevalidate_txs(raws)
        with self.service_lock:
            results = [self.vnode.add_tx(raw) for raw in raws]
        for (raw, _src), res in zip(pending, results):
            if res.code == 0:
                # announce UNCONDITIONALLY (not via gossip_tx's dedup
                # gate): a direct-push delivery already consumed
                # first_seen in on_tx, but its admission still has to be
                # announced to peers the pusher may not reach
                self._announce_tx(tx_hash(raw))
            else:
                # mark processed so peers re-announcing a tx we refuse
                # cannot make us re-pull it forever
                with self._msg_lock:
                    self.mempool_gossip.first_seen(tx_hash(raw))

    def _note_height(self, height: int, peer: str = "") -> None:
        """Track evidence that the network is ahead of us. The first-seen
        timestamp is PRESERVED while we stay behind — resetting it on
        every height advance would starve the sync_grace gate exactly
        when peers commit faster than the grace window (the case where
        catch-up matters most)."""
        if height > self.vnode.app.height + 1:
            with self._msg_lock:
                if self._ahead is None:
                    self._ahead = (height, peer, self.clock.monotonic())
                elif self._ahead[0] < height:
                    self._ahead = (height, peer or self._ahead[1],
                                   self._ahead[2])

    # -- helpers ---------------------------------------------------------

    def _powers(self) -> dict[bytes, int]:
        app = self.vnode.app
        ctx = Context(app.store, InfiniteGasMeter(), app.height, 0,
                      app.chain_id, app.app_version)
        return dict(app.staking.validators(ctx))

    def _refresh_valset(self) -> None:
        """Recompute rotation + vote-verification keys from state (call
        under service_lock). The set changes only at commits, so gossip
        handlers read the cached copies lock-free; they are an intake
        filter at worst one height stale — the authoritative checks
        (_proposal_acceptable, certificate verification) always read
        live state under the lock."""
        pubkeys = self.vnode.known_pubkeys()
        powers = self._powers()
        rotation = sorted(
            op for op in powers if op in pubkeys
        )
        if rotation:
            self.rotation = rotation
        self._pubkey_cache = pubkeys

    def proposer_for(self, height: int, round_: int) -> bytes:
        return self.rotation[(height + round_) % len(self.rotation)]

    def _timeout(self, phase: str, force_cold: bool = False) -> float:
        """Phase timeout with per-failed-round escalation. Cold values
        apply until this node's first committed height (jit compile paid
        once); warm values — capped BY the cold ones, so a fast test
        config is never slowed down — apply after (VERDICT r4 weak #6:
        tighten toward the reference's 10/11 s mainnet shape).
        `force_cold` keeps the cold value for one specific wait: the
        propose phase passes it for a never-seen proposer, whose FIRST
        proposal may be paying ITS cold compile however warm we are."""
        base = getattr(self.cfg, f"timeout_{phase}")
        if not force_cold and self.vnode.app.height >= 1:
            base = min(base, getattr(self.cfg, f"warm_{phase}"))
        return base + self.round * self.cfg.timeout_delta

    # NOTE: the per-block BlockSummary row is written by App.commit itself
    # (chain/app.py — one schema for every consensus mode); the reactor
    # only adds the RoundState rows below.

    def _trace_round(self, height: int, round_: int, step: str,
                     t0: float) -> None:
        """RoundState trace row (the celestia-core pkg/trace columnar
        table, SURVEY §5.1) into this validator's own trace plane —
        served at /trace/round_state by the node HTTP service."""
        try:
            self.vnode.app.traces.write(
                "round_state", height=height, round=round_, step=step,
                elapsed_ms=round((self.clock.monotonic() - t0) * 1e3, 3),
            )
        except Exception:
            # observability must never kill consensus — but not silently
            telemetry.incr("obs.trace_write_errors")

    def _wait(self, deadline: float, check):
        """Poll `check` (under _msg_lock) until non-None or deadline.
        The poll pause is the clock's INTERRUPTIBLE wait-with-wakeup —
        stop() wakes it immediately instead of losing up to a full poll
        interval per fixed sleep, and a VirtualClock resolves it against
        simulated time so a scheduler can preempt an idle node."""
        while not self._stop.is_set():
            with self._msg_lock:
                got = check()
            if got is not None:
                return got
            if self.clock.monotonic() >= deadline:
                return None
            self.clock.wait(self._stop, self.cfg.poll)
        return None

    def _prune(self, floor_height: int) -> None:
        with self._msg_lock:
            self._proposals = {
                k: v for k, v in self._proposals.items()
                if k[0] >= floor_height
            }
            self._votes = {
                k: v for k, v in self._votes.items() if k[0] >= floor_height
            }
            self._vote_pool = [
                v for v in self._vote_pool
                if v.height > floor_height - 10
            ]
            for h in [h for h in self._recent
                      if h < floor_height - self.cfg.recent_commits]:
                del self._recent[h]

    # -- proposal validity ----------------------------------------------

    def _proposal_acceptable(self, prop: c.Proposal, height: int,
                             known: dict[bytes, bytes] | None = None) -> bool:
        """Stateful checks beyond the signature (which on_proposal did):
        the block chains from OUR committed tip, the embedded last-commit
        certificate is real for height-1 (the absences every node will
        apply are derived from it, so a proposer cannot smuggle a
        thin/padded cert past the network), and every evidence item
        actually proves a double-sign — apply() slashes whoever the
        evidence names, so unverified evidence would let a byzantine
        proposer tombstone honest validators."""
        app = self.vnode.app
        if prop.height != height or prop.block.header.height != height:
            return False
        # the envelope must come from the rotation proposer for its
        # (height, round): without this, any registered validator could
        # re-wrap a legitimately certified block in its OWN envelope with
        # different last_cert/evidence via commit gossip, and nodes
        # applying different envelopes would diverge on absence/slash sets
        if prop.proposer != self.proposer_for(prop.height, prop.round):
            return False
        if prop.block.header.last_block_hash != app.last_block_hash:
            return False
        if len(prop.evidence) > len(self.rotation):
            return False  # at most one double-sign per validator
        if known is None:
            known = self.vnode.known_pubkeys()
        accused: set[bytes] = set()
        for ev in prop.evidence:
            pub = known.get(ev.vote_a.validator)
            if pub is None or not ev.verify(app.chain_id, pub):
                return False
            if not 0 < ev.height <= height:
                return False
            if ev.vote_a.validator in accused:
                return False  # duplicates would double-count nothing, but
            accused.add(ev.vote_a.validator)  # reject sloppy proposals
        if height == 1:
            return prop.last_cert is None
        lc = prop.last_cert
        if lc is None or lc.height != height - 1:
            return False
        if lc.block_hash != app.last_block_hash:
            return False
        # verify against the powers that were in force when height-1 was
        # certified (snapshotted just before we applied it): the current
        # set may already reflect a slash that block itself carried. A
        # node without the snapshot (WAL replay / state sync) falls back
        # to current powers — best effort, same as its cert verification.
        if (self._last_powers is not None
                and self._last_powers[0] == height - 1):
            powers = self._last_powers[1]
        else:
            powers = self._powers()
        return lc.verify(app.chain_id, known,
                         sum(powers.values()), powers)

    # -- the state machine ----------------------------------------------

    def _run(self) -> None:
        backoff = 0.2
        while not self._stop.is_set():
            try:
                committed = self._step_traced()
            except Exception as e:  # keep the reactor alive — but COUNTED
                # (reactor.loop_errors) and with escalating backoff, not
                # the old fixed-0.2s hot loop that could spin a wedged
                # node at 5 errors/second forever. Both this backoff and
                # the inter-height pause below are the clock's
                # interruptible wait: stop() no longer blocks behind a
                # sleeping loop (the old fixed time.sleep could hold
                # stop() for a full interval), and a VirtualClock lets
                # the sim scheduler preempt an idle node instead of
                # burning virtual-time steps.
                self.loop_errors += 1
                telemetry.incr("reactor.loop_errors")
                log.error("round error", node=self.vnode.name, err=e)
                committed = False
                self.clock.wait(self._stop, backoff)
                backoff = min(backoff * 2, 5.0)
            else:
                backoff = 0.2
            if committed:
                self.round = 0
                self.clock.wait(self._stop, self.cfg.block_interval)

    def _apply_pending_commit(self) -> bool:
        """Adopt a gossiped commit for our next height, if one is queued.
        Verification order: cert against our own trust roots, proposal
        signature + last-cert, then ProcessProposal — a certified block
        that fails local validity means >1/3 byzantine power or a bug;
        refuse and say so rather than follow the herd."""
        with self._msg_lock:
            pending, self._pending_commits = self._pending_commits, []
        applied = False
        for doc in pending:
            try:
                prop = c.proposal_from_json(doc["proposal"])
                cert = c.cert_from_json(doc["cert"])
            except (KeyError, ValueError, TypeError):
                continue
            with self.service_lock:
                app = self.vnode.app
                height = app.height + 1
                if cert.height != height:
                    continue
                if cert.block_hash != prop.block.header.hash():
                    continue
                known = self.vnode.known_pubkeys()  # ONE staking scan
                pub = known.get(prop.proposer)
                if pub is None or not prop.verify(app.chain_id, pub):
                    continue
                if not self._proposal_acceptable(prop, height, known=known):
                    continue
                if not self.vnode.verify_certificate(cert, pubkeys=known):
                    continue
                if not app.process_proposal(prop.block):
                    log.error(
                        "REFUSING certified block: local validation "
                        "failed (>1/3 byzantine or bug)",
                        node=self.vnode.name, height=height,
                    )
                    continue
                self._last_powers = (height, self._powers())
                h = self.vnode.apply(prop.block, cert,
                                     evidence=prop.evidence,
                                     absent_cert=prop.last_cert)
                self.vnode.clear_lock()
                self._refresh_valset()
                self.app_hashes[height] = h.hex()
                self._seen_proposers.add(prop.proposer)
                telemetry.incr("reactor.commits_adopted")
            # persist OUTSIDE the writer lock (as the self-commit path
            # does): a blocksync batch is one fsync per height, and the
            # lock must not serialize HTTP handlers against disk flushes
            self._remember_commit(doc, height)
            applied = True
        return applied

    def _remember_commit(self, doc: dict, height: int) -> None:
        import base64
        import hashlib

        punished = {
            bytes.fromhex(v["validator"])
            for e in doc.get("proposal", {}).get("evidence", [])
            for v in e.get("votes", [])
        }
        # committed txs left the pool in apply(); drop their want/have
        # tracking too, so gossip state follows pool membership
        committed_hashes = [
            hashlib.sha256(base64.b64decode(t)).digest()
            for t in doc.get("proposal", {}).get("block", {}).get("txs", [])
        ]
        with self._msg_lock:
            self.mempool_gossip.forget(committed_hashes)
            self._recent[height] = doc
            # clear the behind-marker only once this commit actually
            # reaches it — clearing unconditionally would abort a deep
            # blocksync after its first batch
            if self._ahead is not None and self._ahead[0] <= height + 1:
                self._ahead = None
            if punished:
                # x/evidence tombstones are idempotent, but re-proposing
                # settled evidence forever would bloat every proposal
                self._vote_pool = [
                    v for v in self._vote_pool if v.validator not in punished
                ]
        self._persist_commit_record(doc, height)
        self._maybe_snapshot(height)

    def _maybe_snapshot(self, height: int) -> None:
        """Interval state-sync snapshots (the serving half of the sync
        plane): called on BOTH commit paths after the commit record is
        durable, always OUTSIDE the writer lock — only the state capture
        inside sync.maybe_snapshot takes it, briefly."""
        from celestia_app_tpu.chain import sync as sync_mod

        sync_mod.maybe_snapshot(
            self.vnode.app, self.service_lock, self.snapshot_store,
            self.cfg.snapshot_interval, self.cfg.snapshot_keep, height,
        )

    # -- durable commit records (the block store blocksync reads) --------

    def _commits_dir(self) -> str | None:
        if self.vnode.wal_dir is None:
            return None
        import os

        d = os.path.join(os.path.dirname(self.vnode.wal_dir), "commits")
        os.makedirs(d, exist_ok=True)
        return d

    def _persist_commit_record(self, doc: dict, height: int) -> None:
        """The full gossiped commit doc (signed proposal envelope +
        certificate) hits disk per height — exactly what a laggard needs
        to replay the height through the SAME verification path live
        gossip uses (_apply_pending_commit). The reference keeps blocks +
        commits in the block store for blocksync the same way. Durable
        (fsync-before-replace, like every per-height artifact) and
        bounded: records older than cfg.commit_records_keep are pruned
        (amortized) — a laggard farther back than every peer's window
        state-syncs instead."""
        d = self._commits_dir()
        if d is None:
            return
        import os

        path = os.path.join(d, f"{height:020d}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        if height % 256 == 0:
            self._prune_commit_records(d, height)

    def _prune_commit_records(self, d: str, height: int) -> None:
        import os

        floor = height - self.cfg.commit_records_keep
        if floor <= 0:
            return
        for name in os.listdir(d):
            if not name.endswith(".json"):
                continue
            try:
                if int(name.split(".")[0]) < floor:
                    os.unlink(os.path.join(d, name))
            except (ValueError, OSError):
                continue

    def _drop_records_above(self, height: int) -> None:
        """Post-rollback hygiene at startup: never serve commit records
        for heights above our own durable state — after a rollback they
        describe a timeline this node can no longer vouch for."""
        d = self._commits_dir()
        if d is None:
            return
        import os

        for name in os.listdir(d):
            if not name.endswith(".json"):
                continue
            try:
                if int(name.split(".")[0]) > height:
                    os.unlink(os.path.join(d, name))
            except (ValueError, OSError):
                continue

    def _load_commit_record(self, height: int) -> dict | None:
        d = self._commits_dir()
        if d is None:
            return None
        import os

        path = os.path.join(d, f"{height:020d}.json")
        if not os.path.exists(path):
            return None
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _maybe_catch_up(self) -> bool:
        """If peers are persistently ahead, replay their served commit
        records with full verification (blocksync), state-syncing only
        when the gap exceeds cfg.statesync_gap or no peer can serve the
        needed records. Each replayed height goes through
        _apply_pending_commit — proposal signature, certificate against
        THIS node's then-current valset (its own staking state at
        height-1), evidence, ProcessProposal — so a tampered served
        record cannot advance the chain. Replay is pipelined: records
        arrive in blocksync_batch windows and the next window is
        prefetched while this one verifies (_blocksync_step)."""
        with self._msg_lock:
            ahead = self._ahead
        if ahead is None:
            return False
        target, peer, since = ahead
        if self.clock.monotonic() - since < self.cfg.sync_grace:
            return False
        progressed = False
        with self.service_lock:
            gap = target - (self.vnode.app.height + 1)
        if gap > self.cfg.statesync_gap and not self._statesync_tried:
            # a huge gap snapshots first — but ONCE per catch-up episode
            # (the flag resets when we catch up; keying on the moving
            # target would re-fire per batch on a live chain): a dead
            # snapshot endpoint must not tax every replay batch with its
            # timeout
            self._statesync_tried = True
            if self._state_sync(peer):
                progressed = True
        # verified windowed replay (bounded per reactor step; the _ahead
        # marker persists until fully caught up, so the next step
        # continues the sync with the prefetched window)
        if self._blocksync_step(target, peer):
            progressed = True
        with self.service_lock:
            still_behind = self.vnode.app.height + 1 < target
        if not still_behind:
            with self._msg_lock:
                if self._ahead is not None and self._ahead[0] <= target:
                    self._ahead = None  # caught up; stop re-checking
            self._statesync_tried = False  # episode over
            return progressed
        if not progressed:
            # no peer could serve an applicable record (windows pruned
            # past the gap): verified state sync is the only path left
            if self._state_sync(peer):
                progressed = True
                with self._msg_lock:
                    self._ahead = None
                self._statesync_tried = False
        return progressed

    # -- pipelined blocksync (the sync plane's replay half) ---------------

    def _blocksync_step(self, target: int, peer: str) -> bool:
        """Replay up to one blocksync_batch window of heights. The window
        is taken from the prefetch slot when the previous step armed it
        (so its fetch overlapped that step's verification), else fetched
        synchronously via GET /gossip/commits; before applying, the NEXT
        window's fetch is kicked off in the background — verification,
        not the round-trip, is the loop's critical path. Heights the
        batch path could not cover (no range-serving peer, a bad record
        mid-window) fall back to the per-height _replay_height pull,
        which tries every peer."""
        with self.service_lock:
            need = self.vnode.app.height + 1
        if need > target:
            return False
        progressed = False
        applied = 0
        docs: list[dict] = []
        if self.cfg.blocksync_pipeline:
            got = self._take_prefetch(need)
            docs = got if got is not None \
                else self._fetch_commit_batch(need, target, peer)
        if docs:
            # overlap: the next window downloads while THIS one verifies
            self._start_prefetch(need + len(docs), target, peer)
            for doc in docs:
                self.on_commit(doc)
                if not self._apply_pending_commit():
                    break
                applied += 1
                progressed = True
        if docs and applied == len(docs):
            return progressed  # full window applied; next step continues
        # per-height verified pull for the rest of this step's window
        for _ in range(self.cfg.blocksync_batch - applied):
            with self.service_lock:
                need = self.vnode.app.height + 1
            if need > target:
                break
            if not self._replay_height(need, prefer=peer):
                break
            progressed = True
        return progressed

    def _fetch_commit_batch(self, lo: int, target: int,
                            prefer: str) -> list[dict]:
        """One range fetch: consecutive records from `lo`, at most one
        blocksync_batch window, from the first peer that serves a
        non-empty consistent prefix. Pre-sync-plane peers 404 the route
        — they are skipped silently (the per-height path covers them);
        transport failures are counted + logged."""
        import urllib.error

        hi = min(target, lo + self.cfg.blocksync_batch - 1)
        for u in self._peer_order(prefer):
            if not self.net.available(u):
                continue  # breaker open: already recorded, skip
            try:
                doc = self.net.get(
                    u, f"/gossip/commits?from={lo}&to={hi}"
                )
            except urllib.error.HTTPError:
                continue  # old peer without the range route
            except (OSError, ValueError) as e:
                self._count_fetch_error(u, e)
                continue
            got = doc.get("commits") if isinstance(doc, dict) else None
            out: list[dict] = []
            for i, d in enumerate(got or []):
                try:
                    if int(d["cert"]["height"]) != lo + i:
                        break  # non-consecutive: keep the good prefix
                except (KeyError, TypeError, ValueError):
                    break
                out.append(d)
            if out:
                return out
        return []

    def _take_prefetch(self, lo: int) -> list[dict] | None:
        """Claim the prefetched window if it starts exactly at `lo`
        (waiting briefly for an in-flight fetch); a stale window — the
        chain moved differently than predicted — is discarded."""
        with self._prefetch_lock:
            th = self._prefetch_thread
        if th is not None:
            th.join(timeout=self.cfg.gossip_timeout + 1.0)
            if th.is_alive():
                return None  # still downloading: don't stall the step
        with self._prefetch_lock:
            got, self._prefetched = self._prefetched, None
            self._prefetch_thread = None
        if got is None or got[0] != lo:
            return None
        return got[1]

    def _start_prefetch(self, lo: int, target: int, prefer: str) -> None:
        """Arm the one-slot prefetch window for [lo, lo+batch) on a
        background thread — the pipelining half: this download runs
        while the caller verifies the window it just took."""
        if lo > target or not self.cfg.blocksync_pipeline:
            return
        with self._prefetch_lock:
            if self._prefetch_thread is not None:
                return  # one in-flight prefetch at a time

        def work() -> None:
            docs = self._fetch_commit_batch(lo, target, prefer)
            with self._prefetch_lock:
                self._prefetched = (lo, docs) if docs else None

        th = threading.Thread(target=work, daemon=True)
        with self._prefetch_lock:
            self._prefetch_thread = th
        th.start()

    def _replay_height(self, need: int, prefer: str) -> bool:
        """Blocksync one height: try EVERY peer's served record until one
        passes the full verification in _apply_pending_commit — a single
        peer serving a corrupt/tampered record must not defeat the sync
        while honest peers hold a good one."""
        with obs.span(
            "blocksync.pull", traces=self.vnode.app.traces,
            trace_id=obs.trace_id_for(self.vnode.app.chain_id, need),
            height=need, node=self.vnode.name,
        ) as sp:
            for u in self._peer_order(prefer):
                if not self.net.available(u):
                    continue  # breaker open: already recorded, skip
                doc = self._fetch_record_from(u, need)
                if doc is None:
                    continue
                self.on_commit(doc)
                if self._apply_pending_commit():
                    sp.set(peer=u)
                    return True
            sp.set(error="no applicable record")
            return False

    def _peer_order(self, prefer: str) -> list[str]:
        return ([prefer] if prefer else []) + [
            u for u in self.peers if u != prefer
        ]

    def _probe_peer_heights(self) -> None:
        """Probe each peer's height via the lightweight GET
        /consensus/height (one integer — pulling the full status document
        with its telemetry/mempool/net blocks every step was pure waste);
        peers predating the route fall back to /consensus/status. Feeds
        the same catch-up path inbound gossip does."""
        import urllib.error

        for u in self.peers:
            try:
                try:
                    st = self.net.get(u, "/consensus/height")
                except urllib.error.HTTPError:
                    st = self.net.get(u, "/consensus/status")
                self._note_height(int(st["height"]) + 1, u)
            except (OSError, ValueError, KeyError, TypeError):
                continue

    def _count_fetch_error(self, url: str, err: Exception) -> None:
        """Blocksync fetch failures are counted + logged (never silently
        folded into a False return): a dead record peer must be visible
        in /metrics and /consensus/status. Cached breaker rejections are
        NOT re-counted — the transport recorded the underlying failure
        once, and a catch-up episode retries peers per height."""
        from celestia_app_tpu.net.transport import BreakerOpen

        if isinstance(err, BreakerOpen):
            return
        self.blocksync_fetch_errors += 1
        telemetry.incr("reactor.blocksync_fetch_errors")
        log.warning("blocksync fetch failed", node=self.vnode.name,
                    peer=url, err=err)

    def _fetch_record_from(self, url: str, height: int) -> dict | None:
        try:
            doc = self.net.get(url, f"/gossip/commit_at?height={height}")
            return doc or None
        except (OSError, ValueError) as e:
            self._count_fetch_error(url, e)
            return None

    # -- state sync (the joining half of the sync plane) -----------------

    def _statesync_workdir(self) -> str | None:
        import os

        from celestia_app_tpu.chain import sync as sync_mod

        home = sync_mod.home_for(self.vnode)
        if home is None:
            return None
        return os.path.join(home, sync_mod.RESTORE_DIRNAME)

    def _count_statesync_error(self, err: Exception) -> None:
        self.statesync_errors += 1
        telemetry.incr("reactor.statesync_errors")
        log.warning("state sync failed", node=self.vnode.name, err=err)

    def _state_sync(self, prefer: str) -> bool:
        """Chunked, parallel, resumable state sync across ALL healthy
        peers (chain/sync.StateSyncClient): discover the newest served
        manifest, pull chunks concurrently with per-chunk verification
        and durable resume, then adopt under the writer lock through the
        unchanged app-hash-anchored state_sync_bootstrap. Peers without
        the /sync/* routes fall back to the legacy one-shot pull."""
        import tempfile

        from celestia_app_tpu.chain import sync as sync_mod

        workdir = self._statesync_workdir()
        ephemeral = workdir is None
        if ephemeral:  # in-memory node: no resume across restarts anyway
            workdir = tempfile.mkdtemp(prefix="statesync-")
        with self.service_lock:
            floor = self.vnode.app.height
        client = sync_mod.StateSyncClient(
            self._peer_order(prefer), workdir, net=self.net,
            workers=self.cfg.statesync_workers, min_height=floor,
            name=self.vnode.name,
            da_scheme=sync_mod.scheme_of(self.vnode),
        )
        try:
            manifest, chunks = client.fetch()
        except sync_mod.StateSyncUnavailable as e:
            # nothing chunked to join from: try the legacy one-shot
            # endpoint peer-by-peer (pre-sync-plane peers serve it)
            log.info("chunked state sync unavailable",
                     node=self.vnode.name, err=e)
            for u in self._peer_order(prefer):
                if self._state_sync_from(u):
                    return True
            return False
        except (OSError, ValueError) as e:
            self._count_statesync_error(e)
            return False
        finally:
            if ephemeral:
                import shutil as shutil_mod

                shutil_mod.rmtree(workdir, ignore_errors=True)
        try:
            with self.service_lock:
                # re-check under the lock: commits may have advanced the
                # chain past the manifest while chunks were downloading —
                # adoption must never rewind the node
                if int(manifest["height"]) <= self.vnode.app.height:
                    raise ValueError(
                        f"snapshot at {manifest['height']} no longer "
                        f"ahead of height {self.vnode.app.height}"
                    )
                c.state_sync_bootstrap(self.vnode, manifest, chunks)
                self._refresh_valset()  # synced state may carry new validators
        except (ValueError, KeyError) as e:
            # adoption failed (e.g. the manifest's app_hash lied about
            # the reassembled store): the restore material is worthless —
            # REMOVE it, or discover()'s in-progress preference would
            # latch onto the same poisoned manifest on every retry
            client.cleanup()
            self._count_statesync_error(e)
            return False
        client.cleanup()
        telemetry.incr("reactor.statesync_joins")
        log.info("state sync adopted snapshot", node=self.vnode.name,
                 height=manifest["height"],
                 fetched=client.stats["fetched"],
                 reused=client.stats["reused"])
        return True

    def _state_sync_from(self, url: str) -> bool:
        """Legacy one-shot pull (GET /consensus/snapshot, the pre-sync-
        plane protocol) — kept as the fallback for peers that serve no
        chunked snapshots; failures are counted, not swallowed. Our
        height rides the ?min_height= query so a peer whose newest disk
        snapshot is behind us serves a capture instead (a pre-query
        server 404s the parameterized path; retry bare)."""
        import base64
        import urllib.error

        try:
            floor = self.vnode.app.height
            try:
                doc = self.net.get(
                    url, f"/consensus/snapshot?min_height={floor}",
                    timeout=30,
                )
            except urllib.error.HTTPError:
                doc = self.net.get(url, "/consensus/snapshot",
                                   timeout=30)
            chunks = [base64.b64decode(ch) for ch in doc["chunks"]]
            with self.service_lock:
                # the legacy endpoint now serves DISK snapshots, which
                # can be OLDER than this node's tip (the capture-on-
                # request original was always the peer's current height):
                # adopting one would REWIND the chain. Refuse stale.
                if int(doc["manifest"]["height"]) <= self.vnode.app.height:
                    raise ValueError(
                        f"peer snapshot at {doc['manifest']['height']} "
                        f"is not ahead of height {self.vnode.app.height}"
                    )
                c.state_sync_bootstrap(self.vnode, doc["manifest"], chunks)
                self._refresh_valset()  # the synced state may carry new validators
            return True
        except (OSError, ValueError, KeyError, TypeError) as e:
            self._count_statesync_error(e)
            return False

    def _step_traced(self) -> bool:
        """One reactor step under a per-round span: the root every
        gossip.send / wal.append / apply child of this round hangs off
        (trace id = the height's deterministic id)."""
        height = self.vnode.app.height + 1  # label-only read; no lock
        with obs.span(
            "reactor.round", traces=self.vnode.app.traces,
            trace_id=obs.trace_id_for(self.vnode.app.chain_id, height),
            height=height, round=self.round, node=self.vnode.name,
        ) as sp:
            committed = self._step_height()
            sp.set(committed=committed)
            return committed

    def _step_height(self) -> bool:
        """One (height, round) attempt; True iff a block was committed."""
        self._admit_pending_txs()
        if self._apply_pending_commit():
            return True
        if self._maybe_catch_up():
            return True
        with self.service_lock:
            height = self.vnode.app.height + 1
            my_last_cert = self.vnode.certificates.get(height - 1)
        self.height_view = height
        r = self.round
        _t_round = self.clock.monotonic()

        # ---- propose ----
        self.step = "propose"
        i_am_proposer = self.proposer_for(height, r) == self.vnode.address
        # a proposer that lacks the height-1 cert (it state-synced into
        # this height) cannot author valid commit info; it stays silent
        # and the round rotates past it
        if i_am_proposer and self.cfg.produce_batch > 1 \
                and (height == 1 or my_last_cert is not None) \
                and height >= self._prewarm_after:
            # mesh-plane produce prefetch: batch-extend the next
            # produce_batch speculative squares OUTSIDE the service lock
            # (the dispatch — first-call jit compile included — must
            # never stall the round) so propose() below hits a warm
            # device-resident entry. One plan per BATCH WINDOW, not per
            # round (planning B squares every proposal would multiply
            # the greedy layout work by B). Failures are counted, never
            # fatal: the propose path extends per block exactly as
            # without the knob.
            self._prewarm_after = height + self.cfg.produce_batch
            try:
                self.vnode.prewarm_proposals(self.cfg.produce_batch)
            except Exception as e:
                telemetry.incr("reactor.prewarm_errors")
                log.warning("produce prewarm failed", height=height,
                            err=e)
        if i_am_proposer and (height == 1 or my_last_cert is not None):
            with self._msg_lock:
                pool = [list(self._vote_pool)]
            with self.service_lock:
                evidence = tuple(c.detect_equivocation(
                    self.vnode.app.chain_id, pool,
                    self.vnode.known_pubkeys(),
                ))
                block = self.vnode.propose(t=self.clock.now())
            digest = c.Proposal.commit_info_digest(my_last_cert, evidence)
            sig = self.vnode.priv.sign(c.Proposal.sign_bytes(
                self.vnode.app.chain_id, height, r, block.header.hash(),
                digest,
            ))
            prop = c.Proposal(height, r, block, self.vnode.address, sig,
                              my_last_cert, evidence)
            with self._msg_lock:
                self._proposals.setdefault((height, r), prop)
            self._gossip("/gossip/proposal", c.proposal_to_json(prop))

        # cold propose window for (a) a proposer we have never seen a
        # proposal from, or (b) one whose last expected proposal timed
        # out (one retry: a RESTARTED validator repays its jit compile
        # with peers still remembering it as seen) — either may be
        # compiling; a second consecutive timeout reads as dead and
        # rotation returns to warm windows
        expected = self.proposer_for(height, r)
        force_cold = (expected not in self._seen_proposers
                      or expected in self._cold_retry)
        deadline = self.clock.monotonic() + self._timeout(
            "propose", force_cold=force_cold
        )
        prop = self._wait(
            deadline, lambda: self._proposals.get((height, r))
        )
        if prop is None and expected != self.vnode.address:
            if expected in self._cold_retry:
                self._cold_retry.discard(expected)  # dead: warm windows
            elif expected in self._seen_proposers:
                self._cold_retry.add(expected)  # maybe restarted: 1 retry
        elif prop is not None:
            self._cold_retry.discard(expected)
        self._trace_round(height, r, "propose", _t_round)

        # ---- prevote ----
        self.step = "prevote"
        accept = False
        if prop is not None:
            with self.service_lock:
                accept = self._proposal_acceptable(prop, height)
        if accept:
            with self.service_lock:
                pv = self.vnode.prevote_on(prop.block, r)  # ProcessProposal
        else:
            with self.service_lock:
                pv = self.vnode._signed(height, None, "prevote", r)
        self.on_vote({"round": r, "vote": c.vote_to_json(pv)})
        self._gossip("/gossip/vote",
                     {"round": r, "vote": c.vote_to_json(pv)})

        with self.service_lock:
            powers = self._powers()
        total = sum(powers.values())

        def polka_check():
            pool = self._votes.get((height, r, "prevote"), {})
            by_hash: dict[bytes, int] = {}
            nil_power = 0
            for v in pool.values():
                p = powers.get(v.validator, 0)
                if v.block_hash is None:
                    nil_power += p
                else:
                    by_hash[v.block_hash] = by_hash.get(v.block_hash, 0) + p
            for bh, power in by_hash.items():
                if power * 3 > total * 2:
                    return bh
            if nil_power * 3 > total * 2:
                return b"nil"  # sentinel: round is dead, move on
            return None

        deadline = self.clock.monotonic() + self._timeout("prevote")
        polka = self._wait(deadline, polka_check)
        polka_hash = polka if isinstance(polka, bytes) and polka != b"nil" \
            else None
        self._trace_round(height, r, "prevote", _t_round)

        # ---- precommit ----
        self.step = "precommit"
        with self.service_lock:
            # Tendermint lock discipline with round-scoped votes
            # (ValidatorNode.lock_permits — one definition shared with
            # the orchestrated server): precommit the polka block when it
            # matches our lock, we are unlocked, or the polka is at a
            # LATER round than our lock (unlock-on-higher-polka — the
            # cross-round precommit is legal and signed with this round,
            # so it can never read as a double-sign). `accept` gates
            # validity: the signed envelope carries the commit info
            # apply() will consume, so a polka on a block whose envelope
            # WE could not validate gets nil.
            lock_ok = (polka_hash is not None
                       and self.vnode.lock_permits(polka_hash, r))
            if (polka_hash is not None and prop is not None
                    and prop.block.header.hash() == polka_hash
                    and accept and lock_ok):
                self.vnode.on_polka(prop.block, r)
                pc = self.vnode.precommit_on(prop.block, r)
            else:
                pc = self.vnode.precommit_on(None, r)
        self.on_vote({"round": r, "vote": c.vote_to_json(pc)})
        self._gossip("/gossip/vote",
                     {"round": r, "vote": c.vote_to_json(pc)})

        def quorum_check():
            pool = self._votes.get((height, r, "precommit"), {})
            votes = [
                v for v in pool.values() if v.block_hash == polka_hash
            ]
            power = sum(powers.get(v.validator, 0) for v in votes)
            if power * 3 > total * 2:
                return tuple(votes)
            return None

        if polka_hash is None:
            # a nil polka (or none at all) already proved this round dead:
            # no certificate we could act on can form, so don't dead-wait
            # the precommit window — any commit others reached arrives by
            # gossip and is adopted at the top of the next attempt
            cert_votes = None
        else:
            deadline = self.clock.monotonic() + self._timeout("precommit")
            cert_votes = self._wait(deadline, quorum_check)

        # a certificate is only actionable if WE hold the matching
        # proposal: an equivocating proposer could have sent us block A
        # while the majority polka'd block B — applying A under a cert
        # for B would fork this node's state. Without the block, let the
        # commit arrive by gossip instead.
        if (cert_votes is not None
                and (prop is None
                     or prop.block.header.hash() != polka_hash)):
            cert_votes = None

        if cert_votes is None:
            # commit may still arrive by gossip (others saw the quorum)
            if self._apply_pending_commit():
                return True
            # pull-based peer probe: a failed round can mean the network
            # moved on without us (our inbound gossip is not arriving —
            # e.g. we rejoined on a new address). Ask peers where they are
            # so _maybe_catch_up can pull the gap.
            self._probe_peer_heights()
            self.round = r + 1
            self.step = "round-failed"
            self._trace_round(height, r, "round-failed", _t_round)
            telemetry.incr("reactor.round_failures")
            self._prune(self.vnode.app.height + 1)
            return False

        # ---- commit ----
        self.step = "commit"
        cert = c.CommitCertificate(height, polka_hash, cert_votes, r)
        doc = {"proposal": c.proposal_to_json(prop),
               "cert": c.cert_to_json(cert)}
        with self.service_lock:
            if self.vnode.app.height >= height:
                return True  # a gossiped commit beat us to it
            self._last_powers = (height, self._powers())
            ah = self.vnode.apply(prop.block, cert, evidence=prop.evidence,
                                  absent_cert=prop.last_cert)
            self.vnode.clear_lock()
            self._refresh_valset()
            self.app_hashes[height] = ah.hex()
        self._trace_round(height, r, "commit", _t_round)
        telemetry.incr("reactor.commits")
        self._remember_commit(doc, height)
        self._gossip("/gossip/commit", doc)
        self._prune(height + 1)
        return True
