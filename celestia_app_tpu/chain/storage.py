"""Disk-backed chain database: committed state + block store.

Reference parity: the durable side of the reference node — cosmos-sdk's
commit multistore persisted via IAVL/LevelDB plus celestia-core's block
store (app/app.go:427-435 LoadLatestVersion, default_overrides.go pruning
windows).

Commit persistence is DELTA-BASED (the IAVL versioned-tree analog): most
commits write only the keys touched since the previous commit (writes +
deletions); a full snapshot is written every FULL_INTERVAL commits (and at
the first durable commit), so loading height ``h`` = nearest full snapshot
≤ h plus the delta chain up to h. Commit IO therefore scales with touched
keys, not total state size. Every block (header + txs) is kept so proofs
for past heights can be re-derived (pkg/proof/querier.go re-extends the
square from block data).

Layout under ``data_dir``:

    state/<height:020d>.json.gz   full store + identity at height
    delta/<height:020d>.json.gz   changed/deleted keys + identity at height
    blocks/<height:020d>.json.gz  block: header fields + base64 txs
    LATEST                        latest committed height (atomic rename)

Atomicity: temp-file + os.replace per artifact, LATEST written last — a
crash mid-commit leaves the previous height intact and the node resumes
from it (state-sync-style restore is just copying these files).
"""

from __future__ import annotations

import gzip
import json
import os

from celestia_app_tpu.chain.block import Block

PRUNE_KEEP = 100  # same rollback window the in-memory history kept
FULL_INTERVAL = 64  # full snapshot cadence (state-sync interval analog)


def _atomic_write(path: str, data: bytes) -> None:
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    # fsync the directory so the rename itself is durable: without this a
    # power loss can persist a later artifact (LATEST) while losing an
    # earlier rename, breaking the write-ordering guarantee
    dfd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)


class ChainDB:
    def __init__(self, data_dir: str):
        self.dir = data_dir
        os.makedirs(os.path.join(data_dir, "state"), exist_ok=True)
        os.makedirs(os.path.join(data_dir, "delta"), exist_ok=True)
        os.makedirs(os.path.join(data_dir, "blocks"), exist_ok=True)

    # -- commits ---------------------------------------------------------

    def _state_path(self, height: int) -> str:
        return os.path.join(self.dir, "state", f"{height:020d}.json.gz")

    def _delta_path(self, height: int) -> str:
        return os.path.join(self.dir, "delta", f"{height:020d}.json.gz")

    def _heights_in(self, sub: str) -> list[int]:
        out = []
        for name in os.listdir(os.path.join(self.dir, sub)):
            if name.endswith(".json.gz"):
                try:
                    out.append(int(name.split(".")[0]))
                except ValueError:
                    pass
        return sorted(out)

    def save_commit(
        self,
        height: int,
        store,
        meta: dict,
        *,
        force_full: bool = False,
    ) -> None:
        """Persist one commit. ``store`` is the live KVStore: its change log
        (drain_changes) becomes the delta; a full snapshot is written at the
        first durable commit, every FULL_INTERVAL commits, or on demand."""
        changes = store.drain_changes()
        prior = self.latest_height()
        if prior is not None and height <= prior:
            # timeline rewrite (rollback then re-commit): stale state/delta/
            # block files from the abandoned fork must not survive above this
            # height, or a later load would chain the new fork's deltas into
            # the old fork's (reconstructing a state that existed on neither)
            self.delete_above(height)
            force_full = True
        fulls = self._heights_in("state")
        write_full = (
            force_full
            or not fulls
            or height % FULL_INTERVAL == 0
            or height < max(fulls)  # fork guard belt-and-suspenders
        )
        if write_full:
            doc = {
                "height": height,
                "meta": meta,
                "store": {k.hex(): v.hex() for k, v in store.snapshot().items()},
            }
            blob = gzip.compress(
                json.dumps(doc, sort_keys=True, separators=(",", ":")).encode()
            )
            _atomic_write(self._state_path(height), blob)
        else:
            doc = {
                "height": height,
                "meta": meta,
                "changes": {
                    k.hex(): (None if v is None else v.hex())
                    for k, v in changes.items()
                },
            }
            blob = gzip.compress(
                json.dumps(doc, sort_keys=True, separators=(",", ":")).encode()
            )
            _atomic_write(self._delta_path(height), blob)
        _atomic_write(os.path.join(self.dir, "LATEST"), str(height).encode())
        self._prune(height)

    def latest_height(self) -> int | None:
        try:
            with open(os.path.join(self.dir, "LATEST"), "rb") as f:
                return int(f.read().decode())
        except FileNotFoundError:
            return None

    def _read_doc(self, path: str) -> dict:
        with gzip.open(path, "rb") as f:
            return json.loads(f.read())

    def load_commit(self, height: int | None = None):
        """-> (height, store_data, meta); latest when height is None.

        Reconstructs: nearest full snapshot ≤ height, then the delta chain
        (full, height]. Raises FileNotFoundError when the chain is broken
        (pruned past, missing delta)."""
        if height is None:
            height = self.latest_height()
            if height is None:
                raise FileNotFoundError("no committed state on disk")
        fulls = [h for h in self._heights_in("state") if h <= height]
        if not fulls:
            raise FileNotFoundError(f"no snapshot at or below height {height}")
        base = max(fulls)
        doc = self._read_doc(self._state_path(base))
        store = {
            bytes.fromhex(k): bytes.fromhex(v) for k, v in doc["store"].items()
        }
        meta = doc["meta"]
        deltas = [h for h in self._heights_in("delta") if base < h <= height]
        expected = list(range(base + 1, height + 1))
        if deltas != expected:
            raise FileNotFoundError(
                f"broken delta chain for height {height}: have {deltas[:5]}..., "
                f"need {base + 1}..{height}"
            )
        for h in deltas:
            d = self._read_doc(self._delta_path(h))
            for k_hex, v_hex in d["changes"].items():
                k = bytes.fromhex(k_hex)
                if v_hex is None:
                    store.pop(k, None)
                else:
                    store[k] = bytes.fromhex(v_hex)
            meta = d["meta"]
        return height, store, meta

    def delete_above(self, height: int) -> None:
        """Remove commits and blocks above `height` (rollback discards the
        abandoned fork, like the reference's rollback deleting versions)."""
        for sub in ("state", "delta", "blocks"):
            d = os.path.join(self.dir, sub)
            for name in os.listdir(d):
                if not name.endswith(".json.gz"):
                    continue
                try:
                    h = int(name.split(".")[0])
                except ValueError:
                    continue
                if h > height:
                    os.unlink(os.path.join(d, name))

    def _prune(self, latest: int) -> None:
        """Prune outside the rollback window, keeping every height in
        [latest-PRUNE_KEEP, latest] reconstructible: the newest full
        snapshot at or below the window floor anchors the delta chain."""
        floor = latest - PRUNE_KEEP
        fulls = self._heights_in("state")
        anchors = [h for h in fulls if h <= floor]
        anchor = max(anchors) if anchors else None
        for h in fulls:
            if h != anchor and h <= floor:
                os.unlink(self._state_path(h))
        if anchor is not None:
            for h in self._heights_in("delta"):
                if h <= anchor:
                    os.unlink(self._delta_path(h))

    # -- blocks ----------------------------------------------------------

    def _block_path(self, height: int) -> str:
        return os.path.join(self.dir, "blocks", f"{height:020d}.json.gz")

    def save_block(self, block: Block) -> None:
        # THE header codec (chain/consensus.py) — the block store, the WAL,
        # and the socket wire must agree on every field, or a stored block
        # re-hashes differently than the chain committed
        from celestia_app_tpu.chain.consensus import block_to_json

        doc = block_to_json(block)
        blob = gzip.compress(
            json.dumps(doc, sort_keys=True, separators=(",", ":")).encode()
        )
        _atomic_write(self._block_path(block.header.height), blob)

    def load_block(self, height: int) -> Block:
        from celestia_app_tpu.chain.consensus import block_from_json

        with gzip.open(self._block_path(height), "rb") as f:
            doc = json.loads(f.read())
        return block_from_json(doc)

    def block_heights(self) -> list[int]:
        out = []
        for name in os.listdir(os.path.join(self.dir, "blocks")):
            if name.endswith(".json.gz"):
                try:
                    out.append(int(name.split(".")[0]))
                except ValueError:
                    pass
        return sorted(out)
