"""Disk-backed chain database: committed state + block store.

Reference parity: the durable side of the reference node — cosmos-sdk's
commit multistore persisted via IAVL/LevelDB plus celestia-core's block
store (app/app.go:427-435 LoadLatestVersion, default_overrides.go pruning
windows).

Commit persistence is DELTA-BASED (the IAVL versioned-tree analog): most
commits write only the keys touched since the previous commit (writes +
deletions); a full snapshot is written every FULL_INTERVAL commits (and at
the first durable commit), so loading height ``h`` = nearest full snapshot
≤ h plus the delta chain up to h. Commit IO therefore scales with touched
keys, not total state size. Every block (header + txs) is kept so proofs
for past heights can be re-derived (pkg/proof/querier.go re-extends the
square from block data).

Two storage engines sit under ONE ChainDB (the commit/load/prune logic is
engine-independent; engines only move bytes):

- **native** (default where the toolchain exists): native/chaindb.cc via
  ctypes — a segmented append-only record store with CRC framing, fsync
  batching, torn-tail recovery, rollback/prune tombstones, dead-segment GC
  and writer flocks. This is the tm-db analog and the engine a real
  validator runs on.
- **files**: one gzip-JSON artifact per height under state/ delta/ blocks/
  plus a LATEST pointer, each atomically renamed and fsynced. Zero native
  dependencies; also the round-3 on-disk layout, which it still reads.

Selection: ``CELESTIA_CHAINDB`` env = ``native`` / ``files`` / ``auto``
(default). Auto keeps whatever engine a home already uses (seg-*.log ⇒
native; LATEST/state ⇒ files) and picks native for fresh homes when the
.so is buildable.

Crash-safety contract (both engines): the commit artifact is durable
BEFORE the latest-pointer that references it — a crash between the two
resumes from the previous height; a torn tail is dropped on reopen.
"""

from __future__ import annotations

import gzip
import json
import os

from celestia_app_tpu import faults
from celestia_app_tpu.chain.block import Block

PRUNE_KEEP = 100  # same rollback window the in-memory history kept
FULL_INTERVAL = 64  # full snapshot cadence (state-sync interval analog)

# record streams (shared by both engines; the file engine maps them to dirs)
STATE, DELTA, BLOCK, LATEST = 0, 1, 2, 3


def _atomic_write(path: str, data: bytes) -> None:
    # disk fault point: armed "error" surfaces as the OSError any real
    # full-disk/EIO failure would; "crash" kills the process here, before
    # anything of this artifact is durable; "delay" models a slow disk
    if faults.fire("storage.atomic_write", path=path) == "error":
        raise OSError(f"injected fault: storage.atomic_write {path}")
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    # fsync the directory so the rename itself is durable: without this a
    # power loss can persist a later artifact (LATEST) while losing an
    # earlier rename, breaking the write-ordering guarantee
    dfd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)


class FileBackend:
    """gzip-JSON-per-height files; every op is individually durable."""

    DIRS = {STATE: "state", DELTA: "delta", BLOCK: "blocks"}

    def __init__(self, data_dir: str):
        self.dir = data_dir
        for sub in self.DIRS.values():
            os.makedirs(os.path.join(data_dir, sub), exist_ok=True)

    def _path(self, stream: int, height: int) -> str:
        return os.path.join(
            self.dir, self.DIRS[stream], f"{height:020d}.json.gz"
        )

    def put(self, stream: int, height: int, blob: bytes) -> None:
        _atomic_write(self._path(stream, height), blob)

    def get(self, stream: int, height: int) -> bytes | None:
        try:
            with open(self._path(stream, height), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def heights(self, stream: int) -> list[int]:
        out = []
        for name in os.listdir(os.path.join(self.dir, self.DIRS[stream])):
            if name.endswith(".json.gz"):
                try:
                    out.append(int(name.split(".")[0]))
                except ValueError:
                    pass
        return sorted(out)

    def latest(self) -> int | None:
        try:
            with open(os.path.join(self.dir, "LATEST"), "rb") as f:
                return int(f.read().decode())
        except FileNotFoundError:
            return None

    def set_latest(self, height: int) -> None:
        _atomic_write(os.path.join(self.dir, "LATEST"), str(height).encode())

    def delete_at(self, stream: int, height: int) -> None:
        try:
            os.unlink(self._path(stream, height))
        except FileNotFoundError:
            pass

    def delete_above(self, height: int) -> None:
        for stream in self.DIRS:
            for h in self.heights(stream):
                if h > height:
                    self.delete_at(stream, h)
        # keep the pointer consistent with the surviving artifacts (the
        # native engine's tomb_above does this implicitly): a crash after
        # rollback but before the next commit must resume at `height`, not
        # point at deltas that no longer exist
        latest = self.latest()
        if latest is not None and latest > height:
            self.set_latest(height)

    def sync(self) -> None:  # every put/set_latest already fsynced
        pass

    def close(self) -> None:
        pass


class NativeBackend:
    """native/chaindb.cc via ctypes (utils/native_chaindb.py)."""

    def __init__(self, data_dir: str, *, read_only: bool = False):
        from celestia_app_tpu.utils import native_chaindb

        self.dir = data_dir
        os.makedirs(data_dir, exist_ok=True)
        self.log = native_chaindb.NativeLog(data_dir, read_only=read_only)

    def put(self, stream: int, height: int, blob: bytes) -> None:
        self.log.put(stream, height, blob)

    def get(self, stream: int, height: int) -> bytes | None:
        return self.log.get(stream, height)

    def heights(self, stream: int) -> list[int]:
        return self.log.heights(stream)

    def latest(self) -> int | None:
        # LATEST is a stream of empty records; rollback tombstones shrink it
        # in the same append-only log as everything else
        return self.log.latest(LATEST)

    def set_latest(self, height: int) -> None:
        # barrier FIRST: the artifact this pointer references must be
        # durable before the pointer (the file engine gets this ordering
        # from its per-op fsyncs). No trailing sync: losing the pointer
        # itself just resumes from the previous height, which the
        # crash-safety contract permits — save_commit's final sync() is
        # the one that makes the whole commit durable.
        prev = self.log.latest(LATEST)
        self.log.sync()
        self.log.put(LATEST, height, b"")
        # retire the superseded pointer record or the LATEST stream (and
        # open-time replay) grows one dead entry per commit forever; tomb
        # AFTER the new put so a crash between the two cannot regress the
        # pointer below `prev`
        if prev is not None and prev != height:
            self.log.tomb_at(LATEST, prev)

    def delete_at(self, stream: int, height: int) -> None:
        self.log.tomb_at(stream, height)

    def delete_above(self, height: int) -> None:
        self.log.tomb_above(height)
        self.log.sync()

    def sync(self) -> None:
        self.log.sync()

    def close(self) -> None:
        self.log.close()


def _detect_backend(data_dir: str, *, read_only: bool = False):
    """CELESTIA_CHAINDB = native / files / auto (default: keep what the home
    already uses; native for fresh homes when the toolchain exists)."""
    choice = os.environ.get("CELESTIA_CHAINDB", "auto")
    if choice == "files":
        return FileBackend(data_dir)
    if choice == "native":
        return NativeBackend(data_dir, read_only=read_only)
    has_native = bool(
        [n for n in _listdir(data_dir) if n.startswith("seg-")]
    )
    has_files = os.path.exists(os.path.join(data_dir, "LATEST")) or (
        os.path.isdir(os.path.join(data_dir, "state"))
    )
    if has_native and not has_files:
        return NativeBackend(data_dir, read_only=read_only)
    if has_files:
        return FileBackend(data_dir)
    from celestia_app_tpu.utils import native_chaindb

    if native_chaindb.available():
        return NativeBackend(data_dir, read_only=read_only)
    return FileBackend(data_dir)


def _listdir(path: str) -> list[str]:
    try:
        return os.listdir(path)
    except FileNotFoundError:
        return []


def wipe_commits(data_dir: str) -> None:
    """Destroy ALL committed state + blocks (both engines' artifacts),
    keeping everything else in the home (WAL, keys, config). This is the
    disk-level wipe the crash-recovery tests and ops runbooks mean by
    "lost its data dir but kept the WAL" — after it, a node rebuilds from
    genesis + WAL replay (or state-sync)."""
    import shutil

    for sub in FileBackend.DIRS.values():
        shutil.rmtree(os.path.join(data_dir, sub), ignore_errors=True)
    # the reactor's durable commit-record store describes the same wiped
    # timeline — stale records must not be served to laggards
    shutil.rmtree(os.path.join(data_dir, "commits"), ignore_errors=True)
    for name in _listdir(data_dir):
        if name == "LATEST" or name == "LOCK" or name.startswith("seg-"):
            try:
                os.unlink(os.path.join(data_dir, name))
            except FileNotFoundError:
                pass


class ChainDB:
    def __init__(self, data_dir: str, *, backend=None, read_only: bool = False):
        self.dir = data_dir
        os.makedirs(data_dir, exist_ok=True)
        self.backend = backend or _detect_backend(data_dir, read_only=read_only)

    def close(self) -> None:
        self.backend.close()

    # -- commits ---------------------------------------------------------

    @staticmethod
    def _encode(doc: dict) -> bytes:
        return gzip.compress(
            json.dumps(doc, sort_keys=True, separators=(",", ":")).encode()
        )

    @staticmethod
    def _decode(blob: bytes) -> dict:
        return json.loads(gzip.decompress(blob))

    def save_commit(
        self,
        height: int,
        store,
        meta: dict,
        *,
        force_full: bool = False,
    ) -> None:
        """Persist one commit. ``store`` is the live KVStore: its change log
        (drain_changes) becomes the delta; a full snapshot is written at the
        first durable commit, every FULL_INTERVAL commits, or on demand."""
        from celestia_app_tpu import obs
        from celestia_app_tpu.utils import telemetry

        t0 = telemetry.start_timer()
        with obs.span("storage.save_commit", height=height):
            self._save_commit_inner(height, store, meta,
                                    force_full=force_full)
        telemetry.measure_since("storage.save_commit", t0)

    def _save_commit_inner(
        self,
        height: int,
        store,
        meta: dict,
        *,
        force_full: bool = False,
    ) -> None:
        changes = store.drain_changes()
        prior = self.latest_height()
        if prior is not None and height <= prior:
            # timeline rewrite (rollback then re-commit): stale state/delta/
            # block files from the abandoned fork must not survive above this
            # height, or a later load would chain the new fork's deltas into
            # the old fork's (reconstructing a state that existed on neither)
            self.delete_above(height)
            force_full = True
        fulls = self.backend.heights(STATE)
        write_full = (
            force_full
            or not fulls
            or height % FULL_INTERVAL == 0
            or height < max(fulls)  # fork guard belt-and-suspenders
        )
        if write_full:
            doc = {
                "height": height,
                "meta": meta,
                "store": {k.hex(): v.hex() for k, v in store.snapshot().items()},
            }
            self.backend.put(STATE, height, self._encode(doc))
        else:
            doc = {
                "height": height,
                "meta": meta,
                "changes": {
                    k.hex(): (None if v is None else v.hex())
                    for k, v in changes.items()
                },
            }
            self.backend.put(DELTA, height, self._encode(doc))
        # crash point 3 of the commit matrix: the commit artifact (and the
        # block, saved just before) are durable but LATEST still points at
        # height-1 — the crash-safety contract's "between the two" case.
        # Recovery: load() resumes at height-1, WAL replay re-commits.
        if faults.fire("consensus.post_apply_pre_latest",
                       height=height) == "error":
            raise OSError("injected fault: consensus.post_apply_pre_latest")
        self.backend.set_latest(height)
        self._prune(height)
        self.backend.sync()

    def latest_height(self) -> int | None:
        return self.backend.latest()

    def load_commit(self, height: int | None = None):
        """-> (height, store_data, meta); latest when height is None.

        Reconstructs: nearest full snapshot ≤ height, then the delta chain
        (full, height]. Raises FileNotFoundError when the chain is broken
        (pruned past, missing delta)."""
        if height is None:
            height = self.latest_height()
            if height is None:
                raise FileNotFoundError("no committed state on disk")
        fulls = [h for h in self.backend.heights(STATE) if h <= height]
        if not fulls:
            raise FileNotFoundError(f"no snapshot at or below height {height}")
        base = max(fulls)
        doc = self._decode(self.backend.get(STATE, base))
        store = {
            bytes.fromhex(k): bytes.fromhex(v) for k, v in doc["store"].items()
        }
        meta = doc["meta"]
        deltas = [h for h in self.backend.heights(DELTA) if base < h <= height]
        expected = list(range(base + 1, height + 1))
        if deltas != expected:
            raise FileNotFoundError(
                f"broken delta chain for height {height}: have {deltas[:5]}..., "
                f"need {base + 1}..{height}"
            )
        for h in deltas:
            d = self._decode(self.backend.get(DELTA, h))
            for k_hex, v_hex in d["changes"].items():
                k = bytes.fromhex(k_hex)
                if v_hex is None:
                    store.pop(k, None)
                else:
                    store[k] = bytes.fromhex(v_hex)
            meta = d["meta"]
        return height, store, meta

    def delete_above(self, height: int) -> None:
        """Remove commits and blocks above `height` (rollback discards the
        abandoned fork, like the reference's rollback deleting versions)."""
        self.backend.delete_above(height)

    def _prune(self, latest: int) -> None:
        """Prune outside the rollback window, keeping every height in
        [latest-PRUNE_KEEP, latest] reconstructible: the newest full
        snapshot at or below the window floor anchors the delta chain."""
        floor = latest - PRUNE_KEEP
        fulls = self.backend.heights(STATE)
        anchors = [h for h in fulls if h <= floor]
        anchor = max(anchors) if anchors else None
        for h in fulls:
            if h != anchor and h <= floor:
                self.backend.delete_at(STATE, h)
        if anchor is not None:
            for h in self.backend.heights(DELTA):
                if h <= anchor:
                    self.backend.delete_at(DELTA, h)

    # -- blocks ----------------------------------------------------------

    def save_block(self, block: Block) -> None:
        # THE header codec (chain/consensus.py) — the block store, the WAL,
        # and the socket wire must agree on every field, or a stored block
        # re-hashes differently than the chain committed
        from celestia_app_tpu.chain.consensus import block_to_json

        doc = block_to_json(block)
        self.backend.put(BLOCK, block.header.height, self._encode(doc))
        self.backend.sync()

    def load_block(self, height: int) -> Block:
        from celestia_app_tpu.chain.consensus import block_from_json

        blob = self.backend.get(BLOCK, height)
        if blob is None:
            raise FileNotFoundError(f"no block at height {height}")
        return block_from_json(self._decode(blob))

    def block_heights(self) -> list[int]:
        return self.backend.heights(BLOCK)
