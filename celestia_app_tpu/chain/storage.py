"""Disk-backed chain database: committed state + block store.

Reference parity: the durable side of the reference node — cosmos-sdk's
commit multistore persisted via IAVL/LevelDB plus celestia-core's block
store (app/app.go:427-435 LoadLatestVersion, default_overrides.go pruning
windows). The storage model here matches the framework's flat merkleized
KV: every commit atomically persists the full store (gzip'd canonical JSON,
hex keys/values) plus the chain identity, pruned to a rollback window, and
every block (header + txs) is kept so proofs for past heights can be
re-derived (pkg/proof/querier.go re-extends the square from block data).

Layout under ``data_dir``:

    state/<height:020d>.json.gz   committed store + identity at height
    blocks/<height:020d>.json.gz  block: header fields + base64 txs
    LATEST                        latest committed height (atomic rename)

Atomicity: temp-file + os.replace per artifact, LATEST written last — a
crash mid-commit leaves the previous height intact and the node resumes
from it (state-sync-style restore is just copying these files).
"""

from __future__ import annotations

import base64
import gzip
import json
import os

from celestia_app_tpu.chain.block import Block, Header

PRUNE_KEEP = 100  # same rollback window the in-memory history kept


def _atomic_write(path: str, data: bytes) -> None:
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    # fsync the directory so the rename itself is durable: without this a
    # power loss can persist a later artifact (LATEST) while losing an
    # earlier rename, breaking the write-ordering guarantee
    dfd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)


class ChainDB:
    def __init__(self, data_dir: str):
        self.dir = data_dir
        os.makedirs(os.path.join(data_dir, "state"), exist_ok=True)
        os.makedirs(os.path.join(data_dir, "blocks"), exist_ok=True)

    # -- commits ---------------------------------------------------------

    def _state_path(self, height: int) -> str:
        return os.path.join(self.dir, "state", f"{height:020d}.json.gz")

    def save_commit(
        self, height: int, store_data: dict[bytes, bytes], meta: dict
    ) -> None:
        doc = {
            "height": height,
            "meta": meta,
            "store": {k.hex(): v.hex() for k, v in store_data.items()},
        }
        blob = gzip.compress(
            json.dumps(doc, sort_keys=True, separators=(",", ":")).encode()
        )
        _atomic_write(self._state_path(height), blob)
        _atomic_write(os.path.join(self.dir, "LATEST"), str(height).encode())
        self._prune(height)

    def latest_height(self) -> int | None:
        try:
            with open(os.path.join(self.dir, "LATEST"), "rb") as f:
                return int(f.read().decode())
        except FileNotFoundError:
            return None

    def load_commit(self, height: int | None = None):
        """-> (height, store_data, meta); latest when height is None."""
        if height is None:
            height = self.latest_height()
            if height is None:
                raise FileNotFoundError("no committed state on disk")
        with gzip.open(self._state_path(height), "rb") as f:
            doc = json.loads(f.read())
        store = {
            bytes.fromhex(k): bytes.fromhex(v) for k, v in doc["store"].items()
        }
        return doc["height"], store, doc["meta"]

    def delete_above(self, height: int) -> None:
        """Remove commits and blocks above `height` (rollback discards the
        abandoned fork, like the reference's rollback deleting versions)."""
        for sub in ("state", "blocks"):
            d = os.path.join(self.dir, sub)
            for name in os.listdir(d):
                if not name.endswith(".json.gz"):
                    continue
                try:
                    h = int(name.split(".")[0])
                except ValueError:
                    continue
                if h > height:
                    os.unlink(os.path.join(d, name))

    def _prune(self, latest: int) -> None:
        state_dir = os.path.join(self.dir, "state")
        for name in os.listdir(state_dir):
            if not name.endswith(".json.gz"):
                continue
            try:
                h = int(name.split(".")[0])
            except ValueError:
                continue
            if h <= latest - PRUNE_KEEP:
                os.unlink(os.path.join(state_dir, name))

    # -- blocks ----------------------------------------------------------

    def _block_path(self, height: int) -> str:
        return os.path.join(self.dir, "blocks", f"{height:020d}.json.gz")

    def save_block(self, block: Block) -> None:
        h = block.header
        doc = {
            "header": {
                "chain_id": h.chain_id,
                "height": h.height,
                "time_unix": h.time_unix,
                "data_hash": h.data_hash.hex(),
                "square_size": h.square_size,
                "app_hash": h.app_hash.hex(),
                "proposer": h.proposer.hex(),
                "app_version": h.app_version,
                "last_block_hash": h.last_block_hash.hex(),
            },
            "txs": [base64.b64encode(t).decode() for t in block.txs],
        }
        blob = gzip.compress(
            json.dumps(doc, sort_keys=True, separators=(",", ":")).encode()
        )
        _atomic_write(self._block_path(h.height), blob)

    def load_block(self, height: int) -> Block:
        with gzip.open(self._block_path(height), "rb") as f:
            doc = json.loads(f.read())
        hd = doc["header"]
        header = Header(
            chain_id=hd["chain_id"],
            height=hd["height"],
            time_unix=hd["time_unix"],
            data_hash=bytes.fromhex(hd["data_hash"]),
            square_size=hd["square_size"],
            app_hash=bytes.fromhex(hd["app_hash"]),
            proposer=bytes.fromhex(hd["proposer"]),
            app_version=hd["app_version"],
            last_block_hash=bytes.fromhex(hd["last_block_hash"]),
        )
        return Block(header=header, txs=[base64.b64decode(t) for t in doc["txs"]])

    def block_heights(self) -> list[int]:
        out = []
        for name in os.listdir(os.path.join(self.dir, "blocks")):
            if name.endswith(".json.gz"):
                try:
                    out.append(int(name.split(".")[0]))
                except ValueError:
                    pass
        return sorted(out)
