"""ABCI-style query routing, including the custom proof routes.

Reference parity: app/app.go:393-394 registers the custom query routes
``custom/txInclusionProof`` and ``custom/shareInclusionProof``
(pkg/proof/querier.go:20-67), which re-extend the square from the stored
block's txs and emit a ShareProof. This router does the same against the
ChainDB block store, using the batched device prover
(da/proof_device.BlockProver) with per-height caching, plus the standard
keeper queries (bank balance, auth account, gov proposal, staking
validator, blob params, signal tally).

All requests/responses are JSON dicts so the HTTP service layer
(service/server.py) and the CLI can route them verbatim.
"""

from __future__ import annotations

import base64

from celestia_app_tpu import appconsts
from celestia_app_tpu.chain.state import Context, InfiniteGasMeter
from celestia_app_tpu.da import dah as dah_mod
from celestia_app_tpu.da import edscache as edscache_mod
from celestia_app_tpu.da import square as square_mod
from celestia_app_tpu.da.blob import is_blob_tx, unmarshal_blob_tx
from celestia_app_tpu.da.square import PfbEntry
from celestia_app_tpu.utils import telemetry


class QueryError(Exception):
    pass


def rebuild_square(app, height: int):
    """Reconstruct the square from the stored block (querier.go:88-116:
    proofs are derived from block data, not cached trees)."""
    if app.db is None:
        raise QueryError("no block store attached (need data_dir)")
    block = app.db.load_block(height)
    normal, pfbs = [], []
    for raw in block.txs:
        if is_blob_tx(raw):
            btx = unmarshal_blob_tx(raw)
            pfbs.append(PfbEntry(btx.tx, btx.blobs))
        else:
            normal.append(raw)
    threshold = appconsts.subtree_root_threshold(block.header.app_version)
    upper = appconsts.square_size_upper_bound(block.header.app_version)
    square = square_mod.construct(normal, pfbs, upper, threshold)
    return block, square


def build_prover_entry(app, height: int):
    """(block, square, EdsCacheEntry) for a committed height — the
    extend-once read path shared by the query router and the DAS sample
    server (das/server.py). The square is reconstructed from the stored
    block's txs (cheap host work); the EDS/DAH/roots come from the app's
    content-addressed cache when any lifecycle phase already computed
    them, and from ONE engine-gated pipeline dispatch
    (da/edscache.compute_entry — device, or the bit-identical fast_host
    path for host-engine validators, which must not touch the jax
    backend: a down accelerator relay HANGS backend init, wedging the
    HTTP handler mid-service-lock) otherwise."""
    block, square = rebuild_square(app, height)
    ods = dah_mod.shares_to_ods(square.share_bytes())
    cache = getattr(app, "eds_cache", None)
    engine = getattr(app, "engine", "auto")
    codec = getattr(app, "codec", None)
    scheme = codec.name if codec is not None else "rs2d-nmt"
    if cache is not None:
        entry = cache.get_or_compute(ods, engine, scheme)
    else:  # bare apps (fixtures) still get the one-shot pipeline
        entry = edscache_mod.compute_entry(ods, engine, scheme)
    if entry.data_root != block.header.data_hash:
        # a Byzantine (or corrupted-store) header can never be served
        # from the cache: the entry is a pure function of the ODS and the
        # header must match it — same check, cached or cold
        raise QueryError("recomputed data root mismatches stored header")
    return block, square, entry


def build_prover(app, height: int):
    """(block, square, BlockProver, data_root) for a committed height —
    the tuple-shaped wrapper over build_prover_entry the query routes
    consume; the prover builds at most once per entry (lazily, or ahead
    of time by the commit warmer)."""
    block, square, entry = build_prover_entry(app, height)
    if entry.scheme != "rs2d-nmt":
        # share/tx inclusion proofs are an NMT-range construction; other
        # codec-plane schemes serve their own sample proofs via /das/*
        raise QueryError(
            f"share proofs are not defined under DA scheme "
            f"{entry.scheme!r}")
    prover = entry.get_prover(getattr(app, "engine", "auto"))
    return block, square, prover, entry.data_root


class QueryRouter:
    def __init__(self, app):
        self.app = app
        self._prover_cache: dict[int, tuple] = {}
        self._tx_hash_cache: dict[int, dict[str, int]] = {}
        self._cache_generation = getattr(app, "state_generation", 0)

    def _ctx(self) -> Context:
        return Context(
            self.app.store, InfiniteGasMeter(), self.app.height, 0.0,
            self.app.chain_id, self.app.app_version,
        )

    # -- proof plumbing --------------------------------------------------

    def _prover(self, height: int):
        # rollback guard: any load()/load_height() bumps the app's state
        # generation; cached provers from before then may describe a
        # replaced block
        if getattr(self.app, "state_generation", 0) != self._cache_generation:
            self._prover_cache.clear()
            self._cache_generation = self.app.state_generation
        if height in self._prover_cache:
            return self._prover_cache[height]
        entry = build_prover(self.app, height)
        self._prover_cache.clear()  # keep at most one height resident
        self._prover_cache[height] = entry
        return entry

    # -- routes ----------------------------------------------------------

    def query(self, path: str, data: dict) -> dict:
        out = self._route(path, data)
        # count only after routing succeeds: attacker-varied junk paths must
        # not grow the telemetry registry unboundedly
        telemetry.incr(f"query.{path.replace('/', '_')}")
        return out

    def _route(self, path: str, data: dict) -> dict:
        if path == "custom/txInclusionProof":
            return self._tx_inclusion(data)
        if path == "custom/shareInclusionProof":
            return self._share_inclusion(data)
        if path == "custom/namespaceData":
            return self._namespace_data(data)
        if path == "custom/dah":
            return self._dah(data)
        if path == "custom/sampleCell":
            return self._sample_cell(data)
        if path == "bank/balance":
            addr = bytes.fromhex(data["address"])
            return {"balance": self.app.bank.balance(self._ctx(), addr)}
        if path == "auth/account":
            acc = self.app.auth.account(self._ctx(), bytes.fromhex(data["address"]))
            return {"account": acc}
        if path == "gov/proposal":
            return {"proposal": self.app.gov.proposal(self._ctx(), int(data["id"]))}
        if path == "staking/validators":
            ctx = self._ctx()
            return {
                "validators": [
                    {"operator": op.hex(), "power": p}
                    for op, p in self.app.staking.validators(ctx)
                ]
            }
        if path == "blob/params":
            return {"params": self.app.blob.params(self._ctx())}
        if path == "minfee/params":
            return {
                "network_min_gas_price":
                    self.app.minfee.network_min_gas_price(self._ctx())
            }
        if path == "tx":
            return self._tx_by_hash(data)
        if path == "blobstream/attestation":
            from celestia_app_tpu.chain import blobstream as bs_mod

            att = self.app.blobstream.attestation_by_nonce(
                self._ctx(), int(data["nonce"])
            )
            if att is None:
                return {"attestation": None}
            return {"attestation": bs_mod._att_to_json(att)}
        if path == "signal/tally":
            # x/signal QueryVersionTally (x/signal/keeper.go): voting
            # power signalled for a version + the total, plus any
            # scheduled upgrade — what an operator watches pre-flip
            version = int(data["version"])
            ctx = self._ctx()
            power, total = self.app.signal.tally(ctx, version)
            return {
                "power": power,
                "total": total,
                "pending": self.app.signal.pending_upgrade(ctx),
            }
        if path == "blobstream/latest_nonce":
            return {
                "nonce": self.app.blobstream.latest_attestation_nonce(self._ctx())
            }
        if path == "status":
            return {
                "chain_id": self.app.chain_id,
                "height": self.app.height,
                "app_version": self.app.app_version,
                "last_app_hash": self.app.last_app_hash.hex(),
                "last_block_hash": self.app.last_block_hash.hex(),
                "telemetry": telemetry.snapshot(),
            }
        raise QueryError(f"unknown query path {path!r}")

    def _tx_by_hash(self, data: dict) -> dict:
        """Confirmation lookup: find a tx by the sha256 of its broadcast
        bytes (blocks store the same BlobTx-envelope bytes clients hash) —
        the reference's /tx RPC that TxClient.ConfirmTx polls,
        pkg/user/tx_client.go:412. Per-height hash sets are cached so a
        confirmation polling loop costs O(new heights), not a gzip reload
        of the whole lookback window per poll."""
        import hashlib as _hashlib

        want = data["hash"].lower()
        if self.app.db is None:
            raise QueryError("no block store attached (need data_dir)")
        if getattr(self.app, "state_generation", 0) != self._cache_generation:
            self._prover_cache.clear()
            self._tx_hash_cache.clear()
            self._cache_generation = self.app.state_generation
        heights = self.app.db.block_heights()
        for h in reversed(heights[-int(data.get("lookback", 50)) :]):
            cached = self._tx_hash_cache.get(h)
            if cached is None:
                block = self.app.db.load_block(h)
                cached = {
                    _hashlib.sha256(raw).hexdigest(): i
                    for i, raw in enumerate(block.txs)
                }
                self._tx_hash_cache[h] = cached
            if want in cached:
                return {"found": True, "height": h, "index": cached[want]}
        return {"found": False}

    def _tx_inclusion(self, data: dict) -> dict:
        height = int(data["height"])
        tx_index = int(data["tx_index"])
        block, square, prover, root = self._prover(height)
        pf = prover.prove_tx(square, tx_index)
        return {"proof": _share_proof_json(pf), "data_root": root.hex()}

    def _share_inclusion(self, data: dict) -> dict:
        height = int(data["height"])
        start, end = int(data["start"]), int(data["end"])
        namespace = bytes.fromhex(data["namespace"])
        block, square, prover, root = self._prover(height)
        pf = prover.prove_shares(start, end, namespace)
        return {"proof": _share_proof_json(pf), "data_root": root.hex()}

    def prover_for(self, height: int):
        """Public accessor for the per-height device prover: (prover,
        data_root). The CLI's das command and tests use this instead of
        the private cache-entry tuple."""
        _block, _square, prover, root = self._prover(height)
        return prover, root

    def _dah(self, data: dict) -> dict:
        """A block's full DAH (row+col roots) — what a light node needs to
        verify samples; it binds to the header via dah.hash()==data_hash."""
        height = int(data["height"])
        block, square, prover, root = self._prover(height)
        return {
            "row_roots": [r.hex() for r in prover.dah.row_roots],
            "col_roots": [r.hex() for r in prover.dah.col_roots],
            "data_root": root.hex(),
        }

    def _sample_cell(self, data: dict) -> dict:
        """One extended-square cell + NMT proof (the DAS serving side)."""
        height = int(data["height"])
        row, col = int(data["row"]), int(data["col"])
        block, square, prover, root = self._prover(height)
        share, proof = prover.prove_cell(row, col)
        return {
            "share": base64.b64encode(share).decode(),
            "proof": {
                "start": proof.start,
                "end": proof.end,
                "total": proof.total,
                "nodes": [base64.b64encode(n).decode() for n in proof.nodes],
            },
        }

    def _namespace_data(self, data: dict) -> dict:
        """GetSharesByNamespace-style route: every share of a namespace in
        a block with a presence-and-completeness proof, or an absence
        witness (da/namespace_data.py)."""
        from celestia_app_tpu.da import namespace_data as nsd

        height = int(data["height"])
        namespace = bytes.fromhex(data["namespace"])
        block, square, prover, root = self._prover(height)
        nd = nsd.get_namespace_data(prover, namespace)
        return {
            "present": bool(nd.shares),
            "shares": [base64.b64encode(s).decode() for s in nd.shares],
            "proof": _share_proof_json(nd.proof) if nd.proof else None,
            "data_root": root.hex(),
        }


def _share_proof_json(pf) -> dict:
    """Serialize a ShareProof for transport; verifiable via
    proof.share_proof_from_json."""
    return {
        "data": [base64.b64encode(d).decode() for d in pf.data],
        "namespace": pf.namespace.hex(),
        "start_share": pf.start_share,
        "end_share": pf.end_share,
        "share_proofs": [
            {
                "start": sp.start,
                "end": sp.end,
                "total": sp.total,
                "nodes": [base64.b64encode(n).decode() for n in sp.nodes],
            }
            for sp in pf.share_proofs
        ],
        "row_proof": {
            "row_roots": [r.hex() for r in pf.row_proof.row_roots],
            "proofs": [
                {
                    "index": p.index,
                    "total": p.total,
                    "leaf_hash": base64.b64encode(p.leaf_hash).decode(),
                    "aunts": [base64.b64encode(a).decode() for a in p.aunts],
                }
                for p in pf.row_proof.proofs
            ],
            "start_row": pf.row_proof.start_row,
            "end_row": pf.row_proof.end_row,
        },
    }


def share_proof_from_json(doc: dict):
    """Rebuild a verifiable ShareProof from its JSON transport form."""
    from celestia_app_tpu.da.proof import RowProof, ShareProof
    from celestia_app_tpu.utils import merkle_host, nmt_host

    row_proof = RowProof(
        row_roots=[bytes.fromhex(r) for r in doc["row_proof"]["row_roots"]],
        proofs=[
            merkle_host.Proof(
                index=p["index"],
                total=p["total"],
                leaf_hash=base64.b64decode(p["leaf_hash"]),
                aunts=[base64.b64decode(a) for a in p["aunts"]],
            )
            for p in doc["row_proof"]["proofs"]
        ],
        start_row=doc["row_proof"]["start_row"],
        end_row=doc["row_proof"]["end_row"],
    )
    return ShareProof(
        data=[base64.b64decode(d) for d in doc["data"]],
        share_proofs=[
            nmt_host.NmtRangeProof(
                start=sp["start"],
                end=sp["end"],
                total=sp["total"],
                nodes=[base64.b64decode(n) for n in sp["nodes"]],
            )
            for sp in doc["share_proofs"]
        ],
        namespace=bytes.fromhex(doc["namespace"]),
        row_proof=row_proof,
        start_share=doc["start_share"],
        end_share=doc["end_share"],
    )
