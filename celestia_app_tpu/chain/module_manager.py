"""Versioned module manager: per-module [FromVersion, ToVersion] dispatch.

Reference parity: app/module/manager.go — celestia's fork of the SDK module
manager where every module declares the app-version range it serves;
Begin/EndBlock (and migrations) dispatch ONLY to modules of the current
version, and flipping the app version runs the entering/leaving modules'
migrations (store teardown/seeding), exactly how blobstream retires and
minfee arrives at v2 (app/modules.go:94-193 module ranges,
app/app.go:484-508 migrateCommitStore).

The App registers its keepers as `VersionedModule`s with explicit
begin/end-block order (setModuleOrder, app/modules.go:196); the manager is
the single dispatch point, so "which modules run at version N" is data,
not scattered `if app_version` checks.
"""

from __future__ import annotations

import dataclasses
from typing import Callable


@dataclasses.dataclass
class VersionedModule:
    """One module's lifecycle surface over its supported version range."""

    name: str
    from_version: int
    to_version: int
    begin_block: Callable | None = None  # fn(ctx)
    end_block: Callable | None = None  # fn(ctx)
    on_enter: Callable | None = None  # fn(ctx): version range just entered
    on_exit: Callable | None = None  # fn(ctx): version range just left

    def in_range(self, version: int) -> bool:
        return self.from_version <= version <= self.to_version


class ModuleManager:
    def __init__(self):
        self._modules: dict[str, VersionedModule] = {}
        self._begin_order: list[str] = []
        self._end_order: list[str] = []

    def register(self, module: VersionedModule) -> None:
        if module.name in self._modules:
            raise ValueError(f"module {module.name!r} already registered")
        if module.from_version > module.to_version:
            raise ValueError(f"module {module.name!r} has an empty version range")
        self._modules[module.name] = module
        # default order: registration order
        self._begin_order.append(module.name)
        self._end_order.append(module.name)

    def set_begin_order(self, names: list[str]) -> None:
        self._check_order(names)
        self._begin_order = list(names)

    def set_end_order(self, names: list[str]) -> None:
        self._check_order(names)
        self._end_order = list(names)

    def _check_order(self, names: list[str]) -> None:
        if len(names) != len(set(names)):
            dupes = sorted({n for n in names if names.count(n) > 1})
            raise ValueError(f"module order names duplicated: {dupes}")
        if sorted(names) != sorted(self._modules):
            missing = set(self._modules) - set(names)
            extra = set(names) - set(self._modules)
            raise ValueError(
                f"module order must name every module exactly once "
                f"(missing={sorted(missing)}, unknown={sorted(extra)})"
            )

    def active(self, version: int) -> list[str]:
        return [n for n in self._begin_order if self._modules[n].in_range(version)]

    def begin_block(self, ctx, version: int) -> None:
        for name in self._begin_order:
            m = self._modules[name]
            if m.begin_block is not None and m.in_range(version):
                m.begin_block(ctx)

    def end_block(self, ctx, version: int) -> None:
        for name in self._end_order:
            m = self._modules[name]
            if m.end_block is not None and m.in_range(version):
                m.end_block(ctx)

    def migrate(self, ctx, from_version: int, to_version: int) -> None:
        """Run range-boundary hooks for a version flip: modules LEAVING
        their range tear down (blobstream deletes its store at v2,
        app/app.go:467), modules ENTERING seed state (minfee param,
        app/app.go:474)."""
        for name in self._begin_order:
            m = self._modules[name]
            if m.in_range(from_version) and not m.in_range(to_version):
                if m.on_exit is not None:
                    m.on_exit(ctx)
        for name in self._begin_order:
            m = self._modules[name]
            if not m.in_range(from_version) and m.in_range(to_version):
                if m.on_enter is not None:
                    m.on_enter(ctx)
