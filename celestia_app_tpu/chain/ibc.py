"""IBC plane: proof-verified transfer stack with celestia's middlewares.

Reference parity — the reference's transfer stack order (app/app.go:329-346)
is tokenfilter → packet-forward (v2+) → transfer, plus the ICA host module
(v2+, app/app.go:290-300); all are modelled here:

- x/tokenfilter (ibc_middleware.go:38-81): celestia accepts ONLY
  native-denom transfers inbound — a foreign denom is answered with an
  error acknowledgement instead of minting a voucher.
- packet-forward middleware (ibc-apps PFM v6): an inbound transfer whose
  memo carries {"forward": {"receiver", "channel", ...}} is forwarded out
  on the next hop after being received here.
- ICS-27 interchain-accounts HOST: counterparty controllers register a
  deterministic host account and execute whitelisted msgs through it.
- ClientKeeper: a light-client analog tracking counterparty state roots;
  `recv_packet` REQUIRES a Merkle membership proof of the packet
  commitment against a tracked root when the channel is client-backed
  (ibc-go VerifyPacketCommitment) — a forged packet the counterparty never
  committed cannot be relayed in. The proof format is the framework's own
  bucketed app-hash tree (chain/state.py verify_membership), so two
  instances of THIS framework verify each other end-to-end (tests do
  exactly that). Channels registered without a client keep the trusted-
  relay fixture behavior.

Handshakes are keeper-registered (single-process node), as the reference's
ibctesting fixtures do. Packet data is the ICS-20 JSON form.
"""

from __future__ import annotations

import hashlib
import json

from celestia_app_tpu import appconsts
from celestia_app_tpu.chain.state import (
    Context,
    canonical_json,
    get_json,
    put_json,
    verify_absence,
    verify_membership,
)

NATIVE_DENOM = appconsts.BOND_DENOM  # "utia"


def _put(ctx, key: bytes, obj) -> None:
    put_json(ctx, key, obj)


def _get(ctx, key: bytes):
    return get_json(ctx, key)


def escrow_address(port: str, channel: str) -> bytes:
    """Deterministic module account escrowing outbound native tokens
    (ibc-go transfer keeper GetEscrowAddress analog)."""
    return hashlib.sha256(f"ibc-escrow/{port}/{channel}".encode()).digest()[:20]


def receiver_chain_is_source(source_port: str, source_channel: str, denom: str) -> bool:
    """transfertypes.ReceiverChainIsSource: the denom path starts with the
    packet's source port/channel, i.e. the token is returning home."""
    return denom.startswith(f"{source_port}/{source_channel}/")


def packet_commitment(packet: dict) -> bytes:
    """THE commitment preimage — the single definition used by commit,
    refund gating, and cross-chain proof verification (divergent copies
    would silently break counterparty proofs)."""
    return hashlib.sha256(json.dumps(packet, sort_keys=True).encode()).digest()


class IBCError(ValueError):
    pass


class ClientKeeper:
    """Light-client analog: tracked counterparty state roots by height.

    Two client modes at the `update_client` header-submission boundary
    (ibc-go 02-client UpdateClient → tendermint light client semantics):

    - **Verifying** (created with a trusted validator set): every update
      must carry the counterparty's Header plus a CommitCertificate whose
      >2/3-of-power signatures over that header's hash check out against
      the trusted set (chain/consensus.CommitCertificate.verify). The
      recorded root is taken FROM the verified header (its app_hash), so a
      malicious relayer cannot forge roots — packets are then
      trust-minimized end-to-end with the membership/absence proofs below.
    - **Trusting** (created bare — an explicitly-insecure fixture): roots
      are recorded on say-so, preserving only the ordering property that
      every recv must prove membership against a root recorded BEFORE the
      relay. Because MsgUpdateClient is permissionless (ibc-go keeps it
      permissionless only because every client verifies headers), a
      trusting client is updatable VIA TX only by the single relayer
      address pinned at creation (`insecure_relayer`) — otherwise any
      funded account could record a fabricated root and deliver forged
      packets against it, or brick the client with a huge bogus height
      (round-4 advisor finding)."""

    CONS = b"ibc/client/"

    def create_client(
        self, ctx: Context, client_id: str, *,
        chain_id: str | None = None,
        validators: dict[bytes, bytes] | None = None,
        powers: dict[bytes, int] | None = None,
        insecure_relayer: bytes | None = None,
    ) -> None:
        """`validators` maps 20-byte operator address -> 33-byte pubkey
        (the trusted set a real client is initialized with); passing it
        makes the client VERIFYING — the production mode. Without it the
        client is trusting, and `insecure_relayer` (20-byte address)
        names the ONLY account whose MsgUpdateClient txs it accepts; a
        bare trusting client can be updated keeper-direct (in-process
        fixtures) but never via tx."""
        meta_key = self.CONS + client_id.encode() + b"/meta"
        if _get(ctx, meta_key) is not None:
            # re-creation would reset latest_height and let update_client
            # overwrite recorded roots — the monotonicity guard's whole point
            raise IBCError(f"client {client_id!r} already exists")
        meta: dict = {"latest_height": 0}
        if validators:
            if chain_id is None or powers is None:
                raise IBCError(
                    "verifying client needs chain_id + validator powers"
                )
            meta["chain_id"] = chain_id
            meta["validators"] = {
                op.hex(): pk.hex() for op, pk in validators.items()
            }
            meta["powers"] = {op.hex(): int(p) for op, p in powers.items()}
        elif insecure_relayer is not None:
            meta["authorized_relayer"] = insecure_relayer.hex()
        _put(ctx, meta_key, meta)

    def latest_height(self, ctx: Context, client_id: str) -> int | None:
        """The client's highest recorded counterparty height (None if the
        client does not exist) — what a relayer checks before paying an
        update_client it does not need."""
        meta = _get(ctx, self.CONS + client_id.encode() + b"/meta")
        return None if meta is None else meta["latest_height"]

    def update_client(
        self, ctx: Context, client_id: str, height: int,
        root: bytes | None = None, *, header=None, cert=None,
        new_validators: dict[bytes, bytes] | None = None,
        new_powers: dict[bytes, int] | None = None,
        tx_relayer: bytes | None = None,
    ) -> None:
        """Verifying clients run the FULL light-client update
        (chain/light.py): >2/3 of the trusted power for a same-valset
        header; for a changed valset the relayer supplies the candidate
        set, which must match the header's validators_hash commitment,
        carry >2/3 of its own power, and overlap the trusted set by >1/3 —
        the adopted set is then persisted, so the client tracks the
        counterparty's validator set over time (ibc-go 02-client update +
        tendermint light semantics).

        `tx_relayer` is set by the MsgUpdateClient tx handler to the tx
        signer: verifying updates stay permissionless (the header+cert do
        the gating, exactly ibc-go's model), but a TRUSTING client accepts
        a tx update only from its pinned authorized relayer — say-so roots
        from arbitrary funded accounts are an escrow-theft / client-brick
        primitive (round-4 advisor finding)."""
        meta_key = self.CONS + client_id.encode() + b"/meta"
        meta = _get(ctx, meta_key)
        if meta is None:
            raise IBCError(f"unknown client {client_id!r}")
        if height <= meta["latest_height"]:
            raise IBCError(
                f"non-monotonic client update: {height} <= {meta['latest_height']}"
            )
        if meta.get("validators"):
            root = self._verify_header(
                meta, height, header, cert, new_validators, new_powers
            )
        else:
            if tx_relayer is not None:
                authorized = meta.get("authorized_relayer")
                if authorized is None or tx_relayer.hex() != authorized:
                    raise IBCError(
                        "trusting client refuses tx updates except from "
                        "its authorized relayer — create the client "
                        "verifying (header-checked) for permissionless "
                        "updates"
                    )
            if root is None:
                raise IBCError("trusting client update needs a root")
        _put(ctx, self.CONS + f"{client_id}/{height}".encode(),
             {"root": root.hex()})
        meta["latest_height"] = height
        _put(ctx, meta_key, meta)

    @staticmethod
    def _verify_header(meta: dict, height: int, header, cert,
                       new_validators=None, new_powers=None) -> bytes:
        """Light-client update against the client's persisted trusted
        state; on success the (possibly new) valset is written back into
        `meta`. Returns the root to record — the header's own app_hash
        (the state root the counterparty committed, which packet proofs
        verify against)."""
        from celestia_app_tpu.chain import light

        if header is None or cert is None:
            raise IBCError("verifying client requires header + certificate")
        if header.height != height:
            raise IBCError(
                f"header height mismatch: {header.height} != {height}"
            )
        trusted = light.TrustedState(
            height=meta["latest_height"],
            header_hash=b"",
            validators={
                bytes.fromhex(k): bytes.fromhex(v)
                for k, v in meta["validators"].items()
            },
            powers={bytes.fromhex(k): v for k, v in meta["powers"].items()},
        )
        # check_set=False: this set was validated when adopted and has been
        # in OUR store since — re-deriving every pubkey address per relay
        # would be pure overhead on the packet hot path
        lc = light.LightClient(meta["chain_id"], trusted, check_set=False)
        try:
            st = lc.update(header, cert, new_validators=new_validators,
                           new_powers=new_powers)
        except light.LightClientError as e:
            raise IBCError(f"header certificate verification failed: {e}") from None
        # persist the adopted set: the client follows valset changes
        meta["validators"] = {
            op.hex(): pk.hex() for op, pk in st.validators.items()
        }
        meta["powers"] = {op.hex(): int(p) for op, p in st.powers.items()}
        return header.app_hash

    def consensus_root(
        self, ctx: Context, client_id: str, height: int
    ) -> bytes | None:
        rec = _get(ctx, self.CONS + f"{client_id}/{height}".encode())
        return bytes.fromhex(rec["root"]) if rec else None


class ChannelKeeper:
    CHAN = b"ibc/chan/"
    SEQ = b"ibc/seq/"
    COMMIT = b"ibc/commit/"
    ACK = b"ibc/ack/"

    def open_channel(
        self, ctx: Context, port: str, channel: str,
        counterparty_port: str, counterparty_channel: str,
        client_id: str | None = None,
    ) -> None:
        """Register an OPEN channel (handshake result; fixtures call this
        directly, like the reference's testing pkg channels). A channel
        bound to `client_id` REQUIRES commitment proofs on receive.
        Proof-verified handshakes live in `channel_open_init/try/ack/
        confirm` below — this is the fixture shortcut."""
        _put(ctx, self.CHAN + f"{port}/{channel}".encode(), {
            "state": "OPEN",
            "counterparty_port": counterparty_port,
            "counterparty_channel": counterparty_channel,
            "client_id": client_id,
        })

    # -- the ICS-4 channel handshake (ibc-go 04-channel keeper) ----------
    #
    # Four proof-verified steps between chains A and B:
    #   A: ChanOpenInit     -> channel in state INIT
    #   B: ChanOpenTry      -> proves A's INIT record, state TRYOPEN
    #   A: ChanOpenAck      -> proves B's TRYOPEN record, state OPEN
    #   B: ChanOpenConfirm  -> proves A's OPEN record, state OPEN
    # Each proof is a membership proof of the counterparty's channel
    # record under a client-tracked state root — the same light-client
    # primitive packet receive uses, so an OPEN channel is one whose
    # whole lifecycle was proven, not asserted.

    def _chan_key(self, port: str, channel: str) -> bytes:
        return self.CHAN + f"{port}/{channel}".encode()

    def _verify_counterparty_channel(
        self, ctx: Context, clients: "ClientKeeper", client_id: str,
        counterparty_port: str, counterparty_channel: str,
        expected_states: tuple[str, ...],
        expect_port: str, expect_channel: str,
        proof: dict, proof_height: int, counterparty_record: dict,
    ) -> None:
        """The proof boundary shared by TRY/ACK/CONFIRM: the submitted
        counterparty channel RECORD must be committed under a tracked root,
        be in one of `expected_states`, and name US as ITS counterparty."""
        root = clients.consensus_root(ctx, client_id, proof_height)
        if root is None:
            raise IBCError(
                f"no consensus state for {client_id!r} at height {proof_height}"
            )
        # the exact bytes put_json commits on the counterparty, so the
        # membership proof binds the full record (single shared encoder)
        value = canonical_json(counterparty_record)
        key = self._chan_key(counterparty_port, counterparty_channel)
        if not verify_membership(root, key, value, proof):
            raise IBCError("counterparty channel proof verification failed")
        if counterparty_record.get("state") not in expected_states:
            raise IBCError(
                f"counterparty channel in state "
                f"{counterparty_record.get('state')!r}, need {expected_states}"
            )
        if (
            counterparty_record.get("counterparty_port") != expect_port
            or counterparty_record.get("counterparty_channel") != expect_channel
        ):
            raise IBCError("counterparty channel does not name this channel")

    def channel_open_init(
        self, ctx: Context, port: str, channel: str,
        counterparty_port: str, counterparty_channel: str, client_id: str,
    ) -> None:
        if self.channel(ctx, port, channel) is not None:
            raise IBCError(f"channel {port}/{channel} already exists")
        _put(ctx, self._chan_key(port, channel), {
            "state": "INIT",
            "counterparty_port": counterparty_port,
            "counterparty_channel": counterparty_channel,
            "client_id": client_id,
        })

    def channel_open_try(
        self, ctx: Context, clients: "ClientKeeper",
        port: str, channel: str,
        counterparty_port: str, counterparty_channel: str, client_id: str,
        counterparty_record: dict, proof: dict, proof_height: int,
    ) -> None:
        if self.channel(ctx, port, channel) is not None:
            raise IBCError(f"channel {port}/{channel} already exists")
        self._verify_counterparty_channel(
            ctx, clients, client_id, counterparty_port, counterparty_channel,
            ("INIT",), port, channel, proof, proof_height, counterparty_record,
        )
        _put(ctx, self._chan_key(port, channel), {
            "state": "TRYOPEN",
            "counterparty_port": counterparty_port,
            "counterparty_channel": counterparty_channel,
            "client_id": client_id,
        })

    def channel_open_ack(
        self, ctx: Context, clients: "ClientKeeper",
        port: str, channel: str,
        counterparty_record: dict, proof: dict, proof_height: int,
    ) -> None:
        chan = self.channel(ctx, port, channel)
        if chan is None or chan["state"] != "INIT":
            raise IBCError(f"channel {port}/{channel} not in INIT")
        self._verify_counterparty_channel(
            ctx, clients, chan["client_id"],
            chan["counterparty_port"], chan["counterparty_channel"],
            ("TRYOPEN",), port, channel, proof, proof_height,
            counterparty_record,
        )
        chan["state"] = "OPEN"
        _put(ctx, self._chan_key(port, channel), chan)

    def channel_open_confirm(
        self, ctx: Context, clients: "ClientKeeper",
        port: str, channel: str,
        counterparty_record: dict, proof: dict, proof_height: int,
    ) -> None:
        chan = self.channel(ctx, port, channel)
        if chan is None or chan["state"] != "TRYOPEN":
            raise IBCError(f"channel {port}/{channel} not in TRYOPEN")
        self._verify_counterparty_channel(
            ctx, clients, chan["client_id"],
            chan["counterparty_port"], chan["counterparty_channel"],
            ("OPEN",), port, channel, proof, proof_height,
            counterparty_record,
        )
        chan["state"] = "OPEN"
        _put(ctx, self._chan_key(port, channel), chan)

    def channel(self, ctx: Context, port: str, channel: str):
        return _get(ctx, self.CHAN + f"{port}/{channel}".encode())

    def next_sequence(self, ctx: Context, port: str, channel: str) -> int:
        key = self.SEQ + f"{port}/{channel}".encode()
        seq = (_get(ctx, key) or 1)
        _put(ctx, key, seq + 1)
        return seq

    def commit_packet(self, ctx: Context, packet: dict) -> None:
        key = (
            self.COMMIT
            + f"{packet['source_port']}/{packet['source_channel']}/"
            f"{packet['sequence']}".encode()
        )
        ctx.store.set(key, packet_commitment(packet))

    def take_commitment(self, ctx: Context, packet: dict) -> bool:
        """Delete the packet commitment; False if absent OR if the submitted
        packet does not hash to the stored commitment — a forged ack/timeout
        with altered amount/sender must never trigger a refund (ibc-go
        compares the commitment before processing)."""
        key = (
            self.COMMIT
            + f"{packet['source_port']}/{packet['source_channel']}/"
            f"{packet['sequence']}".encode()
        )
        stored = ctx.store.get(key)
        if stored is None:
            return False
        if stored != packet_commitment(packet):
            return False
        ctx.store.delete(key)
        return True

    def _ack_key(self, packet: dict) -> bytes:
        return (
            self.ACK
            + f"{packet['destination_port']}/{packet['destination_channel']}/"
            f"{packet['sequence']}".encode()
        )

    def write_ack(self, ctx: Context, packet: dict, ack: dict) -> None:
        _put(ctx, self._ack_key(packet), ack)

    def get_ack(self, ctx: Context, packet: dict):
        return _get(ctx, self._ack_key(packet))


class TransferKeeper:
    """ICS-20 transfer app (the module the token filter wraps)."""

    PORT = "transfer"
    VOUCHER = b"ibc/voucher/"  # voucher denom supply bookkeeping

    def __init__(self, bank, channels: ChannelKeeper):
        self.bank = bank
        self.channels = channels

    # -- outbound --------------------------------------------------------

    def send_transfer(
        self, ctx: Context, source_channel: str, sender: bytes,
        receiver: str, denom: str, amount: int, memo: str = "",
        timeout_height: int = 0,
    ) -> dict:
        """MsgTransfer: escrow native tokens (or burn returning vouchers)
        and emit the ICS-20 packet."""
        chan = self.channels.channel(ctx, self.PORT, source_channel)
        if chan is None or chan["state"] != "OPEN":
            raise IBCError(f"channel {source_channel!r} not open")
        if amount <= 0:
            raise IBCError("transfer amount must be positive")
        if denom == NATIVE_DENOM:
            self.bank.send(
                ctx, sender, escrow_address(self.PORT, source_channel), amount
            )
        else:
            raise IBCError(
                "only the native denom exists on this chain "
                "(token filter keeps foreign denoms out)"
            )
        packet = {
            "source_port": self.PORT,
            "source_channel": source_channel,
            "destination_port": chan["counterparty_port"],
            "destination_channel": chan["counterparty_channel"],
            "sequence": self.channels.next_sequence(ctx, self.PORT, source_channel),
            "timeout_height": timeout_height,  # counterparty height; 0 = none
            "data": {
                "denom": denom,
                "amount": str(amount),
                "sender": sender.hex(),
                "receiver": receiver,
                "memo": memo,
            },
        }
        self.channels.commit_packet(ctx, packet)
        ctx.emit_event(
            "ibc.transfer", channel=source_channel, denom=denom, amount=amount
        )
        # the full packet rides the event, as ibc-go's send_packet event
        # attributes do — ON-chain only the commitment hash exists, so
        # events are what relayers (tools/relayer.py, hermes in the
        # reference ecosystem) reconstruct packets from
        ctx.emit_event(
            "send_packet", packet_json=canonical_json(packet).decode()
        )
        return packet

    # -- inbound (called via the middleware stack) -----------------------

    def on_recv_packet(self, ctx: Context, packet: dict) -> dict:
        """Transfer app OnRecvPacket: unescrow returning native tokens or
        mint vouchers for foreign ones (the filter prevents the latter)."""
        data = packet["data"]
        amount = int(data["amount"])
        receiver = bytes.fromhex(data["receiver"])
        if receiver_chain_is_source(
            packet["source_port"], packet["source_channel"], data["denom"]
        ):
            # returning native token: strip one path hop and unescrow
            base = data["denom"].split("/", 2)[2]
            if base != NATIVE_DENOM:
                raise IBCError(f"unexpected returning denom {base!r}")
            self.bank.send(
                ctx,
                escrow_address(
                    packet["destination_port"], packet["destination_channel"]
                ),
                receiver,
                amount,
            )
            return {"result": "AQ=="}  # success ack
        # foreign token: plain ICS-20 would mint a voucher here. The token
        # filter middleware rejects before reaching this branch; keeping the
        # mint unimplemented means a mis-wired stack fails loudly.
        raise IBCError("voucher minting is disabled on this chain")

    def on_acknowledgement(self, ctx: Context, packet: dict, ack: dict) -> None:
        """Error acks refund the escrowed tokens (transfer keeper
        OnAcknowledgementPacket). The stored commitment gates processing:
        replayed or duplicate acks are no-ops, never double refunds."""
        if not self.channels.take_commitment(ctx, packet):
            raise IBCError("no commitment for packet (replayed or unknown)")
        if "error" in ack:
            self._refund(ctx, packet)

    def on_timeout(self, ctx: Context, packet: dict) -> None:
        if not self.channels.take_commitment(ctx, packet):
            raise IBCError("no commitment for packet (replayed or unknown)")
        self._refund(ctx, packet)

    def _refund(self, ctx: Context, packet: dict) -> None:
        data = packet["data"]
        self.bank.send(
            ctx,
            escrow_address(packet["source_port"], packet["source_channel"]),
            bytes.fromhex(data["sender"]),
            int(data["amount"]),
        )


class PacketForwardMiddleware:
    """ibc-apps packet-forward middleware (v2+; app/app.go:335-341): an
    inbound transfer whose memo is {"forward": {"receiver": ..., "channel":
    ...}} is delivered to the hop address, then immediately sent onward on
    the named channel. Sits BELOW the token filter and ABOVE transfer,
    matching the reference's stack order."""

    def __init__(self, app: TransferKeeper):
        self.app = app

    def on_recv_packet(self, ctx: Context, packet: dict) -> dict:
        data = packet.get("data")
        fwd = None
        if isinstance(data, dict) and ctx.app_version >= 2:
            # v1 has no packet-forward module (app/modules.go:171 range
            # analog): the memo is carried but never interpreted
            memo = data.get("memo", "")
            if isinstance(memo, str) and memo.startswith("{"):
                try:
                    fwd = json.loads(memo).get("forward")
                except (json.JSONDecodeError, AttributeError):
                    fwd = None
        ack = self.app.on_recv_packet(ctx, packet)
        if fwd is None or "error" in ack:
            return ack
        try:
            receiver = bytes.fromhex(data["receiver"])
            self.app.send_transfer(
                ctx,
                fwd["channel"],
                receiver,
                fwd["receiver"],
                NATIVE_DENOM,
                int(data["amount"]),
            )
        except (IBCError, ValueError, KeyError) as e:
            # the hop failed: the funds were received by the hop address and
            # STAY there (the in-flight model of PFM's non-refundable mode);
            # the ack reports the failure to the origin
            return {"error": f"packet forward failed: {e}"}
        ctx.emit_event(
            "ibc.packet_forward", channel=fwd["channel"], amount=data["amount"]
        )
        return ack


class TokenFilterMiddleware:
    """x/tokenfilter: reject inbound non-native transfers with an error ack
    (ibc_middleware.go:38-81). Wraps the next module's OnRecvPacket; all
    other callbacks pass through."""

    def __init__(self, app):
        self.app = app

    def on_recv_packet(self, ctx: Context, packet: dict) -> dict:
        data = packet.get("data")
        if not isinstance(data, dict) or "denom" not in data:
            # not an ICS-20 packet: pass down the stack (middleware is
            # unilateral, ibc_middleware.go:45-52)
            return self.app.on_recv_packet(ctx, packet)
        if receiver_chain_is_source(
            packet["source_port"], packet["source_channel"], data["denom"]
        ):
            return self.app.on_recv_packet(ctx, packet)
        ctx.emit_event(
            "ibc.tokenfilter.rejected",
            denom=data["denom"],
            sender=data.get("sender", ""),
        )
        return {"error": f"only native denom transfers accepted, got {data['denom']}"}


class ICAHostKeeper:
    """ICS-27 interchain accounts, HOST side (v2+; app/app.go:290-300).

    Counterparty controller chains register one deterministic host account
    per (connection, owner) and drive it with packets of whitelisted msgs.
    The allowlist mirrors the reference's host params (bank send, staking
    delegate/undelegate, gov vote — default_overrides-style subset)."""

    PORT = "icahost"
    ACCOUNTS = b"ica/acc/"
    ALLOWED = ("bank/MsgSend", "staking/MsgDelegate",
               "staking/MsgUndelegate", "gov/MsgVote")

    def __init__(self, router=None):
        self.router = router  # msg executor: fn(ctx, msg_dict, signer) -> None

    def account_address(self, channel: str, owner: str) -> bytes:
        return hashlib.sha256(f"ica/{channel}/{owner}".encode()).digest()[:20]

    def on_recv_packet(self, ctx: Context, packet: dict) -> dict:
        if ctx.app_version < 2:
            raise IBCError("ICA host is a v2+ module")
        data = packet.get("data")
        if not isinstance(data, dict):
            raise IBCError("malformed ICA packet")
        channel = packet["destination_channel"]
        if data.get("type") == "register":
            owner = str(data["owner"])
            addr = self.account_address(channel, owner)
            _put(ctx, self.ACCOUNTS + addr, {"channel": channel, "owner": owner})
            ctx.emit_event("ica.register", owner=owner, address=addr.hex())
            return {"result": addr.hex()}
        if data.get("type") == "tx":
            owner = str(data["owner"])
            addr = self.account_address(channel, owner)
            if _get(ctx, self.ACCOUNTS + addr) is None:
                raise IBCError("interchain account not registered")
            if self.router is None:
                raise IBCError("ICA msg router not wired")
            for m in data.get("msgs", []):
                if m.get("type") not in self.ALLOWED:
                    raise IBCError(
                        f"msg type {m.get('type')!r} not in the ICA allowlist"
                    )
                self.router(ctx, m, addr)
            return {"result": "AQ=="}
        raise IBCError(f"unknown ICA packet type {data.get('type')!r}")


class IBCStack:
    """The assembled stack, mirroring app.go:290-346: clients + channels;
    transfer wrapped by packet-forward (v2+) wrapped by tokenfilter; the
    ICA host on its own port (v2+)."""

    def __init__(self, bank, ica_router=None):
        self.clients = ClientKeeper()
        self.channels = ChannelKeeper()
        self.transfer = TransferKeeper(bank, self.channels)
        self.pfm = PacketForwardMiddleware(self.transfer)
        self.module = TokenFilterMiddleware(self.pfm)
        self.ica_host = ICAHostKeeper(ica_router)

    def _verify_commitment_proof(
        self, ctx: Context, chan: dict, packet: dict,
        proof: dict | None, proof_height: int | None,
    ) -> None:
        """ibc-go VerifyPacketCommitment: the packet must be committed in
        the counterparty's state at a tracked client height."""
        client_id = chan.get("client_id")
        if client_id is None:
            return  # fixture channel: trusted-relay mode
        if proof is None or proof_height is None:
            raise IBCError("channel requires a packet commitment proof")
        root = self.clients.consensus_root(ctx, client_id, proof_height)
        if root is None:
            raise IBCError(
                f"no consensus state for {client_id!r} at height {proof_height}"
            )
        # the counterparty commits under ITS OWN source port/channel key
        key = ChannelKeeper.COMMIT + (
            f"{packet['source_port']}/{packet['source_channel']}/"
            f"{packet['sequence']}".encode()
        )
        if not verify_membership(root, key, packet_commitment(packet), proof):
            raise IBCError("packet commitment proof verification failed")

    def _our_sending_channel(self, ctx: Context, packet: dict) -> dict:
        """The channel WE sent this packet on (ack/timeout settle our side)."""
        chan = self.channels.channel(
            ctx, packet["source_port"], packet["source_channel"]
        )
        if chan is None:
            raise IBCError("unknown source channel")
        return chan

    def acknowledge_packet(
        self, ctx: Context, packet: dict, ack: dict,
        proof: dict | None = None, proof_height: int | None = None,
    ) -> None:
        """Outbound settlement (ibc-go MsgAcknowledgement): on a
        client-backed channel the submitted ack must be PROVEN as the
        counterparty's written acknowledgement — otherwise any account
        could forge an error ack and pull an in-flight packet's escrow
        back while the counterparty delivers it (supply duplication)."""
        chan = self._our_sending_channel(ctx, packet)
        client_id = chan.get("client_id")
        if client_id is not None:
            if proof is None or proof_height is None:
                raise IBCError("channel requires an acknowledgement proof")
            root = self.clients.consensus_root(ctx, client_id, proof_height)
            if root is None:
                raise IBCError(
                    f"no consensus state for {client_id!r} at height {proof_height}"
                )
            ack_key = ChannelKeeper.ACK + (
                f"{packet['destination_port']}/{packet['destination_channel']}/"
                f"{packet['sequence']}".encode()
            )
            if not verify_membership(root, ack_key, canonical_json(ack), proof):
                raise IBCError("acknowledgement proof verification failed")
        self.transfer.on_acknowledgement(ctx, packet, ack)

    def timeout_packet(
        self, ctx: Context, packet: dict,
        proof: dict | None = None, proof_height: int | None = None,
    ) -> None:
        """Timeout refund (ibc-go MsgTimeout): on a client-backed channel
        the packet must carry a timeout height that has PASSED on the
        counterparty (tracked root exists at proof_height ≥ timeout) AND
        the counterparty must provably have NOT processed it (absence of
        its ack record — the receipt-absence analog)."""
        chan = self._our_sending_channel(ctx, packet)
        client_id = chan.get("client_id")
        if client_id is not None:
            timeout = int(packet.get("timeout_height") or 0)
            if timeout <= 0:
                raise IBCError("packet has no timeout height")
            if proof is None or proof_height is None:
                raise IBCError("channel requires a non-receipt proof")
            if proof_height < timeout:
                raise IBCError(
                    f"timeout height {timeout} not reached at proof height "
                    f"{proof_height}"
                )
            root = self.clients.consensus_root(ctx, client_id, proof_height)
            if root is None:
                raise IBCError(
                    f"no consensus state for {client_id!r} at height {proof_height}"
                )
            ack_key = ChannelKeeper.ACK + (
                f"{packet['destination_port']}/{packet['destination_channel']}/"
                f"{packet['sequence']}".encode()
            )
            if not verify_absence(root, ack_key, proof):
                raise IBCError("non-receipt (ack absence) proof failed")
        self.transfer.on_timeout(ctx, packet)

    def recv_packet(
        self,
        ctx: Context,
        packet: dict,
        proof: dict | None = None,
        proof_height: int | None = None,
    ) -> dict:
        """Core relay entry: proof-check, route by port to the middleware
        stack, record the acknowledgement."""
        chan = self.channels.channel(
            ctx, packet["destination_port"], packet["destination_channel"]
        )
        if chan is None or chan["state"] != "OPEN":
            raise IBCError("unknown destination channel")
        # the packet's source must be THIS channel's counterparty (ibc-go
        # checks this before proof verification): without it, a packet the
        # counterparty committed for a DIFFERENT channel — channel ids are
        # per-chain, collisions are normal — would proof-verify here and
        # pay out a second time from someone else's escrow
        if (
            packet["source_port"] != chan["counterparty_port"]
            or packet["source_channel"] != chan["counterparty_channel"]
        ):
            raise IBCError(
                f"packet source {packet['source_port']}/"
                f"{packet['source_channel']} does not match the channel's "
                f"counterparty {chan['counterparty_port']}/"
                f"{chan['counterparty_channel']}"
            )
        self._verify_commitment_proof(ctx, chan, packet, proof, proof_height)
        # packet receipts: a replayed sequence returns the recorded ack
        # without re-executing (no double unescrow)
        prior = self.channels.get_ack(ctx, packet)
        if prior is not None:
            return prior
        # the handler runs in a BRANCHED context that is only flushed on a
        # success ack — ibc-go's cached-context receive: an error ack must
        # leave zero state behind, or (e.g.) a failed packet-forward would
        # keep the delivered funds here WHILE the origin refunds the sender
        # (supply duplication), and a half-executed ICA batch would persist
        # under an ack that says it failed
        per_packet = ctx.branch()
        try:
            if packet["destination_port"] == ICAHostKeeper.PORT:
                ack = self.ica_host.on_recv_packet(per_packet, packet)
            else:
                ack = self.module.on_recv_packet(per_packet, packet)
        except (IBCError, ValueError, KeyError, TypeError, AttributeError) as e:
            # malformed packet data (non-dict msgs entries included) or a
            # failed escrow movement becomes an error acknowledgement,
            # never a relay crash
            ack = {"error": f"{type(e).__name__}: {e}"}
        if "error" not in ack:
            per_packet.store.write()
            ctx.events.extend(per_packet.events)
        self.channels.write_ack(ctx, packet, ack)
        ctx.emit_event(  # relayers read this to settle the ack (ibc-go's
            "write_acknowledgement",  # write_acknowledgement event)
            packet_json=canonical_json(packet).decode(),
            ack_json=canonical_json(ack).decode(),
        )
        return ack
