"""Minimal IBC transfer plane with celestia's token filter middleware.

Reference parity: the reference wires ibc-go's transfer app wrapped by
x/tokenfilter (app/app.go IBC stack assembly; x/tokenfilter/
ibc_middleware.go:38-81): celestia accepts ONLY native-denom transfers
inbound — an incoming packet whose denom did not originate here (i.e. the
denom path does not unwind through the receiving channel) is answered with
an error acknowledgement instead of minting a voucher. This keeps foreign
tokens off the DA chain while allowing utia to round-trip.

Scope: ICS-20 fungible token packet semantics over pre-established
channels (handshakes are out of scope for a single-process node — channels
are registered via keeper calls, as test fixtures do in the reference).
Implemented: escrow/unescrow for native denom, voucher burn for outbound
returns, packet commitments + acknowledgements, error-ack refunds, timeout
refunds, and the token filter. Packet data is the ICS-20 JSON form.
"""

from __future__ import annotations

import hashlib
import json

from celestia_app_tpu import appconsts
from celestia_app_tpu.chain.state import Context, get_json, put_json

NATIVE_DENOM = appconsts.BOND_DENOM  # "utia"


def _put(ctx, key: bytes, obj) -> None:
    put_json(ctx, key, obj)


def _get(ctx, key: bytes):
    return get_json(ctx, key)


def escrow_address(port: str, channel: str) -> bytes:
    """Deterministic module account escrowing outbound native tokens
    (ibc-go transfer keeper GetEscrowAddress analog)."""
    return hashlib.sha256(f"ibc-escrow/{port}/{channel}".encode()).digest()[:20]


def receiver_chain_is_source(source_port: str, source_channel: str, denom: str) -> bool:
    """transfertypes.ReceiverChainIsSource: the denom path starts with the
    packet's source port/channel, i.e. the token is returning home."""
    return denom.startswith(f"{source_port}/{source_channel}/")


class IBCError(ValueError):
    pass


class ChannelKeeper:
    CHAN = b"ibc/chan/"
    SEQ = b"ibc/seq/"
    COMMIT = b"ibc/commit/"
    ACK = b"ibc/ack/"

    def open_channel(
        self, ctx: Context, port: str, channel: str,
        counterparty_port: str, counterparty_channel: str,
    ) -> None:
        """Register an OPEN channel (handshake result; fixtures call this
        directly, like the reference's testing pkg channels)."""
        _put(ctx, self.CHAN + f"{port}/{channel}".encode(), {
            "state": "OPEN",
            "counterparty_port": counterparty_port,
            "counterparty_channel": counterparty_channel,
        })

    def channel(self, ctx: Context, port: str, channel: str):
        return _get(ctx, self.CHAN + f"{port}/{channel}".encode())

    def next_sequence(self, ctx: Context, port: str, channel: str) -> int:
        key = self.SEQ + f"{port}/{channel}".encode()
        seq = (_get(ctx, key) or 1)
        _put(ctx, key, seq + 1)
        return seq

    def commit_packet(self, ctx: Context, packet: dict) -> None:
        key = (
            self.COMMIT
            + f"{packet['source_port']}/{packet['source_channel']}/"
            f"{packet['sequence']}".encode()
        )
        ctx.store.set(key, hashlib.sha256(
            json.dumps(packet, sort_keys=True).encode()
        ).digest())

    def take_commitment(self, ctx: Context, packet: dict) -> bool:
        """Delete the packet commitment; False if absent OR if the submitted
        packet does not hash to the stored commitment — a forged ack/timeout
        with altered amount/sender must never trigger a refund (ibc-go
        compares the commitment before processing)."""
        key = (
            self.COMMIT
            + f"{packet['source_port']}/{packet['source_channel']}/"
            f"{packet['sequence']}".encode()
        )
        stored = ctx.store.get(key)
        if stored is None:
            return False
        submitted = hashlib.sha256(
            json.dumps(packet, sort_keys=True).encode()
        ).digest()
        if stored != submitted:
            return False
        ctx.store.delete(key)
        return True

    def _ack_key(self, packet: dict) -> bytes:
        return (
            self.ACK
            + f"{packet['destination_port']}/{packet['destination_channel']}/"
            f"{packet['sequence']}".encode()
        )

    def write_ack(self, ctx: Context, packet: dict, ack: dict) -> None:
        _put(ctx, self._ack_key(packet), ack)

    def get_ack(self, ctx: Context, packet: dict):
        return _get(ctx, self._ack_key(packet))


class TransferKeeper:
    """ICS-20 transfer app (the module the token filter wraps)."""

    PORT = "transfer"
    VOUCHER = b"ibc/voucher/"  # voucher denom supply bookkeeping

    def __init__(self, bank, channels: ChannelKeeper):
        self.bank = bank
        self.channels = channels

    # -- outbound --------------------------------------------------------

    def send_transfer(
        self, ctx: Context, source_channel: str, sender: bytes,
        receiver: str, denom: str, amount: int,
    ) -> dict:
        """MsgTransfer: escrow native tokens (or burn returning vouchers)
        and emit the ICS-20 packet."""
        chan = self.channels.channel(ctx, self.PORT, source_channel)
        if chan is None or chan["state"] != "OPEN":
            raise IBCError(f"channel {source_channel!r} not open")
        if amount <= 0:
            raise IBCError("transfer amount must be positive")
        if denom == NATIVE_DENOM:
            self.bank.send(
                ctx, sender, escrow_address(self.PORT, source_channel), amount
            )
        else:
            raise IBCError(
                "only the native denom exists on this chain "
                "(token filter keeps foreign denoms out)"
            )
        packet = {
            "source_port": self.PORT,
            "source_channel": source_channel,
            "destination_port": chan["counterparty_port"],
            "destination_channel": chan["counterparty_channel"],
            "sequence": self.channels.next_sequence(ctx, self.PORT, source_channel),
            "data": {
                "denom": denom,
                "amount": str(amount),
                "sender": sender.hex(),
                "receiver": receiver,
            },
        }
        self.channels.commit_packet(ctx, packet)
        ctx.emit_event(
            "ibc.transfer", channel=source_channel, denom=denom, amount=amount
        )
        return packet

    # -- inbound (called via the middleware stack) -----------------------

    def on_recv_packet(self, ctx: Context, packet: dict) -> dict:
        """Transfer app OnRecvPacket: unescrow returning native tokens or
        mint vouchers for foreign ones (the filter prevents the latter)."""
        data = packet["data"]
        amount = int(data["amount"])
        receiver = bytes.fromhex(data["receiver"])
        if receiver_chain_is_source(
            packet["source_port"], packet["source_channel"], data["denom"]
        ):
            # returning native token: strip one path hop and unescrow
            base = data["denom"].split("/", 2)[2]
            if base != NATIVE_DENOM:
                raise IBCError(f"unexpected returning denom {base!r}")
            self.bank.send(
                ctx,
                escrow_address(
                    packet["destination_port"], packet["destination_channel"]
                ),
                receiver,
                amount,
            )
            return {"result": "AQ=="}  # success ack
        # foreign token: plain ICS-20 would mint a voucher here. The token
        # filter middleware rejects before reaching this branch; keeping the
        # mint unimplemented means a mis-wired stack fails loudly.
        raise IBCError("voucher minting is disabled on this chain")

    def on_acknowledgement(self, ctx: Context, packet: dict, ack: dict) -> None:
        """Error acks refund the escrowed tokens (transfer keeper
        OnAcknowledgementPacket). The stored commitment gates processing:
        replayed or duplicate acks are no-ops, never double refunds."""
        if not self.channels.take_commitment(ctx, packet):
            raise IBCError("no commitment for packet (replayed or unknown)")
        if "error" in ack:
            self._refund(ctx, packet)

    def on_timeout(self, ctx: Context, packet: dict) -> None:
        if not self.channels.take_commitment(ctx, packet):
            raise IBCError("no commitment for packet (replayed or unknown)")
        self._refund(ctx, packet)

    def _refund(self, ctx: Context, packet: dict) -> None:
        data = packet["data"]
        self.bank.send(
            ctx,
            escrow_address(packet["source_port"], packet["source_channel"]),
            bytes.fromhex(data["sender"]),
            int(data["amount"]),
        )


class TokenFilterMiddleware:
    """x/tokenfilter: reject inbound non-native transfers with an error ack
    (ibc_middleware.go:38-81). Wraps the transfer app's OnRecvPacket; all
    other callbacks pass through."""

    def __init__(self, app: TransferKeeper):
        self.app = app

    def on_recv_packet(self, ctx: Context, packet: dict) -> dict:
        data = packet.get("data")
        if not isinstance(data, dict) or "denom" not in data:
            # not an ICS-20 packet: pass down the stack (middleware is
            # unilateral, ibc_middleware.go:45-52)
            return self.app.on_recv_packet(ctx, packet)
        if receiver_chain_is_source(
            packet["source_port"], packet["source_channel"], data["denom"]
        ):
            return self.app.on_recv_packet(ctx, packet)
        ctx.emit_event(
            "ibc.tokenfilter.rejected",
            denom=data["denom"],
            sender=data.get("sender", ""),
        )
        return {"error": f"only native denom transfers accepted, got {data['denom']}"}


class IBCStack:
    """The assembled stack: channel keeper + transfer app + token filter,
    mirroring the app.go wiring order."""

    def __init__(self, bank):
        self.channels = ChannelKeeper()
        self.transfer = TransferKeeper(bank, self.channels)
        self.module = TokenFilterMiddleware(self.transfer)

    def recv_packet(self, ctx: Context, packet: dict) -> dict:
        """Core relay entry: routes to the middleware stack and records the
        acknowledgement."""
        chan = self.channels.channel(
            ctx, packet["destination_port"], packet["destination_channel"]
        )
        if chan is None or chan["state"] != "OPEN":
            raise IBCError("unknown destination channel")
        # packet receipts: a replayed sequence returns the recorded ack
        # without re-executing (no double unescrow)
        prior = self.channels.get_ack(ctx, packet)
        if prior is not None:
            return prior
        try:
            ack = self.module.on_recv_packet(ctx, packet)
        except (IBCError, ValueError, KeyError, TypeError) as e:
            # malformed packet data or failed escrow movement becomes an
            # error acknowledgement, never a relay crash
            ack = {"error": f"{type(e).__name__}: {e}"}
        self.channels.write_ack(ctx, packet, ack)
        return ack
