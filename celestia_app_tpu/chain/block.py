"""Block structures: header with data root, block hash chain."""

from __future__ import annotations

import dataclasses
import hashlib

from celestia_app_tpu.da.shares import uvarint


@dataclasses.dataclass(frozen=True)
class Header:
    chain_id: str
    height: int
    time_unix: float
    data_hash: bytes  # DAH root — commits to the extended square
    square_size: int
    app_hash: bytes  # state root AFTER the previous block
    proposer: bytes
    app_version: int
    last_block_hash: bytes = b"\x00" * 32

    def encode(self) -> bytes:
        cid = self.chain_id.encode()
        out = bytearray()
        out += uvarint(len(cid)) + cid
        out += uvarint(self.height)
        out += int(self.time_unix * 1e9).to_bytes(8, "big")
        out += self.data_hash
        out += uvarint(self.square_size)
        out += self.app_hash
        out += uvarint(len(self.proposer)) + self.proposer
        out += uvarint(self.app_version)
        out += self.last_block_hash
        return bytes(out)

    def hash(self) -> bytes:
        return hashlib.sha256(self.encode()).digest()


@dataclasses.dataclass(frozen=True)
class Block:
    header: Header
    txs: tuple[bytes, ...]  # normal txs then BlobTx envelopes


@dataclasses.dataclass
class TxResult:
    code: int  # 0 = ok
    log: str
    gas_wanted: int
    gas_used: int
    events: list[dict]
