"""Block structures: header with data root, block hash chain."""

from __future__ import annotations

import dataclasses
import hashlib

from celestia_app_tpu.da.shares import uvarint


@dataclasses.dataclass(frozen=True)
class Header:
    chain_id: str
    height: int
    time_unix: float
    data_hash: bytes  # DAH root — commits to the extended square
    square_size: int
    app_hash: bytes  # state root AFTER the previous block
    proposer: bytes
    app_version: int
    last_block_hash: bytes = b"\x00" * 32
    # commitment to the CURRENT validator set's (operator, power) pairs
    # (Tendermint header ValidatorsHash analog): what light clients verify
    # certificates against. Pubkeys need not be in state — operator
    # addresses ARE pubkey hashes, so a candidate set is checkable against
    # this commitment plus the address derivation (chain/light.py).
    validators_hash: bytes = b"\x00" * 32
    # DA commitment scheme id (da/codec.py): what construction data_hash
    # commits under. 0 = 2D-RS+NMT, the only pre-codec-plane scheme;
    # absent on old wire docs ⇒ 0 (FORMATS §16.1)
    da_scheme: int = 0

    def encode(self) -> bytes:
        cid = self.chain_id.encode()
        out = bytearray()
        out += uvarint(len(cid)) + cid
        out += uvarint(self.height)
        # exact ns encoding: float*1e9 would exceed f64 integer range for
        # unix times (1.7e18 > 2^53); split whole seconds from the sub-second
        # fraction. Half-up rounding (int(x+0.5)), NOT Python's half-even
        # round(), so Go's math.Round / C's round() reproduce the same hash.
        whole = int(self.time_unix)
        frac_ns = int((self.time_unix - whole) * 1e9 + 0.5)  # lint: disable=det-float
        out += (whole * 1_000_000_000 + frac_ns).to_bytes(8, "big")
        out += self.data_hash
        out += uvarint(self.square_size)
        out += self.app_hash
        out += uvarint(len(self.proposer)) + self.proposer
        out += uvarint(self.app_version)
        out += self.last_block_hash
        out += self.validators_hash
        if self.da_scheme:
            # suffix-encoded ONLY for non-default schemes, so every
            # header hash the chain ever produced under 2D-RS+NMT is
            # unchanged by the codec plane (back-compat rule, FORMATS
            # §16.1); a non-zero scheme id domain-separates itself
            out += uvarint(self.da_scheme)
        return bytes(out)

    def hash(self) -> bytes:
        return hashlib.sha256(self.encode()).digest()


def validators_hash_of(validators: list[tuple[bytes, int]]) -> bytes:
    """Canonical commitment to a validator set: sha256 over the sorted
    (operator, power) pairs. Proposers compute it from staking state;
    every ProcessProposal recomputes and compares; light clients check
    candidate sets against it."""
    out = bytearray()
    for op, power in sorted(validators):
        out += uvarint(len(op)) + op
        out += uvarint(power)
    return hashlib.sha256(bytes(out)).digest()


@dataclasses.dataclass(frozen=True)
class Block:
    header: Header
    txs: tuple[bytes, ...]  # normal txs then BlobTx envelopes


@dataclasses.dataclass
class TxResult:
    code: int  # 0 = ok
    log: str
    gas_wanted: int
    gas_used: int
    events: list[dict]
