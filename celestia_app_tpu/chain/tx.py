"""Transaction codec: messages, bodies, signatures.

Reference parity: the cosmos-sdk Tx (body + auth info + signatures) carrying
sdk.Msgs — here with this framework's deterministic binary encoding (fields
in fixed order, uvarint length prefixes) instead of protobuf. Message set
mirrors the modules the reference wires (SURVEY.md §2): bank MsgSend, blob
MsgPayForBlobs (x/blob/types/payforblob.go:48-77), signal MsgSignalVersion /
MsgTryUpgrade (x/signal), blobstream MsgRegisterEVMAddress (x/blobstream).

Sign doc = sha256(chain_id || account_number || body_bytes); signatures are
64-byte secp256k1 (r || s).
"""

from __future__ import annotations

import dataclasses
import hashlib

from celestia_app_tpu.chain.crypto import PublicKey
from celestia_app_tpu.da.namespace import Namespace
from celestia_app_tpu.da.shares import read_uvarint, uvarint


def _b(data: bytes) -> bytes:
    return uvarint(len(data)) + data


def _s(text: str) -> bytes:
    return _b(text.encode())


class _Reader:
    def __init__(self, raw: bytes, off: int = 0):
        self.raw = raw
        self.off = off

    def u(self) -> int:
        v, self.off = read_uvarint(self.raw, self.off)
        return v

    def b(self) -> bytes:
        n = self.u()
        out = self.raw[self.off : self.off + n]
        if len(out) != n:
            raise ValueError("truncated field")
        self.off += n
        return out

    def s(self) -> str:
        return self.b().decode()

    def done(self) -> bool:
        return self.off == len(self.raw)


# ---------------------------------------------------------------------------
# Messages
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class MsgSend:
    TYPE = "bank/MsgSend"
    from_addr: bytes
    to_addr: bytes
    amount: int  # utia

    def encode(self) -> bytes:
        return _b(self.from_addr) + _b(self.to_addr) + uvarint(self.amount)

    @classmethod
    def decode(cls, raw: bytes) -> "MsgSend":
        r = _Reader(raw)
        return cls(r.b(), r.b(), r.u())


@dataclasses.dataclass(frozen=True)
class MsgPayForBlobs:
    """x/blob MsgPayForBlobs (payforblob.go:48-77)."""

    TYPE = "blob/MsgPayForBlobs"
    signer: bytes  # 20-byte address
    namespaces: tuple[bytes, ...]  # 29-byte raws
    blob_sizes: tuple[int, ...]
    share_commitments: tuple[bytes, ...]  # 32-byte
    share_versions: tuple[int, ...]

    def encode(self) -> bytes:
        out = bytearray(_b(self.signer))
        out += uvarint(len(self.namespaces))
        for ns in self.namespaces:
            out += ns
        out += uvarint(len(self.blob_sizes))
        for s in self.blob_sizes:
            out += uvarint(s)
        out += uvarint(len(self.share_commitments))
        for c in self.share_commitments:
            out += _b(c)
        out += uvarint(len(self.share_versions))
        for v in self.share_versions:
            out += uvarint(v)
        return bytes(out)

    @classmethod
    def decode(cls, raw: bytes) -> "MsgPayForBlobs":
        r = _Reader(raw)
        signer = r.b()
        ns = []
        for _ in range(r.u()):
            ns.append(r.raw[r.off : r.off + 29])
            if len(ns[-1]) != 29:
                raise ValueError("truncated namespace")
            r.off += 29
        sizes = tuple(r.u() for _ in range(r.u()))
        commits = tuple(r.b() for _ in range(r.u()))
        versions = tuple(r.u() for _ in range(r.u()))
        return cls(signer, tuple(ns), sizes, commits, versions)

    def validate_basic(self) -> None:
        n = len(self.namespaces)
        if n == 0:
            raise ValueError("no blobs in MsgPayForBlobs")
        if not (len(self.blob_sizes) == len(self.share_commitments) == len(self.share_versions) == n):
            raise ValueError("MsgPayForBlobs field lengths mismatch")
        if len(self.signer) != 20:
            raise ValueError("bad signer address")
        for ns_raw in self.namespaces:
            Namespace(ns_raw).validate_for_blob()
        for c in self.share_commitments:
            if len(c) != 32:
                raise ValueError("bad share commitment size")


@dataclasses.dataclass(frozen=True)
class MsgSignalVersion:
    """x/signal: a validator signals readiness for an app version."""

    TYPE = "signal/MsgSignalVersion"
    validator: bytes
    version: int

    def encode(self) -> bytes:
        return _b(self.validator) + uvarint(self.version)

    @classmethod
    def decode(cls, raw: bytes) -> "MsgSignalVersion":
        r = _Reader(raw)
        return cls(r.b(), r.u())


@dataclasses.dataclass(frozen=True)
class MsgTryUpgrade:
    """x/signal: tally signals; schedule the upgrade if >= 5/6 power."""

    TYPE = "signal/MsgTryUpgrade"
    signer: bytes

    def encode(self) -> bytes:
        return _b(self.signer)

    @classmethod
    def decode(cls, raw: bytes) -> "MsgTryUpgrade":
        return cls(_Reader(raw).b())


@dataclasses.dataclass(frozen=True)
class MsgRegisterEVMAddress:
    """x/blobstream (v1 only): validator registers its EVM address."""

    TYPE = "blobstream/MsgRegisterEVMAddress"
    validator: bytes
    evm_address: bytes  # 20 bytes

    def encode(self) -> bytes:
        return _b(self.validator) + _b(self.evm_address)

    @classmethod
    def decode(cls, raw: bytes) -> "MsgRegisterEVMAddress":
        r = _Reader(raw)
        return cls(r.b(), r.b())


@dataclasses.dataclass(frozen=True)
class MsgDelegate:
    """x/staking MsgDelegate: bond utia to a validator."""

    TYPE = "staking/MsgDelegate"
    delegator: bytes
    validator: bytes
    amount: int

    def encode(self) -> bytes:
        return _b(self.delegator) + _b(self.validator) + uvarint(self.amount)

    @classmethod
    def decode(cls, raw: bytes) -> "MsgDelegate":
        r = _Reader(raw)
        return cls(r.b(), r.b(), r.u())


@dataclasses.dataclass(frozen=True)
class MsgUndelegate:
    """x/staking MsgUndelegate: begin unbonding (21-day queue)."""

    TYPE = "staking/MsgUndelegate"
    delegator: bytes
    validator: bytes
    amount: int

    def encode(self) -> bytes:
        return _b(self.delegator) + _b(self.validator) + uvarint(self.amount)

    @classmethod
    def decode(cls, raw: bytes) -> "MsgUndelegate":
        r = _Reader(raw)
        return cls(r.b(), r.b(), r.u())


@dataclasses.dataclass(frozen=True)
class MsgBeginRedelegate:
    """x/staking MsgBeginRedelegate: move stake between validators."""

    TYPE = "staking/MsgBeginRedelegate"
    delegator: bytes
    src_validator: bytes
    dst_validator: bytes
    amount: int

    def encode(self) -> bytes:
        return (
            _b(self.delegator) + _b(self.src_validator)
            + _b(self.dst_validator) + uvarint(self.amount)
        )

    @classmethod
    def decode(cls, raw: bytes) -> "MsgBeginRedelegate":
        r = _Reader(raw)
        return cls(r.b(), r.b(), r.b(), r.u())


@dataclasses.dataclass(frozen=True)
class MsgCreateValidator:
    """x/staking MsgCreateValidator (operator key = account key here).

    `pubkey` is the optional consensus public key (33-byte compressed),
    the reference's Pubkey field on MsgCreateValidator — registering it
    on-chain is what lets a runtime-created validator's votes verify and
    its address join the proposer rotation (chain/reactor.py). Encoded
    only when present, so pre-existing tx bytes (and their app-hash pins)
    are unchanged."""

    TYPE = "staking/MsgCreateValidator"
    operator: bytes
    self_stake: int
    pubkey: bytes = b""

    def encode(self) -> bytes:
        out = _b(self.operator) + uvarint(self.self_stake)
        if self.pubkey:
            out += _b(self.pubkey)
        return out

    @classmethod
    def decode(cls, raw: bytes) -> "MsgCreateValidator":
        r = _Reader(raw)
        op, stake = r.b(), r.u()
        pubkey = b"" if r.done() else r.b()
        return cls(op, stake, pubkey)


@dataclasses.dataclass(frozen=True)
class MsgSubmitProposal:
    """x/gov MsgSubmitProposal carrying param changes (the reference routes
    param-change content through x/paramfilter's guarded handler)."""

    TYPE = "gov/MsgSubmitProposal"
    proposer: bytes
    changes_json: bytes  # canonical JSON [{"param": ..., "value": ...}]
    initial_deposit: int
    title: str = ""

    def encode(self) -> bytes:
        t = self.title.encode()
        return (
            _b(self.proposer) + _b(self.changes_json)
            + uvarint(self.initial_deposit) + _b(t)
        )

    @classmethod
    def decode(cls, raw: bytes) -> "MsgSubmitProposal":
        r = _Reader(raw)
        return cls(r.b(), r.b(), r.u(), r.b().decode())


@dataclasses.dataclass(frozen=True)
class MsgDeposit:
    TYPE = "gov/MsgDeposit"
    depositor: bytes
    proposal_id: int
    amount: int

    def encode(self) -> bytes:
        return _b(self.depositor) + uvarint(self.proposal_id) + uvarint(self.amount)

    @classmethod
    def decode(cls, raw: bytes) -> "MsgDeposit":
        r = _Reader(raw)
        return cls(r.b(), r.u(), r.u())


@dataclasses.dataclass(frozen=True)
class MsgVote:
    TYPE = "gov/MsgVote"
    voter: bytes
    proposal_id: int
    option: str  # yes | no | abstain | veto

    def encode(self) -> bytes:
        return _b(self.voter) + uvarint(self.proposal_id) + _b(self.option.encode())

    @classmethod
    def decode(cls, raw: bytes) -> "MsgVote":
        r = _Reader(raw)
        return cls(r.b(), r.u(), r.b().decode())


@dataclasses.dataclass(frozen=True)
class MsgTransfer:
    """ibc-go transfer MsgTransfer (token filter guards the inbound side).

    `timeout_height` is the counterparty height after which the packet
    may be timed out instead of received (ibc-go's TimeoutHeight; 0 =
    no timeout). Encoded only when set, so pre-existing tx bytes are
    unchanged."""

    TYPE = "ibc/MsgTransfer"
    sender: bytes
    source_channel: str
    receiver: str  # address encoding of the counterparty chain
    denom: str
    amount: int
    timeout_height: int = 0

    def encode(self) -> bytes:
        out = (
            _b(self.sender) + _b(self.source_channel.encode())
            + _b(self.receiver.encode()) + _b(self.denom.encode())
            + uvarint(self.amount)
        )
        if self.timeout_height:
            out += uvarint(self.timeout_height)
        return out

    @classmethod
    def decode(cls, raw: bytes) -> "MsgTransfer":
        r = _Reader(raw)
        sender, chan, recv, denom, amount = (
            r.b(), r.b().decode(), r.b().decode(), r.b().decode(), r.u()
        )
        timeout = 0 if r.done() else r.u()
        return cls(sender, chan, recv, denom, amount, timeout)


@dataclasses.dataclass(frozen=True)
class MsgUpdateClient:
    """ibc-go client MsgUpdateClient as a CONSENSUS transaction: the
    recorded counterparty root is part of the replicated state, so every
    validator holds identical client state and the proof-gated relay txs
    (MsgRecvPacket/ack/timeout) evaluate identically network-wide — a
    node-local keeper update would fork validators the first time a
    proof checks out on one and not another. For verifying clients the
    header/certificate/valset JSON payloads ride along (chain/light.py
    semantics); say-so clients take the bare root."""

    TYPE = "ibc/MsgUpdateClient"
    relayer: bytes
    client_id: str
    height: int
    root: bytes  # 32-byte counterparty app hash
    header_json: bytes = b""  # consensus.header_to_json (verifying)
    cert_json: bytes = b""  # consensus.cert_to_json (verifying)
    valset_json: bytes = b""  # {"operators": {hex: pubkey hex}, "powers"}

    def encode(self) -> bytes:
        return (
            _b(self.relayer) + _b(self.client_id.encode())
            + uvarint(self.height) + _b(self.root)
            + _b(self.header_json) + _b(self.cert_json)
            + _b(self.valset_json)
        )

    @classmethod
    def decode(cls, raw: bytes) -> "MsgUpdateClient":
        r = _Reader(raw)
        return cls(r.b(), r.b().decode(), r.u(), r.b(), r.b(), r.b(),
                   r.b())


@dataclasses.dataclass(frozen=True)
class MsgRecvPacket:
    """ibc-go channel MsgRecvPacket: a relayer submits an inbound packet
    WITH its commitment proof as a transaction, so packet application is
    part of consensus (deterministic across validators) rather than a
    node-local side channel. Payloads are the framework's canonical-JSON
    packet/proof forms (chain/ibc.py)."""

    TYPE = "ibc/MsgRecvPacket"
    relayer: bytes
    packet_json: bytes
    proof_json: bytes  # empty = fixture channel (no client binding)
    proof_height: int

    def encode(self) -> bytes:
        return (
            _b(self.relayer) + _b(self.packet_json)
            + _b(self.proof_json) + uvarint(self.proof_height)
        )

    @classmethod
    def decode(cls, raw: bytes) -> "MsgRecvPacket":
        r = _Reader(raw)
        return cls(r.b(), r.b(), r.b(), r.u())


@dataclasses.dataclass(frozen=True)
class MsgAcknowledgePacket:
    """ibc-go MsgAcknowledgement: outbound-packet settlement (refund on
    error acks), gated by the stored commitment AND — on client-backed
    channels — a membership proof of the counterparty's written ack."""

    TYPE = "ibc/MsgAcknowledgePacket"
    relayer: bytes
    packet_json: bytes
    ack_json: bytes
    proof_json: bytes = b""
    proof_height: int = 0

    def encode(self) -> bytes:
        return (
            _b(self.relayer) + _b(self.packet_json) + _b(self.ack_json)
            + _b(self.proof_json) + uvarint(self.proof_height)
        )

    @classmethod
    def decode(cls, raw: bytes) -> "MsgAcknowledgePacket":
        r = _Reader(raw)
        return cls(r.b(), r.b(), r.b(), r.b(), r.u())


@dataclasses.dataclass(frozen=True)
class MsgTimeoutPacket:
    """ibc-go MsgTimeout: refund an expired outbound packet — on
    client-backed channels gated by timeout-height expiry plus an ABSENCE
    proof of the counterparty's ack (the receipt-absence analog)."""

    TYPE = "ibc/MsgTimeoutPacket"
    relayer: bytes
    packet_json: bytes
    proof_json: bytes = b""
    proof_height: int = 0

    def encode(self) -> bytes:
        return (
            _b(self.relayer) + _b(self.packet_json)
            + _b(self.proof_json) + uvarint(self.proof_height)
        )

    @classmethod
    def decode(cls, raw: bytes) -> "MsgTimeoutPacket":
        r = _Reader(raw)
        return cls(r.b(), r.b(), r.b(), r.u())


@dataclasses.dataclass(frozen=True)
class MsgExec:
    """x/authz MsgExec: the grantee executes messages on the granter's
    behalf; each inner message's native signer must have granted the tx
    signer authorization for that message type."""

    TYPE = "authz/MsgExec"
    grantee: bytes
    inner: tuple  # decoded msg objects

    def encode(self) -> bytes:
        out = bytearray(_b(self.grantee))
        out += uvarint(len(self.inner))
        for m in self.inner:
            out += _s(m.TYPE) + _b(m.encode())
        return bytes(out)

    @classmethod
    def decode(cls, raw: bytes) -> "MsgExec":
        r = _Reader(raw)
        grantee = r.b()
        inner = []
        for _ in range(r.u()):
            t = r.s()
            inner.append(decode_msg(t, r.b()))
        return cls(grantee, tuple(inner))


MSG_TYPES = {
    m.TYPE: m
    for m in (
        MsgSend, MsgPayForBlobs, MsgSignalVersion, MsgTryUpgrade,
        MsgRegisterEVMAddress, MsgDelegate, MsgUndelegate, MsgBeginRedelegate,
        MsgCreateValidator, MsgSubmitProposal, MsgDeposit, MsgVote, MsgTransfer,
        MsgExec, MsgRecvPacket, MsgAcknowledgePacket, MsgTimeoutPacket,
        MsgUpdateClient,
    )
}


def decode_msg(type_url: str, payload: bytes):
    cls = MSG_TYPES.get(type_url)
    if cls is None:
        raise ValueError(f"unknown msg type {type_url!r}")
    return cls.decode(payload)


# ---------------------------------------------------------------------------
# Tx
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class TxBody:
    msgs: tuple  # decoded msg objects
    chain_id: str
    account_number: int
    sequence: int
    fee: int  # utia
    gas_limit: int
    memo: str = ""
    timeout_height: int = 0
    fee_granter: bytes = b""  # feegrant: empty = the signer pays

    def encode(self) -> bytes:
        out = bytearray(uvarint(len(self.msgs)))
        for m in self.msgs:
            out += _s(m.TYPE) + _b(m.encode())
        out += _s(self.chain_id)
        out += uvarint(self.account_number)
        out += uvarint(self.sequence)
        out += uvarint(self.fee)
        out += uvarint(self.gas_limit)
        out += _s(self.memo)
        out += uvarint(self.timeout_height)
        out += _b(self.fee_granter)
        return bytes(out)

    @classmethod
    def decode(cls, raw: bytes) -> tuple["TxBody", int]:
        r = _Reader(raw)
        msgs = []
        for _ in range(r.u()):
            t = r.s()
            msgs.append(decode_msg(t, r.b()))
        body = cls(
            msgs=tuple(msgs),
            chain_id=r.s(),
            account_number=r.u(),
            sequence=r.u(),
            fee=r.u(),
            gas_limit=r.u(),
            memo=r.s(),
            timeout_height=r.u(),
            fee_granter=r.b(),
        )
        return body, r.off


@dataclasses.dataclass(frozen=True)
class Tx:
    body: TxBody
    pubkey: bytes  # 33-byte compressed secp256k1
    signature: bytes  # 64-byte r||s

    def encode(self) -> bytes:
        return _b(self.body.encode()) + _b(self.pubkey) + _b(self.signature)

    @classmethod
    def decode(cls, raw: bytes) -> "Tx":
        r = _Reader(raw)
        body_raw = r.b()
        body, used = TxBody.decode(body_raw)
        if used != len(body_raw):
            raise ValueError("trailing bytes in tx body")
        tx = cls(body=body, pubkey=r.b(), signature=r.b())
        if not r.done():
            raise ValueError("trailing bytes in tx")
        return tx

    def hash(self) -> bytes:
        return hashlib.sha256(self.encode()).digest()

    def sign_doc(self) -> bytes:
        return sign_doc(self.body)

    def verify_signature(self) -> bool:
        return PublicKey(self.pubkey).verify(self.signature, self.sign_doc())


def sign_doc(body: TxBody) -> bytes:
    return (
        _s(body.chain_id) + uvarint(body.account_number) + _b(body.encode())
    )


def sign_tx(body: TxBody, priv) -> Tx:
    """Sign a body with a chain.crypto.PrivateKey."""
    sig = priv.sign(sign_doc(body))
    return Tx(body=body, pubkey=priv.public_key().compressed, signature=sig)


def decode_tx(raw: bytes):
    """Wire dispatcher: protobuf TxRaw (the wire default, reference-
    compatible — see wire/codec.py) or this framework's legacy codec."""
    from celestia_app_tpu.wire import codec as wire_codec

    return wire_codec.decode_any_tx(raw)
