"""The admission plane: two-phase tx admission with batched sig verification.

The per-user hot path used to pay one scalar secp256k1 verification per
transaction per phase — CheckTx at the mempool door, the PrepareProposal
ante filter, ProcessProposal on every validator, FinalizeBlock delivery,
and again on blocksync/WAL replay. This module restructures that into the
ROADMAP's two-phase admit:

  phase 1 (stateless): a whole batch of pending signatures is verified in
      ONE vmapped device dispatch (`ops/secp256k1.verify_batch`), and
      every success is recorded in the App's `VerifiedSigCache` keyed by
      the exact (pubkey, signature, sign-doc) triple;
  phase 2 (stateful): the ante chain runs per tx as before — nonce, fee,
      gas, blob gates — but its signature step consults the cache first,
      so a tx admitted at CheckTx is NEVER re-verified at proposal,
      delivery, or replay time.

The cache is sound by construction: a key is inserted only after the
signature verified TRUE over exactly the bytes the ante would verify, so
a hit can only skip a verification that would have returned True with
identical inputs. Consensus results are bit-identical with the cache on,
off, hot, or cold — prevalidation is an optimization plane, never an
authority, and any failure inside it degrades to the scalar path (counted
in telemetry, `admission.prevalidate_errors`).

The traffic plane (ISSUE 15) extends phase 1 with the SAME pattern for
blob share commitments: the reference recomputes each blob's commitment
in both CheckTx and ProcessProposal (`ValidateBlobTx`), and this repo
used to pay a per-blob host-Python subtree-root MMR at CheckTx and then
recompute every one of them again in ProcessProposal's batch.
`prevalidate` now also computes ALL uncached pending blobs' commitments
in one `da/commitment_device` dispatch and fills the App's
`VerifiedCommitmentCache`; `blob_validation.validate_blob_tx` consults
it before paying a host recompute, so a commitment checked at CheckTx
is NEVER recomputed at PrepareProposal, ProcessProposal, FinalizeBlock,
or WAL replay. The cache maps blob content to its COMPUTED-TRUE
commitment — a hit can only skip a recompute that would have produced
the identical bytes, so a Byzantine tx whose claimed commitment
mismatches is rejected by the same byte-compare, warm cache or cold.

Telemetry (the counters the tier-1 no-re-verification test pins):
  admission.sig_cache_hits       ante skipped a verify via the cache
  admission.sig_scalar_verified  ante ran a scalar verify (cache miss)
  admission.batch_dispatches     device batch dispatches
  admission.batch_lanes          signatures sent through the device path
  admission.batch_verified       lanes that verified and were cached
  admission.batch_rejected       lanes that failed batch verification
  admission.prevalidate_below_batch  batches too small for the device

Commitment counters (the tier-1 no-recompute test pins; FORMATS §20):
  commitment.cache_hits          a validation consumed a cached commitment
  commitment.recomputes          a validation paid a commitment compute
                                 (per blob; host path or a cold batch)
  commitment.batch_dispatches    prevalidation commitment batches
  commitment.batch_lanes         blobs computed by prevalidation batches
  commitment.prevalidate_below_batch  commitment batches below the gate
"""

from __future__ import annotations

import hashlib
import os
import threading
from collections import OrderedDict

from celestia_app_tpu.utils import telemetry

# Below this many uncached signatures the device dispatch is not worth
# its padding (and, on first use in a process, its jit compile): the
# ante's scalar path fills the cache instead. Tests and the bench pin it
# via env to force either path.
MIN_DEVICE_BATCH = int(os.environ.get("CELESTIA_ADMISSION_MIN_BATCH", "16"))
SIG_CACHE_MAX = 65536


def sig_key(pubkey: bytes, signature: bytes, message: bytes) -> bytes:
    """The cache key: length-framed so no two distinct (pubkey, sig,
    sign-doc) triples can collide by concatenation ambiguity."""
    h = hashlib.sha256()
    for part in (pubkey, signature, message):
        h.update(len(part).to_bytes(4, "big"))
        h.update(part)
    return h.digest()


class VerifiedSigCache:
    """Bounded LRU set of signature triples that verified TRUE.

    Lives on the App (one per state machine); CheckTx, the proposal
    paths, and replay all share it. Entries are state-independent facts
    (pure curve math over fixed bytes), so the cache survives rollbacks
    and reloads untouched."""

    def __init__(self, maxsize: int = SIG_CACHE_MAX):
        self.maxsize = maxsize
        self._lock = threading.Lock()
        self._keys: OrderedDict[bytes, None] = OrderedDict()  # guarded-by: _lock
        # LRU churn evidence: soak verdicts assert the cap actually
        # cycled (per-instance, unlike the process-global telemetry)
        self.evictions = 0  # guarded-by: _lock

    key = staticmethod(sig_key)

    def __len__(self) -> int:
        with self._lock:
            return len(self._keys)

    def hit(self, key: bytes) -> bool:
        with self._lock:
            if key in self._keys:
                self._keys.move_to_end(key)
                telemetry.incr("admission.sig_cache_hits")
                return True
            return False

    def contains(self, key: bytes) -> bool:
        """Membership probe WITHOUT the hit counter or LRU refresh —
        prevalidation's dedup uses this so `admission.sig_cache_hits`
        keeps meaning "the ante skipped a verify"."""
        with self._lock:
            return key in self._keys

    def put(self, key: bytes) -> None:
        with self._lock:
            self._keys[key] = None
            self._keys.move_to_end(key)
            while len(self._keys) > self.maxsize:
                self._keys.popitem(last=False)
                self.evictions += 1
                telemetry.incr("admission.sig_cache_evictions")


COMMITMENT_CACHE_MAX = 16384


def commitment_key(namespace: bytes, share_version: int, data: bytes,
                   subtree_root_threshold: int) -> bytes:
    """The commitment-cache key: sha256 over the length-framed
    (namespace, share-version, blob bytes, threshold) tuple — exactly
    the inputs `da/commitment.create_commitment` hashes from, framed so
    no two distinct blobs can collide by concatenation ambiguity (two
    blobs sharing a byte prefix must never share a key). The integer
    fields encode as decimal bytes: total over ANY int, so an
    adversarial tx carrying an out-of-range share version (the wire
    varint is unbounded; Blob() does not validate on construction) can
    never make prevalidation's key pass raise and knock the whole
    window's honest blobs off the batch — it just gets a key, and the
    ante's own validation rejects the blob later."""
    h = hashlib.sha256()
    for part in (namespace, b"%d" % share_version, data,
                 b"%d" % subtree_root_threshold):
        h.update(len(part).to_bytes(4, "big"))
        h.update(part)
    return h.digest()


class VerifiedCommitmentCache:
    """Bounded LRU of blob-content keys -> their COMPUTED share
    commitment (32 bytes).

    Lives on the App beside the VerifiedSigCache; CheckTx, both proposal
    phases, and replay share it. Values are pure functions of the key
    (the MMR-of-NMT-subtree-roots over the blob's shares), so the cache
    survives rollbacks and reloads untouched, and a hit can only skip a
    recompute that would have produced identical bytes — the Byzantine
    mismatch case still rejects on the same byte-compare."""

    def __init__(self, maxsize: int = COMMITMENT_CACHE_MAX):
        self.maxsize = maxsize
        self._lock = threading.Lock()
        self._map: OrderedDict[bytes, bytes] = OrderedDict()  # guarded-by: _lock
        # LRU churn evidence for soak verdicts (see VerifiedSigCache)
        self.evictions = 0  # guarded-by: _lock

    key = staticmethod(commitment_key)

    def __len__(self) -> int:
        with self._lock:
            return len(self._map)

    def hit(self, key: bytes) -> bytes | None:
        """The cached commitment, counting the hit (a validation skipped
        a recompute), or None on a miss."""
        with self._lock:
            value = self._map.get(key)
            if value is not None:
                self._map.move_to_end(key)
                telemetry.incr("commitment.cache_hits")
            return value

    def contains(self, key: bytes) -> bool:
        """Membership probe WITHOUT the hit counter or LRU refresh —
        prevalidation's dedup uses this so `commitment.cache_hits`
        keeps meaning "a validation skipped a recompute"."""
        with self._lock:
            return key in self._map

    def put(self, key: bytes, commitment: bytes) -> None:
        with self._lock:
            self._map[key] = commitment
            self._map.move_to_end(key)
            while len(self._map) > self.maxsize:
                self._map.popitem(last=False)
                self.evictions += 1
                telemetry.incr("commitment.cache_evictions")


def status_block(app) -> dict:
    """The admission/traffic block both HTTP status surfaces serve
    (/status and /consensus/status, FORMATS §12.3/§20.3): the verified-
    sig and verified-commitment cache economics plus any co-resident
    txsim load's counters. Counters are process-wide (exactly what
    /metrics exposes); the cache sizes are this App's."""
    counters = telemetry.snapshot().get("counters", {})

    def g(name: str) -> int:
        return counters.get(name, 0)

    sig_cache = getattr(app, "sig_cache", None)
    commitment_cache = getattr(app, "commitment_cache", None)
    return {
        "sig_cache_hits": g("admission.sig_cache_hits"),
        "sig_scalar_verified": g("admission.sig_scalar_verified"),
        "batch_verified": g("admission.batch_verified"),
        "batch_rejected": g("admission.batch_rejected"),
        "sig_cache_size": len(sig_cache) if sig_cache is not None else 0,
        "commitment": {
            "cache_hits": g("commitment.cache_hits"),
            "recomputes": g("commitment.recomputes"),
            "batch_dispatches": g("commitment.batch_dispatches"),
            "batch_lanes": g("commitment.batch_lanes"),
            "cache_size": (len(commitment_cache)
                           if commitment_cache is not None else 0),
        },
        "txsim": {
            "submitted": g("txsim.submitted"),
            "accepted": g("txsim.accepted"),
            "confirmed": g("txsim.confirmed"),
            "rejected": g("txsim.rejected"),
            "resyncs": g("txsim.resyncs"),
            "errors": g("txsim.errors"),
        },
    }


# sentinel for a raw whose BlobTx envelope failed to parse: both phase-1
# halves skip it (the ante rejects it with its own error later)
_UNDECODABLE = object()


def _unmarshal_batch(raws) -> list:
    """One envelope parse per raw, shared by both phase-1 halves (the
    sig half and the commitment half must not each pay a full BlobTx
    decode over devnet-scale blobs). Entries: a BlobTx, None (a plain
    tx), or _UNDECODABLE."""
    from celestia_app_tpu.da import blob as blob_mod

    out = []
    for raw in raws:
        try:
            out.append(blob_mod.try_unmarshal_blob_tx(raw))
        except ValueError:
            out.append(_UNDECODABLE)
    return out


def extract_blob_items(raws, btxs=None):
    """Every blob of every decodable BlobTx in `raws`, in block order.
    Undecodable entries are skipped — the ante/validate path remains the
    authority and rejects them with its own error. `btxs` optionally
    supplies the pre-parsed envelopes (_unmarshal_batch)."""
    if btxs is None:
        btxs = _unmarshal_batch(raws)
    blobs = []
    for btx in btxs:
        if btx is not None and btx is not _UNDECODABLE:
            blobs.extend(btx.blobs)
    return blobs


def prevalidate_commitments(app, raws, btxs=None) -> int:
    """Phase 1, commitment half: compute the share commitments of every
    pending blob not already in the App's verified-commitment cache in
    ONE batched dispatch (da/commitment_device via
    blob_validation.batch_commitments — device-class engines take the
    vmapped SHA-256 subtree-root MMR launch, host engines the host
    loop), and cache the results. Returns how many blobs were computed.
    Never raises and never rejects: a blob that skips the batch simply
    meets `validate_blob_tx`'s per-blob host compute later, with
    identical bytes (counted `commitment.recomputes`)."""
    from celestia_app_tpu import appconsts

    cache = getattr(app, "commitment_cache", None)
    if cache is None or not raws:
        return 0
    threshold = appconsts.subtree_root_threshold(app.app_version)
    pending = []
    keys = []
    seen: set[bytes] = set()
    for blob in extract_blob_items(raws, btxs=btxs):
        try:
            # stateless per-blob gate (share version, namespace, empty
            # data): an adversarial blob must not reach the batch
            # compute, where its malformed shape would throw the WHOLE
            # window's honest blobs back onto the per-tx host path —
            # the ante rejects the tx itself later with its own error
            blob.validate()
        except ValueError:
            continue
        key = commitment_key(blob.namespace.raw, blob.share_version,
                             blob.data, threshold)
        if key in seen or cache.contains(key):
            continue
        seen.add(key)
        pending.append(blob)
        keys.append(key)
    if not pending:
        return 0
    if len(pending) < MIN_DEVICE_BATCH:
        telemetry.incr("commitment.prevalidate_below_batch")
        return 0
    from celestia_app_tpu.chain import blob_validation

    try:
        commitments = blob_validation.batch_commitments(
            pending, threshold, engine=getattr(app, "engine", "host"))
    except Exception as e:
        # the per-blob host path in validate_blob_tx stays authoritative
        telemetry.incr("admission.prevalidate_errors")
        from celestia_app_tpu import obs

        obs.get_logger("chain.admission").error(
            "batch commitment prevalidation failed; per-blob host path "
            "takes over", err=e,
        )
        return 0
    telemetry.incr("commitment.batch_dispatches")
    telemetry.incr("commitment.batch_lanes", by=len(pending))
    for key, commitment in zip(keys, commitments):
        cache.put(key, commitment)
    return len(pending)


def extract_sig_item(app, raw: bytes, store=None, btx=_UNDECODABLE):
    """(pubkey, signature, sign-doc bytes) for one raw tx, or None when
    the tx cannot be prevalidated — undecodable, policy-rejected sig
    shape (non-64-byte or high-S, which `PublicKey.verify` refuses before
    any curve math), or a proto tx whose signer account does not exist
    yet (its sign doc needs the account number ensure_account will only
    assign inside the ante). None is never an error: the ante remains
    the authority and simply verifies those txs on its scalar path.
    `btx` optionally supplies the pre-parsed envelope (prevalidate's
    shared parse); the _UNDECODABLE default means "parse here"."""
    from celestia_app_tpu.chain.crypto import _N, PublicKey
    from celestia_app_tpu.chain.state import Context, InfiniteGasMeter
    from celestia_app_tpu.chain.tx import decode_tx
    from celestia_app_tpu.da import blob as blob_mod

    try:
        if btx is _UNDECODABLE:  # standalone call: parse the envelope
            btx = blob_mod.try_unmarshal_blob_tx(raw)
        tx = decode_tx(btx.tx if btx is not None else raw)
    except ValueError:
        return None
    sig = tx.signature
    if len(sig) != 64 or int.from_bytes(sig[32:], "big") > _N // 2:
        return None
    if getattr(tx, "wire_format", "native") == "proto":
        addr = PublicKey(tx.pubkey).address()
        ctx = Context(
            store if store is not None else app.store,
            InfiniteGasMeter(), app.height, 0,
            app.chain_id, app.app_version,
        )
        acc = app.auth.account(ctx, addr)
        if acc is None:
            return None
        doc = tx.sign_doc(app.chain_id, acc["number"])
    else:
        doc = tx.sign_doc()
    return (tx.pubkey, sig, doc)


def prevalidate(app, raws, *, check_state: bool = False,
                commitments: bool = True) -> int:
    """Phase 1: batch-verify the signatures of `raws` that are not
    already in the App's verified-sig cache, in one device dispatch, and
    cache the successes — and batch-compute the share commitments of
    their uncached blobs the same way (prevalidate_commitments).
    Returns how many signature lanes verified. Never raises and never
    rejects anything — a tx that fails (or skips) batch verification
    simply meets the ante's scalar verify later and fails THERE, with
    identical semantics.

    ``commitments=False`` skips the commitment half: ProcessProposal
    passes it because its own `resolve_commitments` already does ONE
    keyed pass through the cache (running both would hash every blob's
    bytes twice), and WAL replay passes it because delivery under a
    commit certificate validates no commitments at all."""
    if not raws:
        return 0
    btxs = _unmarshal_batch(raws)  # ONE envelope parse per raw, both halves
    # commitment half first (its own try: a commitment failure must not
    # cost the signature batch, and vice versa — both halves degrade
    # independently to their scalar/host paths)
    if commitments:
        try:
            prevalidate_commitments(app, raws, btxs=btxs)
        except Exception:
            telemetry.incr("admission.prevalidate_errors")
    cache = getattr(app, "sig_cache", None)
    if cache is None:
        return 0
    from celestia_app_tpu.ops import secp256k1 as fast

    store = None
    if check_state:
        # single read: a concurrent commit nulls app._check_state, and a
        # torn two-read would hand Context a None store. A stale branch
        # is harmless — the cache only stores state-independent facts.
        store = app._check_state
        if store is None:
            store = app.store
    items: list[tuple[bytes, bytes, bytes]] = []
    keys: list[bytes] = []
    seen: set[bytes] = set()
    for raw, btx in zip(raws, btxs):
        if btx is _UNDECODABLE:
            continue  # malformed envelope: the ante rejects it itself
        try:
            item = extract_sig_item(app, raw, store=store, btx=btx)
        except Exception:
            # prevalidation NEVER raises (callers may run it outside the
            # service lock, racing commits): an unexpected extraction
            # failure just leaves the tx to the ante's scalar path
            telemetry.incr("admission.prevalidate_errors")
            item = None
        if item is None:
            continue
        key = sig_key(*item)
        if key in seen or cache.contains(key):
            continue
        seen.add(key)
        items.append(item)
        keys.append(key)
    if not items:
        return 0
    if len(items) < MIN_DEVICE_BATCH or not fast.available():
        telemetry.incr("admission.prevalidate_below_batch")
        return 0
    try:
        mask = fast.verify_batch(items)
    except Exception as e:
        # the scalar path in the ante stays authoritative; count + log
        telemetry.incr("admission.prevalidate_errors")
        from celestia_app_tpu import obs

        obs.get_logger("chain.admission").error(
            "batch sig prevalidation failed; scalar path takes over",
            err=e,
        )
        return 0
    telemetry.incr("admission.batch_dispatches")
    telemetry.incr("admission.batch_lanes", by=len(items))
    verified = 0
    for ok, key in zip(mask, keys):
        if bool(ok):
            cache.put(key)
            verified += 1
        else:
            telemetry.incr("admission.batch_rejected")
    telemetry.incr("admission.batch_verified", by=verified)
    return verified
