"""The admission plane: two-phase tx admission with batched sig verification.

The per-user hot path used to pay one scalar secp256k1 verification per
transaction per phase — CheckTx at the mempool door, the PrepareProposal
ante filter, ProcessProposal on every validator, FinalizeBlock delivery,
and again on blocksync/WAL replay. This module restructures that into the
ROADMAP's two-phase admit:

  phase 1 (stateless): a whole batch of pending signatures is verified in
      ONE vmapped device dispatch (`ops/secp256k1.verify_batch`), and
      every success is recorded in the App's `VerifiedSigCache` keyed by
      the exact (pubkey, signature, sign-doc) triple;
  phase 2 (stateful): the ante chain runs per tx as before — nonce, fee,
      gas, blob gates — but its signature step consults the cache first,
      so a tx admitted at CheckTx is NEVER re-verified at proposal,
      delivery, or replay time.

The cache is sound by construction: a key is inserted only after the
signature verified TRUE over exactly the bytes the ante would verify, so
a hit can only skip a verification that would have returned True with
identical inputs. Consensus results are bit-identical with the cache on,
off, hot, or cold — prevalidation is an optimization plane, never an
authority, and any failure inside it degrades to the scalar path (counted
in telemetry, `admission.prevalidate_errors`).

Telemetry (the counters the tier-1 no-re-verification test pins):
  admission.sig_cache_hits       ante skipped a verify via the cache
  admission.sig_scalar_verified  ante ran a scalar verify (cache miss)
  admission.batch_dispatches     device batch dispatches
  admission.batch_lanes          signatures sent through the device path
  admission.batch_verified       lanes that verified and were cached
  admission.batch_rejected       lanes that failed batch verification
  admission.prevalidate_below_batch  batches too small for the device
"""

from __future__ import annotations

import hashlib
import os
import threading
from collections import OrderedDict

from celestia_app_tpu.utils import telemetry

# Below this many uncached signatures the device dispatch is not worth
# its padding (and, on first use in a process, its jit compile): the
# ante's scalar path fills the cache instead. Tests and the bench pin it
# via env to force either path.
MIN_DEVICE_BATCH = int(os.environ.get("CELESTIA_ADMISSION_MIN_BATCH", "16"))
SIG_CACHE_MAX = 65536


def sig_key(pubkey: bytes, signature: bytes, message: bytes) -> bytes:
    """The cache key: length-framed so no two distinct (pubkey, sig,
    sign-doc) triples can collide by concatenation ambiguity."""
    h = hashlib.sha256()
    for part in (pubkey, signature, message):
        h.update(len(part).to_bytes(4, "big"))
        h.update(part)
    return h.digest()


class VerifiedSigCache:
    """Bounded LRU set of signature triples that verified TRUE.

    Lives on the App (one per state machine); CheckTx, the proposal
    paths, and replay all share it. Entries are state-independent facts
    (pure curve math over fixed bytes), so the cache survives rollbacks
    and reloads untouched."""

    def __init__(self, maxsize: int = SIG_CACHE_MAX):
        self.maxsize = maxsize
        self._lock = threading.Lock()
        self._keys: OrderedDict[bytes, None] = OrderedDict()  # guarded-by: _lock

    key = staticmethod(sig_key)

    def __len__(self) -> int:
        with self._lock:
            return len(self._keys)

    def hit(self, key: bytes) -> bool:
        with self._lock:
            if key in self._keys:
                self._keys.move_to_end(key)
                telemetry.incr("admission.sig_cache_hits")
                return True
            return False

    def contains(self, key: bytes) -> bool:
        """Membership probe WITHOUT the hit counter or LRU refresh —
        prevalidation's dedup uses this so `admission.sig_cache_hits`
        keeps meaning "the ante skipped a verify"."""
        with self._lock:
            return key in self._keys

    def put(self, key: bytes) -> None:
        with self._lock:
            self._keys[key] = None
            self._keys.move_to_end(key)
            while len(self._keys) > self.maxsize:
                self._keys.popitem(last=False)


def extract_sig_item(app, raw: bytes, store=None):
    """(pubkey, signature, sign-doc bytes) for one raw tx, or None when
    the tx cannot be prevalidated — undecodable, policy-rejected sig
    shape (non-64-byte or high-S, which `PublicKey.verify` refuses before
    any curve math), or a proto tx whose signer account does not exist
    yet (its sign doc needs the account number ensure_account will only
    assign inside the ante). None is never an error: the ante remains
    the authority and simply verifies those txs on its scalar path."""
    from celestia_app_tpu.chain.crypto import _N, PublicKey
    from celestia_app_tpu.chain.state import Context, InfiniteGasMeter
    from celestia_app_tpu.chain.tx import decode_tx
    from celestia_app_tpu.da import blob as blob_mod

    try:
        btx = blob_mod.try_unmarshal_blob_tx(raw)
        tx = decode_tx(btx.tx if btx is not None else raw)
    except ValueError:
        return None
    sig = tx.signature
    if len(sig) != 64 or int.from_bytes(sig[32:], "big") > _N // 2:
        return None
    if getattr(tx, "wire_format", "native") == "proto":
        addr = PublicKey(tx.pubkey).address()
        ctx = Context(
            store if store is not None else app.store,
            InfiniteGasMeter(), app.height, 0,
            app.chain_id, app.app_version,
        )
        acc = app.auth.account(ctx, addr)
        if acc is None:
            return None
        doc = tx.sign_doc(app.chain_id, acc["number"])
    else:
        doc = tx.sign_doc()
    return (tx.pubkey, sig, doc)


def prevalidate(app, raws, *, check_state: bool = False) -> int:
    """Phase 1: batch-verify the signatures of `raws` that are not
    already in the App's verified-sig cache, in one device dispatch, and
    cache the successes. Returns how many lanes verified. Never raises
    and never rejects anything — a tx that fails (or skips) batch
    verification simply meets the ante's scalar verify later and fails
    THERE, with identical semantics."""
    cache = getattr(app, "sig_cache", None)
    if cache is None or not raws:
        return 0
    from celestia_app_tpu.ops import secp256k1 as fast

    store = None
    if check_state:
        # single read: a concurrent commit nulls app._check_state, and a
        # torn two-read would hand Context a None store. A stale branch
        # is harmless — the cache only stores state-independent facts.
        store = app._check_state
        if store is None:
            store = app.store
    items: list[tuple[bytes, bytes, bytes]] = []
    keys: list[bytes] = []
    seen: set[bytes] = set()
    for raw in raws:
        try:
            item = extract_sig_item(app, raw, store=store)
        except Exception:
            # prevalidation NEVER raises (callers may run it outside the
            # service lock, racing commits): an unexpected extraction
            # failure just leaves the tx to the ante's scalar path
            telemetry.incr("admission.prevalidate_errors")
            item = None
        if item is None:
            continue
        key = sig_key(*item)
        if key in seen or cache.contains(key):
            continue
        seen.add(key)
        items.append(item)
        keys.append(key)
    if not items:
        return 0
    if len(items) < MIN_DEVICE_BATCH or not fast.available():
        telemetry.incr("admission.prevalidate_below_batch")
        return 0
    try:
        mask = fast.verify_batch(items)
    except Exception as e:
        # the scalar path in the ante stays authoritative; count + log
        telemetry.incr("admission.prevalidate_errors")
        from celestia_app_tpu import obs

        obs.get_logger("chain.admission").error(
            "batch sig prevalidation failed; scalar path takes over",
            err=e,
        )
        return 0
    telemetry.incr("admission.batch_dispatches")
    telemetry.incr("admission.batch_lanes", by=len(items))
    verified = 0
    for ok, key in zip(mask, keys):
        if bool(ok):
            cache.put(key)
            verified += 1
        else:
            telemetry.incr("admission.batch_rejected")
    telemetry.incr("admission.batch_verified", by=verified)
    return verified
