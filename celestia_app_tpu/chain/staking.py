"""Full staking mechanics: validators, delegations, unbonding, redelegation.

Reference parity: the cosmos-sdk x/staking subset celestia-app wires through
its versioned module manager (app/app.go:262-277 — including registering the
blobstream hooks on validator lifecycle events) with celestia's parameters
(21-day unbonding, utia bond denom, power reduction 1e6). State layout:

  staking/val/<operator>            validator record (tokens, shares, status)
  staking/del/<operator>/<delegator>  delegation shares
  staking/ubd/<operator>/<delegator>  unbonding entries
                                      [{amount, completion, creation_height}]
  staking/red/<src><dst><delegator>   redelegation entries
                                      [{amount, completion, creation_height}]

All consensus-state arithmetic is FIXED-POINT INTEGER: shares are integer
"share units" (SHARE_SCALE units per utia at a 1:1 exchange rate), mirroring
the SDK's 18-decimal `sdk.Dec` shares. No float ever enters `put_json`
state, so a second implementation (e.g. the SURVEY §7.1.7 Go shim) can
reproduce the app hash bit-for-bit.

Semantics mirrored from the SDK keeper:
  - delegate: tokens -> shares at the validator's current exchange rate
    (shares/tokens, floor division); bonded tokens leave the delegator's
    balance.
  - undelegate: shares -> tokens enter the unbonding queue with the entry's
    creation height recorded (x/staking UnbondingDelegationEntry.CreationHeight);
    returned to the delegator once ctx.time passes completion (EndBlocker).
  - redelegate: instant move between validators, but TRACKED as a
    redelegation entry for MAX_ENTRIES limiting and destination slashing
    (x/staking Redelegation.Entries), and the source power drop fires the
    blobstream hook.
  - slash(fraction, infraction_height): burns `fraction` of bonded tokens;
    unbonding entries and redelegations are slashed ONLY if created at or
    after the infraction height (x/staking keeper/slash.go SlashUnbondingDelegation
    / SlashRedelegation — entries that predate the infraction are innocent).
    Redelegated stake is slashed at the DESTINATION validator, pro-rata in
    its shares. `infraction_height=None` (direct keeper calls/fixtures)
    slashes all live entries.
  - power = bonded_tokens // POWER_REDUCTION; power changes feed
    x/blobstream's SignificantPowerDiff valset cadence (abci.go:84-136) and
    x/signal tallies.

The genesis-style `set_validator(operator, power)` entry point is kept for
fixtures: it creates a validator with self-delegated tokens = power * 1e6.
"""

from __future__ import annotations

import json
from fractions import Fraction

from celestia_app_tpu import appconsts
from celestia_app_tpu.chain.state import Context, get_json, put_json

POWER_REDUCTION = 1_000_000  # utia per unit of consensus power (sdk default)
UNBONDING_TIME_SECONDS = 21 * 24 * 3600  # celestia mainnet: 21 days
MAX_ENTRIES = 7  # sdk default: simultaneous unbonding entries per pair

# Integer share units per utia at a 1:1 exchange rate (the SDK's sdk.Dec
# carries 18 decimals; 12 here keeps share*token products well inside int64
# ranges a native reimplementation would use, while leaving no observable
# rounding at utia granularity).
SHARE_SCALE = 10**12

BONDED_POOL = b"\x00" * 19 + b"\x02"  # module account holding bonded tokens
NOT_BONDED_POOL = b"\x00" * 19 + b"\x03"  # holds unbonding tokens


def _put(ctx, key: bytes, obj) -> None:
    put_json(ctx, key, obj)


def _get(ctx, key: bytes):
    return get_json(ctx, key)


def _as_fraction(fraction) -> Fraction:
    """Accept 0.5, (1, 4), or Fraction; floats go through str() so literals
    like 0.01 become exactly 1/100 (not the binary float's true value)."""
    if isinstance(fraction, Fraction):
        return fraction
    if isinstance(fraction, tuple):
        return Fraction(fraction[0], fraction[1])
    return Fraction(str(fraction))


class StakingKeeper:
    VAL = b"staking/val/"
    DEL = b"staking/del/"
    UBD = b"staking/ubd/"
    RED = b"staking/red/"
    PARAMS = b"staking/params"

    def __init__(self, bank=None):
        self.bank = bank  # optional: fixtures without balances skip transfers
        # hooks (blobstream registers here, app/app.go:271-277)
        self.hooks: list = []

    # -- params ---------------------------------------------------------

    def params(self, ctx: Context) -> dict:
        return _get(ctx, self.PARAMS) or {
            "unbonding_time": UNBONDING_TIME_SECONDS,
            "bond_denom": appconsts.BOND_DENOM,
            "max_entries": MAX_ENTRIES,
        }

    def set_params(self, ctx: Context, params: dict) -> None:
        _put(ctx, self.PARAMS, params)

    # -- validators -----------------------------------------------------

    def validator(self, ctx: Context, operator: bytes):
        return _get(ctx, self.VAL + operator)

    def _set_val(self, ctx: Context, operator: bytes, v: dict) -> None:
        _put(ctx, self.VAL + operator, v)

    def create_validator(
        self, ctx: Context, operator: bytes, self_stake: int,
        pubkey: bytes = b"",
    ) -> None:
        """MsgCreateValidator: operator self-delegates `self_stake` utia.

        `pubkey` (optional, 33-byte compressed consensus key — the
        reference MsgCreateValidator's Pubkey field) is recorded in the
        validator record so consensus can verify this validator's votes
        and include it in proposer rotation without it being in genesis
        (ValidatorNode.known_pubkeys / chain/reactor.py)."""
        if self.validator(ctx, operator) is not None:
            raise ValueError("validator already exists")
        if self_stake <= 0:
            raise ValueError("self stake must be positive")
        rec = {"tokens": 0, "shares": 0, "jailed": False, "bonded": True}
        if pubkey:
            rec["pubkey"] = pubkey.hex()
        self._set_val(ctx, operator, rec)
        for h in self.hooks:
            fn = getattr(h, "after_validator_created", None)
            if fn is not None:
                fn(ctx, operator)
        self.delegate(ctx, operator, operator, self_stake)

    def set_validator(self, ctx: Context, operator: bytes, power: int) -> None:
        """Genesis entry point: validator with `power` units of self-stake.

        The stake is MINTED (genesis staked supply is separate from genesis
        account balances, as in reference genesis files), not debited from
        the operator's spendable balance.
        """
        if self.validator(ctx, operator) is None:
            if self.bank is not None:
                self.bank.mint(ctx, operator, power * POWER_REDUCTION)
            self.create_validator(ctx, operator, power * POWER_REDUCTION)
        else:
            v = self.validator(ctx, operator)
            new_tokens = power * POWER_REDUCTION
            delta = new_tokens - v["tokens"]
            # scale shares with tokens so the exchange rate is preserved,
            # and keep the bonded pool + supply consistent via mint/burn
            if v["tokens"] > 0:
                v["shares"] = v["shares"] * new_tokens // v["tokens"]
            elif new_tokens > 0:
                v["shares"] = new_tokens * SHARE_SCALE
            v["tokens"] = new_tokens
            self._set_val(ctx, operator, v)
            if self.bank is not None:
                if delta > 0:
                    self.bank.mint(ctx, BONDED_POOL, delta)
                elif delta < 0:
                    self.bank.burn(ctx, BONDED_POOL, -delta)

    def validator_power(self, ctx: Context, operator: bytes) -> int:
        v = self.validator(ctx, operator)
        if v is None or v["jailed"] or not v["bonded"]:
            return 0
        return v["tokens"] // POWER_REDUCTION

    def total_power(self, ctx: Context) -> int:
        return sum(p for _, p in self.validators(ctx))

    def validators(self, ctx: Context) -> list[tuple[bytes, int]]:
        out = []
        for k, raw in ctx.store.iterate_prefix(self.VAL):
            op = k[len(self.VAL) :]
            p = self.validator_power(ctx, op)
            if p > 0:
                out.append((op, p))
        return out

    def consensus_pubkeys(self, ctx: Context) -> dict[bytes, bytes]:
        """operator -> consensus pubkey for every validator that registered
        one on-chain (MsgCreateValidator.pubkey). Genesis validators'
        pubkeys ride the genesis doc instead; consumers merge both
        (ValidatorNode.known_pubkeys)."""
        import json as json_mod

        out: dict[bytes, bytes] = {}
        for k, raw in ctx.store.iterate_prefix(self.VAL):
            v = json_mod.loads(raw)  # iterate yields the stored value —
            if v.get("pubkey"):      # no second keyed lookup per record
                out[k[len(self.VAL):]] = bytes.fromhex(v["pubkey"])
        return out

    # -- delegations ----------------------------------------------------

    def _del_key(self, operator: bytes, delegator: bytes) -> bytes:
        # addresses are fixed 20-byte strings; no separator (raw bytes may
        # contain any value, so a delimiter would be ambiguous)
        return self.DEL + operator + delegator

    def delegation(self, ctx: Context, operator: bytes, delegator: bytes) -> int:
        """Delegated shares in integer share units (SHARE_SCALE per utia at 1:1)."""
        return _get(ctx, self._del_key(operator, delegator)) or 0

    def delegations_of(self, ctx: Context, delegator: bytes):
        """[(operator, shares)] for one delegator (gov tally input)."""
        out = []
        for k, raw in ctx.store.iterate_prefix(self.DEL):
            rest = k[len(self.DEL) :]
            op, dl = rest[:20], rest[20:]
            if dl == delegator:
                out.append((op, json.loads(raw)))
        return out

    def shares_to_tokens(self, v: dict, shares: int) -> int:
        if v["shares"] == 0:
            return 0
        return shares * v["tokens"] // v["shares"]

    def _shares_from_tokens(self, v: dict, amount: int) -> int:
        """Shares worth `amount` utia at the current rate (floor, SDK
        SharesFromTokens)."""
        if v["shares"] == 0:
            return amount * SHARE_SCALE
        return amount * v["shares"] // v["tokens"]

    def delegate(
        self, ctx: Context, operator: bytes, delegator: bytes, amount: int
    ) -> None:
        v = self.validator(ctx, operator)
        if v is None:
            raise ValueError("unknown validator")
        if amount <= 0:
            raise ValueError("delegation must be positive")
        if self.bank is not None:
            self.bank.send(ctx, delegator, BONDED_POOL, amount)
        self._fire_delegation_hook(ctx, operator, delegator)
        # shares at current exchange rate (1:1 when no shares outstanding)
        new_shares = self._shares_from_tokens(v, amount)
        v["tokens"] += amount
        v["shares"] += new_shares
        self._set_val(ctx, operator, v)
        key = self._del_key(operator, delegator)
        _put(ctx, key, (self.delegation(ctx, operator, delegator)) + new_shares)
        ctx.emit_event("staking.delegate", validator=operator.hex(), amount=amount)

    def _unbond_shares(
        self, ctx: Context, operator: bytes, delegator: bytes, amount: int
    ) -> int:
        """Validate + compute the share cost of removing `amount` utia.

        A full exit (amount covering the delegation's whole token value)
        removes ALL held shares so no dust delegation records linger."""
        v = self.validator(ctx, operator)
        if v is None:
            raise ValueError("unknown validator")
        if amount <= 0:
            raise ValueError("amount must be positive")
        if v["tokens"] <= 0 or v["shares"] <= 0:
            raise ValueError("validator has no bonded tokens")
        shares_held = self.delegation(ctx, operator, delegator)
        max_tokens = self.shares_to_tokens(v, shares_held)
        if amount > max_tokens:
            raise ValueError("not enough delegated")
        if amount == max_tokens:
            return shares_held
        return self._shares_from_tokens(v, amount)

    def undelegate(
        self, ctx: Context, operator: bytes, delegator: bytes, amount: int
    ) -> int:
        """Begin unbonding `amount` utia; returns completion time."""
        shares_needed = self._unbond_shares(ctx, operator, delegator, amount)
        ubd_key = self.UBD + operator + delegator
        entries = _get(ctx, ubd_key) or []
        if len(entries) >= self.params(ctx)["max_entries"]:
            raise ValueError("too many unbonding entries")
        completion = int(ctx.time_unix) + self.params(ctx)["unbonding_time"]
        entries.append(
            {
                "amount": amount,
                "completion": completion,
                "creation_height": ctx.height,
            }
        )
        _put(ctx, ubd_key, entries)
        self._remove_shares(ctx, operator, delegator, shares_needed, amount)
        if self.bank is not None:
            self.bank.send(ctx, BONDED_POOL, NOT_BONDED_POOL, amount)
        for h in self.hooks:
            fn = getattr(h, "after_validator_begin_unbonding", None)
            if fn is not None:
                fn(ctx)
        ctx.emit_event(
            "staking.unbond",
            validator=operator.hex(),
            amount=amount,
            completion=completion,
        )
        return completion

    def _red_key(self, src: bytes, dst: bytes, delegator: bytes) -> bytes:
        return self.RED + src + dst + delegator

    def redelegate(
        self,
        ctx: Context,
        src: bytes,
        dst: bytes,
        delegator: bytes,
        amount: int,
    ) -> None:
        """Instant move src -> dst (no unbonding wait), tracked as a
        redelegation entry so a later slash of src for an infraction that
        predates the move still reaches the stake at dst (SlashRedelegation)."""
        v_dst = self.validator(ctx, dst)
        if v_dst is None:
            raise ValueError("unknown validator")
        shares_needed = self._unbond_shares(ctx, src, delegator, amount)
        red_key = self._red_key(src, dst, delegator)
        red_entries = _get(ctx, red_key) or []
        if len(red_entries) >= self.params(ctx)["max_entries"]:
            raise ValueError("too many redelegation entries")
        self._remove_shares(ctx, src, delegator, shares_needed, amount)
        # credit dst at its exchange rate
        self._fire_delegation_hook(ctx, dst, delegator)
        v_dst = self.validator(ctx, dst)
        new_shares = self._shares_from_tokens(v_dst, amount)
        v_dst["tokens"] += amount
        v_dst["shares"] += new_shares
        self._set_val(ctx, dst, v_dst)
        key = self._del_key(dst, delegator)
        _put(ctx, key, self.delegation(ctx, dst, delegator) + new_shares)
        red_entries.append(
            {
                "amount": amount,
                "completion": int(ctx.time_unix) + self.params(ctx)["unbonding_time"],
                "creation_height": ctx.height,
            }
        )
        _put(ctx, red_key, red_entries)
        # source power dropped: same hook the reference fires on redelegations
        for h in self.hooks:
            fn = getattr(h, "after_validator_begin_unbonding", None)
            if fn is not None:
                fn(ctx)

    def _fire_delegation_hook(self, ctx: Context, operator: bytes, delegator: bytes) -> None:
        for h in self.hooks:
            fn = getattr(h, "before_delegation_modified", None)
            if fn is not None:
                fn(ctx, operator, delegator)

    def _remove_shares(
        self, ctx: Context, operator: bytes, delegator: bytes,
        shares: int, tokens: int,
    ) -> None:
        self._fire_delegation_hook(ctx, operator, delegator)
        v = self.validator(ctx, operator)
        key = self._del_key(operator, delegator)
        remaining = self.delegation(ctx, operator, delegator) - shares
        if remaining <= 0:
            ctx.store.delete(key)
        else:
            _put(ctx, key, remaining)
        v["tokens"] -= tokens
        v["shares"] -= shares
        if v["shares"] <= 0:
            v["shares"] = 0
            v["tokens"] = max(v["tokens"], 0)
        self._set_val(ctx, operator, v)

    # -- unbonding queue / slashing -------------------------------------

    def begin_unbonding(self, ctx: Context, operator: bytes) -> None:
        """Whole-validator exit (legacy fixture API): undelegate everything."""
        v = self.validator(ctx, operator)
        if v is None:
            raise ValueError("unknown validator")
        ctx.store.delete(self.VAL + operator)
        for k, _ in list(ctx.store.iterate_prefix(self.DEL + operator)):
            ctx.store.delete(k)
        for h in self.hooks:
            fn = getattr(h, "after_validator_begin_unbonding", None)
            if fn is not None:
                fn(ctx)

    def slash(
        self,
        ctx: Context,
        operator: bytes,
        fraction,
        infraction_height: int | None = None,
    ) -> int:
        """Burn `fraction` of the validator's bonded tokens, of unbonding
        entries created at/after the infraction, and of stake redelegated
        away at/after the infraction (slashed at the destination validator,
        x/staking keeper/slash.go) — then jail the validator.

        `fraction` may be a float literal (0.01 → exactly 1/100), a
        (num, den) tuple, or a Fraction. `infraction_height=None` slashes
        every live entry (fixture/legacy behavior)."""
        frac = _as_fraction(fraction)
        num, den = frac.numerator, frac.denominator
        v = self.validator(ctx, operator)
        if v is None:
            raise ValueError("unknown validator")
        burned = v["tokens"] * num // den
        v["tokens"] -= burned
        v["jailed"] = True
        self._set_val(ctx, operator, v)
        if self.bank is not None and burned > 0:
            self.bank.burn(ctx, BONDED_POOL, burned)
        # unbonding entries: only those created at/after the infraction
        # (x/staking SlashUnbondingDelegation — older entries were already
        # out when the offense happened)
        for k, raw in list(ctx.store.iterate_prefix(self.UBD + operator)):
            entries = json.loads(raw)
            for e in entries:
                if (
                    infraction_height is not None
                    and e.get("creation_height", 0) < infraction_height
                ):
                    continue
                cut = e["amount"] * num // den
                e["amount"] -= cut
                burned += cut
                if self.bank is not None and cut > 0:
                    self.bank.burn(ctx, NOT_BONDED_POOL, cut)
            _put(ctx, k, entries)
        # redelegations out of this validator: slash the moved stake at its
        # destination (SlashRedelegation), pro-rata in dst shares
        for k, raw in list(ctx.store.iterate_prefix(self.RED + operator)):
            rest = k[len(self.RED) + len(operator) :]
            dst, delegator = rest[:20], rest[20:]
            entries = json.loads(raw)
            changed = False
            for e in entries:
                if (
                    infraction_height is not None
                    and e.get("creation_height", 0) < infraction_height
                ):
                    continue
                cut = e["amount"] * num // den
                if cut <= 0:
                    continue
                cut = self._slash_at_destination(ctx, dst, delegator, cut)
                e["amount"] -= cut
                burned += cut
                changed = True
            if changed:
                _put(ctx, k, entries)
        for h in self.hooks:
            fn = getattr(h, "after_validator_begin_unbonding", None)
            if fn is not None:
                fn(ctx)
        ctx.emit_event("staking.slash", validator=operator.hex(), burned=burned)
        return burned

    def _slash_at_destination(
        self, ctx: Context, dst: bytes, delegator: bytes, cut: int
    ) -> int:
        """Burn up to `cut` utia of `delegator`'s stake at validator `dst`;
        returns the amount actually burned (bounded by what is still there)."""
        v = self.validator(ctx, dst)
        if v is None or v["tokens"] <= 0 or v["shares"] <= 0:
            return 0
        shares_held = self.delegation(ctx, dst, delegator)
        if shares_held <= 0:
            return 0
        max_tokens = self.shares_to_tokens(v, shares_held)
        cut = min(cut, max_tokens)
        if cut <= 0:
            return 0
        shares_cut = (
            shares_held if cut == max_tokens else self._shares_from_tokens(v, cut)
        )
        self._remove_shares(ctx, dst, delegator, shares_cut, cut)
        if self.bank is not None:
            self.bank.burn(ctx, BONDED_POOL, cut)
        return cut

    def unjail(self, ctx: Context, operator: bytes) -> None:
        v = self.validator(ctx, operator)
        if v is None:
            raise ValueError("unknown validator")
        v["jailed"] = False
        self._set_val(ctx, operator, v)

    def end_blocker(self, ctx: Context) -> list[tuple[bytes, int]]:
        """Mature unbonding entries whose completion time has passed, and
        prune matured redelegation entries (their slash window closed)."""
        released = []
        for k, raw in list(ctx.store.iterate_prefix(self.UBD)):
            entries = json.loads(raw)
            rest = k[len(self.UBD) :]
            _op, delegator = rest[:20], rest[20:]
            keep = []
            for e in entries:
                if e["completion"] <= ctx.time_unix:
                    if self.bank is not None:
                        self.bank.send(ctx, NOT_BONDED_POOL, delegator, e["amount"])
                    released.append((delegator, e["amount"]))
                else:
                    keep.append(e)
            if keep:
                _put(ctx, k, keep)
            else:
                ctx.store.delete(k)
        for k, raw in list(ctx.store.iterate_prefix(self.RED)):
            keep = [e for e in json.loads(raw) if e["completion"] > ctx.time_unix]
            if keep:
                _put(ctx, k, keep)
            else:
                ctx.store.delete(k)
        return released
