"""Full staking mechanics: validators, delegations, unbonding, redelegation.

Reference parity: the cosmos-sdk x/staking subset celestia-app wires through
its versioned module manager (app/app.go:262-277 — including registering the
blobstream hooks on validator lifecycle events) with celestia's parameters
(21-day unbonding, utia bond denom, power reduction 1e6). State layout:

  staking/val/<operator>            validator record (tokens, shares, status)
  staking/del/<operator>/<delegator>  delegation shares
  staking/ubd/<operator>/<delegator>  unbonding entries [{amount, completion}]
  staking/red/...                     redelegation entries

Semantics mirrored from the SDK keeper:
  - delegate: tokens -> shares at the validator's current exchange rate
    (tokens/delegator_shares); bonded tokens leave the delegator's balance.
  - undelegate: shares -> tokens enter the unbonding queue; returned to the
    delegator's balance once ctx.time passes completion (EndBlocker).
  - redelegate: instant move between validators (no unbonding wait, but
    tracked so the source validator's power drop fires the blobstream hook).
  - slash: burns a fraction of tokens (and pro-rata from unbonding entries),
    jails the validator.
  - power = bonded_tokens // POWER_REDUCTION; power changes feed
    x/blobstream's SignificantPowerDiff valset cadence (abci.go:84-136) and
    x/signal tallies.

The genesis-style `set_validator(operator, power)` entry point is kept for
fixtures: it creates a validator with self-delegated tokens = power * 1e6.
"""

from __future__ import annotations

import json

from celestia_app_tpu import appconsts
from celestia_app_tpu.chain.state import Context, get_json, put_json

POWER_REDUCTION = 1_000_000  # utia per unit of consensus power (sdk default)
UNBONDING_TIME_SECONDS = 21 * 24 * 3600  # celestia mainnet: 21 days
MAX_ENTRIES = 7  # sdk default: simultaneous unbonding entries per pair

BONDED_POOL = b"\x00" * 19 + b"\x02"  # module account holding bonded tokens
NOT_BONDED_POOL = b"\x00" * 19 + b"\x03"  # holds unbonding tokens


def _put(ctx, key: bytes, obj) -> None:
    put_json(ctx, key, obj)


def _get(ctx, key: bytes):
    return get_json(ctx, key)


class StakingKeeper:
    VAL = b"staking/val/"
    DEL = b"staking/del/"
    UBD = b"staking/ubd/"
    PARAMS = b"staking/params"

    def __init__(self, bank=None):
        self.bank = bank  # optional: fixtures without balances skip transfers
        # hooks (blobstream registers here, app/app.go:271-277)
        self.hooks: list = []

    # -- params ---------------------------------------------------------

    def params(self, ctx: Context) -> dict:
        return _get(ctx, self.PARAMS) or {
            "unbonding_time": UNBONDING_TIME_SECONDS,
            "bond_denom": appconsts.BOND_DENOM,
            "max_entries": MAX_ENTRIES,
        }

    def set_params(self, ctx: Context, params: dict) -> None:
        _put(ctx, self.PARAMS, params)

    # -- validators -----------------------------------------------------

    def validator(self, ctx: Context, operator: bytes):
        return _get(ctx, self.VAL + operator)

    def _set_val(self, ctx: Context, operator: bytes, v: dict) -> None:
        _put(ctx, self.VAL + operator, v)

    def create_validator(
        self, ctx: Context, operator: bytes, self_stake: int
    ) -> None:
        """MsgCreateValidator: operator self-delegates `self_stake` utia."""
        if self.validator(ctx, operator) is not None:
            raise ValueError("validator already exists")
        if self_stake <= 0:
            raise ValueError("self stake must be positive")
        self._set_val(
            ctx,
            operator,
            {"tokens": 0, "shares": 0.0, "jailed": False, "bonded": True},
        )
        for h in self.hooks:
            fn = getattr(h, "after_validator_created", None)
            if fn is not None:
                fn(ctx, operator)
        self.delegate(ctx, operator, operator, self_stake)

    def set_validator(self, ctx: Context, operator: bytes, power: int) -> None:
        """Genesis entry point: validator with `power` units of self-stake.

        The stake is MINTED (genesis staked supply is separate from genesis
        account balances, as in reference genesis files), not debited from
        the operator's spendable balance.
        """
        if self.validator(ctx, operator) is None:
            if self.bank is not None:
                self.bank.mint(ctx, operator, power * POWER_REDUCTION)
            self.create_validator(ctx, operator, power * POWER_REDUCTION)
        else:
            v = self.validator(ctx, operator)
            new_tokens = power * POWER_REDUCTION
            delta = new_tokens - v["tokens"]
            # scale shares with tokens so the exchange rate is preserved,
            # and keep the bonded pool + supply consistent via mint/burn
            if v["tokens"] > 0:
                v["shares"] *= new_tokens / v["tokens"]
            elif new_tokens > 0:
                v["shares"] = float(new_tokens)
            v["tokens"] = new_tokens
            self._set_val(ctx, operator, v)
            if self.bank is not None:
                if delta > 0:
                    self.bank.mint(ctx, BONDED_POOL, delta)
                elif delta < 0:
                    self.bank.burn(ctx, BONDED_POOL, -delta)

    def validator_power(self, ctx: Context, operator: bytes) -> int:
        v = self.validator(ctx, operator)
        if v is None or v["jailed"] or not v["bonded"]:
            return 0
        return v["tokens"] // POWER_REDUCTION

    def total_power(self, ctx: Context) -> int:
        return sum(p for _, p in self.validators(ctx))

    def validators(self, ctx: Context) -> list[tuple[bytes, int]]:
        out = []
        for k, raw in ctx.store.iterate_prefix(self.VAL):
            op = k[len(self.VAL) :]
            p = self.validator_power(ctx, op)
            if p > 0:
                out.append((op, p))
        return out

    # -- delegations ----------------------------------------------------

    def _del_key(self, operator: bytes, delegator: bytes) -> bytes:
        # addresses are fixed 20-byte strings; no separator (raw bytes may
        # contain any value, so a delimiter would be ambiguous)
        return self.DEL + operator + delegator

    def delegation(self, ctx: Context, operator: bytes, delegator: bytes) -> float:
        return _get(ctx, self._del_key(operator, delegator)) or 0.0

    def delegations_of(self, ctx: Context, delegator: bytes):
        """[(operator, shares)] for one delegator (gov tally input)."""
        out = []
        for k, raw in ctx.store.iterate_prefix(self.DEL):
            rest = k[len(self.DEL) :]
            op, dl = rest[:20], rest[20:]
            if dl == delegator:
                out.append((op, json.loads(raw)))
        return out

    def shares_to_tokens(self, v: dict, shares: float) -> int:
        if v["shares"] == 0:
            return 0
        return int(shares * v["tokens"] / v["shares"])

    def delegate(
        self, ctx: Context, operator: bytes, delegator: bytes, amount: int
    ) -> None:
        v = self.validator(ctx, operator)
        if v is None:
            raise ValueError("unknown validator")
        if amount <= 0:
            raise ValueError("delegation must be positive")
        if self.bank is not None:
            self.bank.send(ctx, delegator, BONDED_POOL, amount)
        self._fire_delegation_hook(ctx, operator, delegator)
        # shares at current exchange rate (1:1 when no shares outstanding)
        new_shares = (
            float(amount)
            if v["shares"] == 0
            else amount * v["shares"] / v["tokens"]
        )
        v["tokens"] += amount
        v["shares"] += new_shares
        self._set_val(ctx, operator, v)
        key = self._del_key(operator, delegator)
        _put(ctx, key, (self.delegation(ctx, operator, delegator)) + new_shares)
        ctx.emit_event("staking.delegate", validator=operator.hex(), amount=amount)

    def undelegate(
        self, ctx: Context, operator: bytes, delegator: bytes, amount: int
    ) -> float:
        """Begin unbonding `amount` utia; returns completion time."""
        v = self.validator(ctx, operator)
        if v is None:
            raise ValueError("unknown validator")
        if amount <= 0:
            raise ValueError("amount must be positive")
        if v["tokens"] <= 0 or v["shares"] <= 0:
            raise ValueError("validator has no bonded tokens")
        shares_held = self.delegation(ctx, operator, delegator)
        shares_needed = amount * v["shares"] / v["tokens"]
        if shares_needed > shares_held * (1 + 1e-12):
            raise ValueError("not enough delegated")
        ubd_key = self.UBD + operator + delegator
        entries = _get(ctx, ubd_key) or []
        if len(entries) >= self.params(ctx)["max_entries"]:
            raise ValueError("too many unbonding entries")
        completion = ctx.time_unix + self.params(ctx)["unbonding_time"]
        entries.append({"amount": amount, "completion": completion})
        _put(ctx, ubd_key, entries)
        self._remove_shares(ctx, operator, delegator, shares_needed, amount)
        if self.bank is not None:
            self.bank.send(ctx, BONDED_POOL, NOT_BONDED_POOL, amount)
        for h in self.hooks:
            fn = getattr(h, "after_validator_begin_unbonding", None)
            if fn is not None:
                fn(ctx)
        ctx.emit_event(
            "staking.unbond",
            validator=operator.hex(),
            amount=amount,
            completion=completion,
        )
        return completion

    def redelegate(
        self,
        ctx: Context,
        src: bytes,
        dst: bytes,
        delegator: bytes,
        amount: int,
    ) -> None:
        """Instant move src -> dst (sdk allows without unbonding wait)."""
        v_src = self.validator(ctx, src)
        v_dst = self.validator(ctx, dst)
        if v_src is None or v_dst is None:
            raise ValueError("unknown validator")
        if amount <= 0:
            raise ValueError("amount must be positive")
        if v_src["tokens"] <= 0 or v_src["shares"] <= 0:
            raise ValueError("source validator has no bonded tokens")
        shares_needed = amount * v_src["shares"] / v_src["tokens"]
        if shares_needed > self.delegation(ctx, src, delegator) * (1 + 1e-12):
            raise ValueError("not enough delegated")
        self._remove_shares(ctx, src, delegator, shares_needed, amount)
        # credit dst at its exchange rate
        self._fire_delegation_hook(ctx, dst, delegator)
        v_dst = self.validator(ctx, dst)
        new_shares = (
            float(amount)
            if v_dst["shares"] == 0
            else amount * v_dst["shares"] / v_dst["tokens"]
        )
        v_dst["tokens"] += amount
        v_dst["shares"] += new_shares
        self._set_val(ctx, dst, v_dst)
        key = self._del_key(dst, delegator)
        _put(ctx, key, self.delegation(ctx, dst, delegator) + new_shares)
        # source power dropped: same hook the reference fires on redelegations
        for h in self.hooks:
            fn = getattr(h, "after_validator_begin_unbonding", None)
            if fn is not None:
                fn(ctx)

    def _fire_delegation_hook(self, ctx: Context, operator: bytes, delegator: bytes) -> None:
        for h in self.hooks:
            fn = getattr(h, "before_delegation_modified", None)
            if fn is not None:
                fn(ctx, operator, delegator)

    def _remove_shares(
        self, ctx: Context, operator: bytes, delegator: bytes,
        shares: float, tokens: int,
    ) -> None:
        self._fire_delegation_hook(ctx, operator, delegator)
        v = self.validator(ctx, operator)
        key = self._del_key(operator, delegator)
        remaining = self.delegation(ctx, operator, delegator) - shares
        if remaining < 1e-9:
            ctx.store.delete(key)
        else:
            _put(ctx, key, remaining)
        v["tokens"] -= tokens
        v["shares"] -= shares
        if v["shares"] < 1e-9:
            v["shares"] = 0.0
            v["tokens"] = max(v["tokens"], 0)
        self._set_val(ctx, operator, v)

    # -- unbonding queue / slashing -------------------------------------

    def begin_unbonding(self, ctx: Context, operator: bytes) -> None:
        """Whole-validator exit (legacy fixture API): undelegate everything."""
        v = self.validator(ctx, operator)
        if v is None:
            raise ValueError("unknown validator")
        ctx.store.delete(self.VAL + operator)
        for k, _ in list(ctx.store.iterate_prefix(self.DEL + operator)):
            ctx.store.delete(k)
        for h in self.hooks:
            fn = getattr(h, "after_validator_begin_unbonding", None)
            if fn is not None:
                fn(ctx)

    def slash(self, ctx: Context, operator: bytes, fraction: float) -> int:
        """Burn `fraction` of the validator's bonded tokens AND of its
        pending unbonding entries (the SDK slashes both so undelegating
        cannot front-run a slash), then jail it."""
        v = self.validator(ctx, operator)
        if v is None:
            raise ValueError("unknown validator")
        burned = int(v["tokens"] * fraction)
        v["tokens"] -= burned
        v["jailed"] = True
        self._set_val(ctx, operator, v)
        if self.bank is not None and burned > 0:
            self.bank.burn(ctx, BONDED_POOL, burned)
        for k, raw in list(ctx.store.iterate_prefix(self.UBD + operator)):
            entries = json.loads(raw)
            for e in entries:
                cut = int(e["amount"] * fraction)
                e["amount"] -= cut
                burned += cut
                if self.bank is not None and cut > 0:
                    self.bank.burn(ctx, NOT_BONDED_POOL, cut)
            _put(ctx, k, entries)
        for h in self.hooks:
            fn = getattr(h, "after_validator_begin_unbonding", None)
            if fn is not None:
                fn(ctx)
        ctx.emit_event("staking.slash", validator=operator.hex(), burned=burned)
        return burned

    def unjail(self, ctx: Context, operator: bytes) -> None:
        v = self.validator(ctx, operator)
        if v is None:
            raise ValueError("unknown validator")
        v["jailed"] = False
        self._set_val(ctx, operator, v)

    def end_blocker(self, ctx: Context) -> list[tuple[bytes, int]]:
        """Mature unbonding entries whose completion time has passed."""
        released = []
        for k, raw in list(ctx.store.iterate_prefix(self.UBD)):
            entries = json.loads(raw)
            rest = k[len(self.UBD) :]
            _op, delegator = rest[:20], rest[20:]
            keep = []
            for e in entries:
                if e["completion"] <= ctx.time_unix:
                    if self.bank is not None:
                        self.bank.send(ctx, NOT_BONDED_POOL, delegator, e["amount"])
                    released.append((delegator, e["amount"]))
                else:
                    keep.append(e)
            if keep:
                _put(ctx, k, keep)
            else:
                ctx.store.delete(k)
        return released
