"""Light client: verify a chain of headers from commit certificates alone.

Reference parity: celestia-core's `light` package (Tendermint light client
— SURVEY §1 L1). A light client holds a trusted (height, validator set)
and accepts new headers without executing any transactions:

- **sequential / same-valset**: the certificate must carry >2/3 of the
  TRUSTED set's power over the new header's hash.
- **valset change (skipping trust, Tendermint's 1/3 rule)**: the caller
  supplies the candidate new set; it must hash to the new header's
  `validators_hash` commitment, every pubkey must derive its operator
  address (addresses ARE pubkey hashes, chain/crypto.py), the certificate
  must carry >2/3 of the NEW set — and, to prevent long-range forks,
  >1/3 of the TRUSTED set's power must also have signed.

Headers commit to their (operator, power) set via `validators_hash`
(chain/block.validators_hash_of); every full node's ProcessProposal
recomputes and enforces it, so the commitment a light client sees is the
one consensus agreed on. The IBC verifying client (chain/ibc.py) is the
packet-plane consumer of the same certificate verification; this module
adds header-chain FOLLOWING with valset transitions.
"""

from __future__ import annotations

import dataclasses

from celestia_app_tpu.chain.block import Header, validators_hash_of
from celestia_app_tpu.chain.consensus import CommitCertificate
from celestia_app_tpu.chain.crypto import PublicKey


class LightClientError(ValueError):
    pass


@dataclasses.dataclass
class TrustedState:
    height: int
    header_hash: bytes
    validators: dict[bytes, bytes]  # operator address -> 33-byte pubkey
    powers: dict[bytes, int]


class LightClient:
    """Follows headers by certificate verification only."""

    def __init__(self, chain_id: str, trusted: TrustedState,
                 check_set: bool = True):
        """`check_set=False` skips re-validating the trusted set's
        pubkey/address derivations — for callers restoring a set THEY
        validated and persisted (the IBC client keeper's per-update
        reconstruction), not for fresh external input."""
        self.chain_id = chain_id
        self.trusted = trusted
        # data roots condemned by verified bad-encoding fraud proofs:
        # headers carrying them are refused even with a valid certificate
        # (>2/3 of validators signing a non-codeword IS the fraud-proof
        # threat model — specs fraud_proofs.md)
        self.condemned_roots: set[bytes] = set()
        if check_set:
            self._check_set(trusted.validators, trusted.powers)

    def submit_fraud_proof(self, commitments, proof) -> bool:
        """A gossiped incorrect-coding fraud proof against a block's DA
        commitments. The proof's TYPE selects the codec through the
        registry (each codec declares its ``fraud_proof_type`` —
        da/codec.py), so a new scheme's proofs are dispatchable by
        registration alone. If it VERIFIES — the committed roots carry
        an invalid codeword — the data root (``commitments.hash()``,
        whichever scheme) is condemned and any header carrying it will
        be refused. Returns whether the proof checked out."""
        from celestia_app_tpu.da import codec as dacodec

        codec = None
        for sid in dacodec.registered_ids():
            candidate = dacodec.by_id(sid)
            try:
                proof_type = candidate.fraud_proof_type()
            except NotImplementedError:
                continue
            if isinstance(proof, proof_type):
                codec = candidate
                break
        if codec is None:
            return False
        try:
            ok = codec.verify_fraud_proof(commitments, proof)
        except Exception:
            # untrusted input end to end: a proof whose type does not
            # match the commitments' scheme (e.g. a BEFP against
            # CmtCommitments) must be refused, never escape — this API
            # promises a bool verdict on gossip
            from celestia_app_tpu.utils import telemetry

            telemetry.incr("light.malformed_fraud_proofs")
            return False
        if not ok:
            return False
        self.condemned_roots.add(commitments.hash())
        return True

    @staticmethod
    def _check_set(validators: dict[bytes, bytes],
                   powers: dict[bytes, int]) -> None:
        for op, pub in validators.items():
            if PublicKey(pub).address() != op:
                raise LightClientError(
                    f"pubkey does not derive operator {op.hex()[:12]}"
                )
        if set(validators) != set(powers):
            raise LightClientError("validator/power key sets differ")

    def _signed_power(self, cert: CommitCertificate,
                      validators: dict[bytes, bytes],
                      powers: dict[bytes, int]) -> int:
        # the ONE vote-counting implementation (CommitCertificate)
        return cert.signed_power(self.chain_id, validators, powers)

    def update(
        self,
        header: Header,
        cert: CommitCertificate,
        new_validators: dict[bytes, bytes] | None = None,
        new_powers: dict[bytes, int] | None = None,
    ) -> TrustedState:
        """Advance trust to `header`. Raises LightClientError on any
        verification failure; on success the new state is adopted AND
        returned."""
        if header.chain_id != self.chain_id:
            raise LightClientError("wrong chain id")
        if header.height <= self.trusted.height:
            raise LightClientError(
                f"non-monotonic header: {header.height} <= {self.trusted.height}"
            )
        if cert.height != header.height or cert.block_hash != header.hash():
            raise LightClientError("certificate does not cover this header")
        if header.data_hash in self.condemned_roots:
            raise LightClientError(
                "header carries a data root condemned by a fraud proof"
            )
        # sequential hash-linkage: an adjacent header must chain to the
        # trusted one (skipping updates have no such check — the overlap
        # rule carries trust across the gap)
        if (header.height == self.trusted.height + 1
                and self.trusted.header_hash
                and header.last_block_hash != self.trusted.header_hash):
            raise LightClientError(
                "adjacent header does not chain to the trusted header"
            )

        if new_validators is None:
            # same-valset path: the header must still commit to the
            # trusted set, and >2/3 of it must have signed
            want = validators_hash_of(
                [(op, p) for op, p in self.trusted.powers.items()]
            )
            if header.validators_hash != want:
                raise LightClientError(
                    "validator set changed: supply the new set"
                )
            total = sum(self.trusted.powers.values())
            if self._signed_power(
                cert, self.trusted.validators, self.trusted.powers
            ) * 3 <= total * 2:
                raise LightClientError("certificate below 2/3 of trusted power")
            vals, powers = self.trusted.validators, self.trusted.powers
        else:
            if new_powers is None:
                raise LightClientError("new validator set needs powers")
            self._check_set(new_validators, new_powers)
            # the candidate set must BE the one the header commits to
            want = validators_hash_of(list(new_powers.items()))
            if header.validators_hash != want:
                raise LightClientError(
                    "candidate set does not match the header's commitment"
                )
            new_total = sum(new_powers.values())
            if self._signed_power(
                cert, new_validators, new_powers
            ) * 3 <= new_total * 2:
                raise LightClientError("certificate below 2/3 of new power")
            # Tendermint skipping-trust overlap: >1/3 of the TRUSTED set
            # must also have signed, or a long-gone valset could fork us
            old_total = sum(self.trusted.powers.values())
            if self._signed_power(
                cert, self.trusted.validators, self.trusted.powers
            ) * 3 <= old_total:
                raise LightClientError(
                    "certificate below 1/3 of trusted power (no overlap)"
                )
            vals, powers = new_validators, new_powers

        self.trusted = TrustedState(
            height=header.height,
            header_hash=header.hash(),
            validators=dict(vals),
            powers=dict(powers),
        )
        return self.trusted
