"""Governance: param-change proposals with celestia's paramfilter blocklist.

Reference parity:
  - cosmos-sdk x/gov v1 lifecycle (submit -> deposit period -> voting period
    -> tally -> execute/reject) with celestia's overrides
    (app/default_overrides.go:207-227: MinDeposit 10_000 TIA, one-week
    voting period — scaled here in seconds).
  - x/paramfilter (x/paramfilter/gov_handler.go:17-60 + the blocked-param
    list wired at app/app.go:739-773): proposals that touch consensus-
    critical params are rejected at execution, so "governance" can never
    flip them without a hard fork.

Tally (gov keeper semantics): validators vote with their full power;
delegators who vote override their share of their validator's vote.
Outcomes per the SDK: quorum 1/3 of bonded power must vote, else rejected;
veto >= 1/3 of votes rejects (deposit burned); yes > 1/2 of non-abstain
passes.

Param routing: targets are "<module>/<key>" strings applied through the
keepers (blob params, minfee floor, blobstream window, gov's own params,
staking params). The BLOCKED set mirrors the reference's forbidden params.
"""

from __future__ import annotations

import json

from celestia_app_tpu import obs
from celestia_app_tpu.chain.state import Context, get_json, put_json

log = obs.get_logger("chain.gov")

# celestia mainnet-flavored defaults (scaled: periods in seconds)
DEFAULT_MIN_DEPOSIT = 10_000_000_000  # 10,000 TIA in utia
DEFAULT_MAX_DEPOSIT_PERIOD = 7 * 24 * 3600
DEFAULT_VOTING_PERIOD = 7 * 24 * 3600
# Exact rationals (num, den): ratio tests are integer cross-multiplications
# so no float ever decides a consensus outcome.
QUORUM = (1, 3)
THRESHOLD = (1, 2)
VETO_THRESHOLD = (1, 3)

# x/paramfilter: the reference blocks these from governance
# (app/app.go:739-773 blockedParams)
BLOCKED_PARAMS = frozenset(
    {
        "bank/send_enabled",
        "staking/bond_denom",
        "staking/unbonding_time",
        "staking/max_validators",
        "consensus/validator_pubkey_types",
    }
)

VOTE_OPTIONS = ("yes", "no", "abstain", "veto")


GOV_POOL = b"\x00" * 19 + b"\x04"  # module account escrowing deposits


class ParamFilterError(ValueError):
    """A proposal tried to change a blocked parameter (tx-level rejection,
    hence ValueError: DeliverTx converts it into a failed TxResult)."""


def _put(ctx, key: bytes, obj) -> None:
    put_json(ctx, key, obj)


def _get(ctx, key: bytes):
    return get_json(ctx, key)


class GovKeeper:
    PROPOSAL = b"gov/prop/"
    ACTIVE = b"gov/active"
    VOTE = b"gov/vote/"  # gov/vote/<prop_id 8B BE><voter 20B>
    NEXT_ID = b"gov/next_proposal_id"
    PARAMS = b"gov/params"

    def __init__(self, staking, bank, param_router):
        self.staking = staking
        self.bank = bank
        # param_router: dict "<module>/<key>" -> setter(ctx, value)
        self.param_router = param_router

    # -- params ---------------------------------------------------------

    def params(self, ctx: Context) -> dict:
        return _get(ctx, self.PARAMS) or {
            "min_deposit": DEFAULT_MIN_DEPOSIT,
            "max_deposit_period": DEFAULT_MAX_DEPOSIT_PERIOD,
            "voting_period": DEFAULT_VOTING_PERIOD,
        }

    def set_params(self, ctx: Context, params: dict) -> None:
        _put(ctx, self.PARAMS, params)

    # -- lifecycle -------------------------------------------------------

    @staticmethod
    def _pid_key(pid: int) -> bytes:
        if not isinstance(pid, int) or not (0 <= pid < 1 << 63):
            raise ValueError(f"invalid proposal id {pid!r}")
        return pid.to_bytes(8, "big")

    def proposal(self, ctx: Context, pid: int):
        return _get(ctx, self.PROPOSAL + self._pid_key(pid))

    def _set_proposal(self, ctx: Context, p: dict) -> None:
        _put(ctx, self.PROPOSAL + self._pid_key(p["id"]), p)

    def submit_proposal(
        self,
        ctx: Context,
        proposer: bytes,
        changes: list[dict],
        initial_deposit: int,
        title: str = "",
    ) -> int:
        """changes = [{"param": "<module>/<key>", "value": ...}]."""
        if not isinstance(changes, list) or not changes:
            raise ValueError("proposal changes must be a non-empty list")
        for c in changes:
            if not isinstance(c, dict) or not isinstance(c.get("param"), str) \
                    or "value" not in c:
                raise ValueError("each change needs a string 'param' and a 'value'")
            # paramfilter rejects blocked params up front as well as at
            # execution (gov_handler.go returns an error either way)
            if c["param"] in BLOCKED_PARAMS:
                raise ParamFilterError(f"param {c['param']!r} is not governable")
            if c["param"] not in self.param_router:
                raise ValueError(f"unroutable param {c['param']!r}")
        if initial_deposit < 0:
            raise ValueError("negative deposit")
        self.bank.send(ctx, proposer, GOV_POOL, initial_deposit)
        pid = (_get(ctx, self.NEXT_ID) or 1)
        _put(ctx, self.NEXT_ID, pid + 1)
        p = {
            "id": pid,
            "proposer": proposer.hex(),
            "title": title,
            "changes": changes,
            "deposit": initial_deposit,
            "depositors": {proposer.hex(): initial_deposit},
            "status": "deposit_period",
            "submit_time": int(ctx.time_unix),
            "voting_start": None,
            "voting_end": None,
        }
        if initial_deposit >= self.params(ctx)["min_deposit"]:
            self._activate_voting(ctx, p)
        self._set_proposal(ctx, p)
        active = _get(ctx, self.ACTIVE) or []
        active.append(pid)
        _put(ctx, self.ACTIVE, active)
        ctx.emit_event("gov.submit_proposal", id=pid)
        return pid

    def _activate_voting(self, ctx: Context, p: dict) -> None:
        p["status"] = "voting_period"
        p["voting_start"] = int(ctx.time_unix)
        p["voting_end"] = int(ctx.time_unix) + int(self.params(ctx)["voting_period"])

    def deposit(self, ctx: Context, pid: int, depositor: bytes, amount: int) -> None:
        p = self.proposal(ctx, pid)
        if p is None or p["status"] != "deposit_period":
            raise ValueError("proposal not in deposit period")
        self.bank.send(ctx, depositor, GOV_POOL, amount)
        p["deposit"] += amount
        d = p["depositors"]
        d[depositor.hex()] = d.get(depositor.hex(), 0) + amount
        if p["deposit"] >= self.params(ctx)["min_deposit"]:
            self._activate_voting(ctx, p)
        self._set_proposal(ctx, p)

    def vote(self, ctx: Context, pid: int, voter: bytes, option: str) -> None:
        if option not in VOTE_OPTIONS:
            raise ValueError(f"invalid vote option {option!r}")
        p = self.proposal(ctx, pid)
        if p is None or p["status"] != "voting_period":
            raise ValueError("proposal not in voting period")
        key = self.VOTE + self._pid_key(pid) + voter
        _put(ctx, key, {"option": option})

    # -- tally -----------------------------------------------------------

    def _votes(self, ctx: Context, pid: int) -> dict[bytes, str]:
        out = {}
        prefix = self.VOTE + self._pid_key(pid)
        for k, raw in ctx.store.iterate_prefix(prefix):
            out[k[len(prefix) :]] = json.loads(raw)["option"]
        return out

    def tally(self, ctx: Context, pid: int) -> dict:
        """SDK keeper/tally.go: delegator votes override their slice of the
        validator's inherited vote; counts are in token units."""
        votes = self._votes(ctx, pid)
        counts = {o: 0 for o in VOTE_OPTIONS}  # integer utia token units
        total_bonded = 0
        # validator base votes minus shares of delegators who voted directly
        for op, _power in self.staking.validators(ctx):
            v = self.staking.validator(ctx, op)
            total_bonded += v["tokens"]
            if v["shares"] == 0:
                continue
            # shares of delegators who voted directly get deducted from the
            # validator's inherited vote
            deducted = 0
            for voter, option in votes.items():
                if voter == op:
                    continue
                shares = self.staking.delegation(ctx, op, voter)
                if shares > 0:
                    counts[option] += shares * v["tokens"] // v["shares"]
                    deducted += shares
            if op in votes:
                counts[votes[op]] += (v["shares"] - deducted) * v["tokens"] // v["shares"]
        voted = sum(counts.values())
        result = {
            "counts": counts,
            "voted": voted,
            "total_bonded": total_bonded,
        }
        q_num, q_den = QUORUM
        v_num, v_den = VETO_THRESHOLD
        t_num, t_den = THRESHOLD
        if total_bonded == 0 or voted * q_den < total_bonded * q_num:
            result["outcome"] = "rejected_quorum"
        elif voted > 0 and counts["veto"] * v_den >= voted * v_num:
            result["outcome"] = "rejected_veto"
        else:
            non_abstain = voted - counts["abstain"]
            if non_abstain > 0 and counts["yes"] * t_den > non_abstain * t_num:
                result["outcome"] = "passed"
            else:
                result["outcome"] = "rejected"
        return result

    # -- execution / end blocker -----------------------------------------

    def _execute(self, ctx: Context, p: dict) -> None:
        """Apply param changes through the filter (the paramfilter's guarded
        handler, gov_handler.go:31-41: any blocked param fails the whole
        proposal atomically)."""
        for c in p["changes"]:
            if c["param"] in BLOCKED_PARAMS:
                raise ParamFilterError(f"param {c['param']!r} is not governable")
        for c in p["changes"]:
            self.param_router[c["param"]](ctx, c["value"])

    def end_blocker(self, ctx: Context) -> None:
        active = _get(ctx, self.ACTIVE) or []
        still_active = []
        for pid in active:
            p = self.proposal(ctx, pid)
            terminal = False
            if p["status"] == "deposit_period":
                if ctx.time_unix > p["submit_time"] + self.params(ctx)["max_deposit_period"]:
                    p["status"] = "rejected_deposit"  # deposit burned
                    self.bank.burn(ctx, GOV_POOL, p["deposit"])
                    self._set_proposal(ctx, p)
                    terminal = True
            elif p["status"] == "voting_period":
                if ctx.time_unix >= p["voting_end"]:
                    result = self.tally(ctx, p["id"])
                    outcome = result["outcome"]
                    if outcome == "passed":
                        try:
                            self._execute(ctx, p)
                            p["status"] = "passed"
                        except Exception as e:
                            # the failure is consensus state (every node
                            # records it identically); the log line is
                            # the operator's pointer to it
                            log.warning(
                                "proposal execution failed",
                                id=p["id"], err=e,
                            )
                            p["status"] = "failed"
                            p["failure"] = str(e)
                    else:
                        p["status"] = outcome
                    # deposit: burned on veto, else refunded per depositor
                    # (the SDK refunds each depositor, keeper/deposit.go)
                    if outcome == "rejected_veto":
                        self.bank.burn(ctx, GOV_POOL, p["deposit"])
                    else:
                        for addr_hex, amt in p["depositors"].items():
                            self.bank.send(
                                ctx, GOV_POOL, bytes.fromhex(addr_hex), amt
                            )
                    self._set_proposal(ctx, p)
                    terminal = True
                    ctx.emit_event(
                        "gov.proposal_result", id=p["id"], status=p["status"]
                    )
            if not terminal:
                still_active.append(pid)
        if still_active != active:
            _put(ctx, self.ACTIVE, still_active)

