"""Protocol constants, mirroring the reference's four config layers.

Reference parity (celestia-app):
- immutable share geometry: ``pkg/appconsts/global_consts.go:15-78``
- versioned consts keyed by app version: ``pkg/appconsts/{v1,v2,v3}/app_consts.go``
  + accessors ``pkg/appconsts/versioned_consts.go:20-27``
- governance-mutable initial values: ``pkg/appconsts/initial_consts.go``
- consensus timing: ``pkg/appconsts/consensus_consts.go:6-13``
"""

from __future__ import annotations

import dataclasses

# ---------------------------------------------------------------------------
# Layer 1: immutable share geometry (global_consts.go)
# ---------------------------------------------------------------------------

SHARE_SIZE = 512
NAMESPACE_VERSION_SIZE = 1
NAMESPACE_ID_SIZE = 28
NAMESPACE_SIZE = NAMESPACE_VERSION_SIZE + NAMESPACE_ID_SIZE  # 29
SHARE_INFO_BYTES = 1
SEQUENCE_LEN_BYTES = 4
SHARE_RESERVED_BYTES = 4

# Content capacity of each share variant (spec: specs/src/specs/shares.md).
FIRST_SPARSE_SHARE_CONTENT_SIZE = (
    SHARE_SIZE - NAMESPACE_SIZE - SHARE_INFO_BYTES - SEQUENCE_LEN_BYTES
)  # 478
CONTINUATION_SPARSE_SHARE_CONTENT_SIZE = (
    SHARE_SIZE - NAMESPACE_SIZE - SHARE_INFO_BYTES
)  # 482
FIRST_COMPACT_SHARE_CONTENT_SIZE = (
    SHARE_SIZE
    - NAMESPACE_SIZE
    - SHARE_INFO_BYTES
    - SEQUENCE_LEN_BYTES
    - SHARE_RESERVED_BYTES
)  # 474
CONTINUATION_COMPACT_SHARE_CONTENT_SIZE = (
    SHARE_SIZE - NAMESPACE_SIZE - SHARE_INFO_BYTES - SHARE_RESERVED_BYTES
)  # 478

SUPPORTED_SHARE_VERSIONS = (0,)
SHARE_VERSION_ZERO = 0

MIN_SQUARE_SIZE = 1
# Upper bound on axis length of the *extended* square = 2 * 512. The
# reference stops at 2*128; the mesh plane (parallel/mesh_engine.py)
# admits k=256/512 squares through the sharded pipeline, so the layout
# accounting and wire parsing must accept them. Consensus-visible
# bounds do NOT read this constant: the CONSENSUS cap (and the
# gov_max_square_size validation bound, chain/app.py) stays the
# versioned square_size_upper_bound (128) unless a chain explicitly
# raises it via the consensus-critical `max_square_size` home config
# key — this constant only bounds what the plumbing can express.
MAX_EXTENDED_SQUARE_WIDTH = 1024

# NMT node serialization: minNs(29) || maxNs(29) || sha256 digest(32).
NMT_ROOT_SIZE = 2 * NAMESPACE_SIZE + 32  # 90
HASH_SIZE = 32

# ---------------------------------------------------------------------------
# Layer 2: versioned constants (pkg/appconsts/{v1,v2,v3}/app_consts.go)
# ---------------------------------------------------------------------------

LATEST_VERSION = 3


@dataclasses.dataclass(frozen=True)
class VersionedConsts:
    square_size_upper_bound: int
    subtree_root_threshold: int
    # v2+ only; ignored (None) at v1 (minfee module absent).
    network_min_gas_price: float | None
    tx_size_cost_per_byte: int
    gas_per_blob_byte: int


_VERSIONED: dict[int, VersionedConsts] = {
    1: VersionedConsts(
        square_size_upper_bound=128,
        subtree_root_threshold=64,
        network_min_gas_price=None,
        tx_size_cost_per_byte=10,
        gas_per_blob_byte=8,
    ),
    2: VersionedConsts(
        square_size_upper_bound=128,
        subtree_root_threshold=64,
        network_min_gas_price=0.000001,
        tx_size_cost_per_byte=10,
        gas_per_blob_byte=8,
    ),
    3: VersionedConsts(
        square_size_upper_bound=128,
        subtree_root_threshold=64,
        network_min_gas_price=0.000001,
        tx_size_cost_per_byte=10,
        gas_per_blob_byte=8,
    ),
}


def versioned(app_version: int) -> VersionedConsts:
    try:
        return _VERSIONED[app_version]
    except KeyError:
        raise ValueError(f"unsupported app version {app_version}") from None


def square_size_upper_bound(app_version: int) -> int:
    return versioned(app_version).square_size_upper_bound


def subtree_root_threshold(app_version: int) -> int:
    return versioned(app_version).subtree_root_threshold


# ---------------------------------------------------------------------------
# Layer 3: governance-mutable initial values (initial_consts.go)
# ---------------------------------------------------------------------------

DEFAULT_GOV_MAX_SQUARE_SIZE = 64
DEFAULT_GAS_PER_BLOB_BYTE = 8
DEFAULT_MAX_BYTES = DEFAULT_GOV_MAX_SQUARE_SIZE**2 * CONTINUATION_SPARSE_SHARE_CONTENT_SIZE
DEFAULT_MIN_GAS_PRICE = 0.002
DEFAULT_NETWORK_MIN_GAS_PRICE = 0.000001

# Gas prices that enter CONSENSUS state / decisions are fixed-point integers
# in "atto" units (1e18 per utia-per-gas — the cosmos sdk.Dec precision).
# Floats remain only at node-local boundaries (config files, display).
ATTO = 10**18


def gas_price_to_atto(price) -> int:
    """Exact float-literal → integer atto conversion (0.002 → 2*10**15).

    Uses the decimal string of the float so config literals convert to the
    rational a human wrote, not the nearest binary double."""
    from fractions import Fraction

    if isinstance(price, int):
        return price * ATTO
    return int(Fraction(str(price)) * ATTO)
# ~7 days of 12s blocks (x/signal). CONSENSUS-CRITICAL: every validator
# in a network must agree on this value. Devnets/e2e tests shorten it via
# the provisioned home config (`upgrade_height_delay` in config.json,
# plumbed App(upgrade_height_delay=...) -> SignalKeeper the same way
# v2_upgrade_height rides) — NEVER a per-process env var, which two
# validators could silently disagree on and fork at the flip.
DEFAULT_UPGRADE_HEIGHT_DELAY = 50_400

# x/blob gas model (x/blob/types/payforblob.go:20-42,158-179)
PFB_GAS_FIXED_COST = 75_000
BYTES_PER_BLOB_INFO = 70

# ---------------------------------------------------------------------------
# Layer 4: consensus timing defaults (consensus_consts.go, default_overrides.go)
# ---------------------------------------------------------------------------

TIMEOUT_PROPOSE_SECONDS = 10.0
TIMEOUT_COMMIT_SECONDS = 11.0
GOAL_BLOCK_TIME_SECONDS = 15.0
MEMPOOL_TX_TTL_BLOCKS = 5
MEMPOOL_MAX_TX_BYTES = 128**2 * CONTINUATION_SPARSE_SHARE_CONTENT_SIZE
# CAT pool caps (celestia-core mempool config: Size / MaxTxsBytes): the
# whole pool is bounded by count AND bytes, with lowest-gas-price eviction
# once either cap is hit (mempool/cat/pool.go). Wall-clock TTL mirrors the
# height TTL at the goal block time (TTLDuration ~ TTLNumBlocks blocks).
MEMPOOL_MAX_TXS = 5000
MEMPOOL_MAX_POOL_BYTES = 64 * MEMPOOL_MAX_TX_BYTES  # ~505 MB
MEMPOOL_TX_TTL_SECONDS = MEMPOOL_TX_TTL_BLOCKS * GOAL_BLOCK_TIME_SECONDS
SNAPSHOT_INTERVAL_BLOCKS = 1500
SNAPSHOT_KEEP_RECENT = 2

BOND_DENOM = "utia"
