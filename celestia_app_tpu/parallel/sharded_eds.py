"""Multi-device EDS pipeline: row-sharded RS extension + NMT roots.

The single-chip pipeline (da/eds.py) maps the whole square onto one device.
This module shards it over a (data, seq) mesh (parallel/mesh.py):

- a batch of B squares is split over the ``data`` axis (block parallelism),
- the k rows of each square are split over the ``seq`` axis.

Dataflow per square, all inside one shard_map region (so XLA schedules the
collectives on ICI):

  1. row pass     — each device RS-extends its local rows (local matmul),
  2. all_to_all   — transpose from row-sharding to column-sharding,
  3. column pass  — extend full columns locally; this yields Q2 for original
                    columns and Q3 for parity columns at once (the product
                    code commutes: row-extending Q2 == column-extending Q1,
                    both are E·Q0·Eᵀ — data_structures.md:304-310 semantics),
  4. column NMT roots — each device hashes the column trees it owns,
  5. all_to_all   — transpose back to row-sharding,
  6. row NMT roots — each device hashes its row trees,
  7. data root    — computed outside the shard_map on the gathered 4k axis
                    roots (tiny tree; XLA inserts the all-gather).

Collectives used: 2 × all_to_all over ``seq`` (the expensive transposes ride
ICI), plus the implicit all-gather of 90-byte roots. Nothing crosses DCN.

Reference parity: same codewords and roots as rsmt2d + nmt
(pkg/da/data_availability_header.go:65-108) — asserted bit-identical against
the single-device pipeline in tests/test_sharded_eds.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from celestia_app_tpu import appconsts
from celestia_app_tpu.da import namespace as ns_mod
from celestia_app_tpu.ops import leopard, merkle, nmt, rs
from celestia_app_tpu.parallel.mesh import DATA_AXIS, SEQ_AXIS

NS = appconsts.NAMESPACE_SIZE
SHARE = appconsts.SHARE_SIZE


def _leaf_ns_local(
    sq_local: jax.Array, k: int, major_start: jax.Array
) -> jax.Array:
    """Leaf namespaces for locally-owned axis trees.

    ``sq_local`` is (B_l, M_l, 2k, SHARE): M_l major-axis entries (rows or
    columns) starting at global index ``major_start``, each a full tree of 2k
    leaves. A leaf keeps its share's own namespace prefix iff it lies in Q0 —
    global major index < k AND minor index < k — else it gets the parity
    namespace (pkg/wrapper/nmt_wrapper.go:93-114 semantics).
    """
    m_l = sq_local.shape[1]
    major = major_start + jnp.arange(m_l)
    minor = jnp.arange(2 * k)
    in_q0 = (major[:, None] < k) & (minor[None, :] < k)  # (M_l, 2k)
    parity = jnp.asarray(np.frombuffer(ns_mod.PARITY_NS_RAW, dtype=np.uint8))
    return jnp.where(in_q0[None, :, :, None], sq_local[..., :NS], parity)


def _leaf_nodes_local(
    sq_local: jax.Array, k: int, major_start: jax.Array
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """(B_l, M_l, 2k, SHARE) local axis slabs -> leaf (min, max, v) arrays,
    each (B_l, M_l, 2k, .)."""
    b_l, m_l = sq_local.shape[0], sq_local.shape[1]
    leaf_ns = _leaf_ns_local(sq_local, k, major_start)
    mins, maxs, vs = nmt.leaf_nodes(
        leaf_ns.reshape(b_l * m_l, 2 * k, NS),
        sq_local.reshape(b_l * m_l, 2 * k, SHARE),
    )
    return (
        mins.reshape(b_l, m_l, 2 * k, NS),
        maxs.reshape(b_l, m_l, 2 * k, NS),
        vs.reshape(b_l, m_l, 2 * k, 32),
    )


def _roots_from_leaves_local(
    mins: jax.Array, maxs: jax.Array, vs: jax.Array
) -> jax.Array:
    """(B_l, M_l, 2k, .) leaf nodes -> (B_l, M_l, 90) NMT roots."""
    b_l, m_l, two_k = vs.shape[0], vs.shape[1], vs.shape[2]
    roots = nmt.roots_from_leaf_nodes(
        mins.reshape(b_l * m_l, two_k, NS),
        maxs.reshape(b_l * m_l, two_k, NS),
        vs.reshape(b_l * m_l, two_k, 32),
    )
    return roots.reshape(b_l, m_l, 90)


def _local_pipeline(k: int, n_seq: int):
    """The per-device program run under shard_map."""
    mat, to_bits, from_bits, _sym_bits = rs._codec(k)  # field by k
    bit_mat = jnp.asarray(mat)

    def run(ods_local: jax.Array):
        # ods_local: (B_l, k/n, k, SHARE) — this device's slab of original rows.
        seq_idx = lax.axis_index(SEQ_AXIS)

        # 1. Row pass: extend local rows. Mixing is over the share index
        #    within each row, which is fully local.
        row_bits = to_bits(ods_local)
        q1_local = from_bits(rs._gf_mix(bit_mat, row_bits))
        top_local = jnp.concatenate([ods_local, q1_local], axis=2)
        # (B_l, k/n, 2k, S)

        # 2. Transpose to column-sharding: split the 2k columns across the
        #    mesh, gather all k original rows. One all-to-all over ICI.
        cols_local = lax.all_to_all(
            top_local, SEQ_AXIS, split_axis=2, concat_axis=1, tiled=True
        )  # (B_l, k, 2k/n, S): all original rows × this device's columns
        col_major = jnp.swapaxes(cols_local, 1, 2)  # (B_l, 2k/n, k, S)

        # 3. Column pass: extend each owned column over its k data symbols.
        #    Original columns yield Q2; parity columns yield Q3 (== E·Q0·Eᵀ).
        par_major = from_bits(
            rs._gf_mix(bit_mat, to_bits(col_major))
        )  # (B_l, 2k/n, k, S)
        eds_cols = jnp.concatenate([col_major, par_major], axis=2)
        # (B_l, 2k/n, 2k, S): full columns, column-major

        # 4. Column-tree leaf nodes + roots for owned columns. Leaf (r, c)
        #    has the identical preimage (0x00 || ns || share) in row tree r
        #    and column tree c (da/eds.pipeline_fn does the same dedup on
        #    one chip), so hash each leaf ONCE here and ship the 32-byte
        #    digests to the row owners — 1/16 the bytes of re-hashing the
        #    512-byte shares and none of the 9-block SHA work.
        col_start = seq_idx * (2 * k // n_seq)
        col_mins, col_maxs, col_vs = _leaf_nodes_local(eds_cols, k, col_start)
        col_roots_local = _roots_from_leaves_local(col_mins, col_maxs, col_vs)

        # 5. Transpose back to row-sharding for the row trees: split the 2k
        #    rows (axis 2) across devices, gather all columns on axis 1 —
        #    shares for the EDS output, digests for the row-tree leaves.
        #    Shares and digests agree on the leading (B_l, 2k/n cols, 2k
        #    rows) geometry, so they ride ONE all-to-all packed along the
        #    byte axis (one collective instead of two; also dodges an XLA
        #    CPU all-to-all combiner bug that mis-matches the two
        #    operands' layouts at small seq extents).
        packed = jnp.concatenate([eds_cols, col_vs], axis=3)
        packed_back = lax.all_to_all(
            packed, SEQ_AXIS, split_axis=2, concat_axis=1, tiled=True
        )  # (B_l, 2k cols in global order, 2k/n owned rows, S+32)
        rows_back = packed_back[..., :SHARE]
        vs_back = packed_back[..., SHARE:]
        eds_rows = jnp.swapaxes(rows_back, 1, 2)  # (B_l, 2k/n, 2k, S)
        row_vs = jnp.swapaxes(vs_back, 1, 2)  # (B_l, 2k/n, 2k, 32)

        # 6. Row NMT roots for owned rows: namespaces recomputed locally
        #    from the row slab (cheap), digests reused from step 4.
        row_start = seq_idx * (2 * k // n_seq)
        row_ns = _leaf_ns_local(eds_rows, k, row_start)
        row_roots_local = _roots_from_leaves_local(row_ns, row_ns, row_vs)

        return eds_rows, row_roots_local, col_roots_local

    return run


def sharded_pipeline_fn(mesh: Mesh, k: int):
    """Build the mesh-sharded block pipeline.

    Returns a jittable fn: (B, k, k, SHARE) u8 batch of original squares ->
    (eds (B, 2k, 2k, SHARE), row_roots (B, 2k, 90), col_roots (B, 2k, 90),
    data_roots (B, 32)), with B sharded over ``data`` and square rows over
    ``seq``.
    """
    n_seq = mesh.shape[SEQ_AXIS]
    if k % n_seq != 0:
        raise ValueError(f"seq axis {n_seq} must divide square size {k}")

    local = _local_pipeline(k, n_seq)
    specs = dict(
        mesh=mesh,
        in_specs=P(DATA_AXIS, SEQ_AXIS, None, None),
        out_specs=(
            P(DATA_AXIS, SEQ_AXIS, None, None),
            P(DATA_AXIS, SEQ_AXIS, None),
            P(DATA_AXIS, SEQ_AXIS, None),
        ),
    )
    # The SHA-256 fori_loop carries mix replicated init state (H0) with
    # device-varying data; skip VMA inference rather than thread pvary
    # through every op (outputs are all explicitly sharded anyway).
    # jax < 0.5 ships shard_map under jax.experimental with the older
    # check_rep spelling of the same knob.
    if hasattr(jax, "shard_map"):
        shard = jax.shard_map(local, check_vma=False, **specs)
    else:
        from jax.experimental.shard_map import shard_map as _shard_map

        shard = _shard_map(local, check_rep=False, **specs)

    def run(ods_batch: jax.Array):
        eds, row_roots, col_roots = shard(ods_batch)
        axis_roots = jnp.concatenate([row_roots, col_roots], axis=1)  # (B, 4k, 90)
        data_roots = jax.vmap(merkle.merkle_root_pow2)(axis_roots)
        return eds, row_roots, col_roots, data_roots

    return run


def input_sharding(mesh: Mesh) -> NamedSharding:
    """THE pipeline input placement — (B over ``data``, rows over
    ``seq``). Exposed so dispatchers (parallel/mesh_engine._run_sharded)
    can upload the batch EXPLICITLY through the transfer ledger instead
    of letting jit move it silently; using the jitted program's own
    in_sharding makes the explicit put a no-op at dispatch time."""
    return NamedSharding(mesh, P(DATA_AXIS, SEQ_AXIS, None, None))


@functools.lru_cache(maxsize=None)
def _jitted(mesh: Mesh, k: int):
    fn = sharded_pipeline_fn(mesh, k)
    return jax.jit(fn, in_shardings=input_sharding(mesh))


def jitted_sharded_pipeline(mesh: Mesh, k: int):
    """Compiled sharded pipeline, cached per (mesh, k)."""
    return _jitted(mesh, k)
