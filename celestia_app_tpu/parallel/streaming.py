"""Streaming PrepareProposal: overlap host layout with device extend+commit.

BASELINE.md config 4/5: at 2 blocks/s the proposer must not serialize
[host: square layout] → [device: RS extend + NMT roots] per block. JAX
dispatch is asynchronous — a jitted call returns device futures immediately
— so a one-deep software pipeline overlaps the device's work on block N with
the host's layout of block N+1 (the reference has no equivalent: rsmt2d
encodes synchronously on the Go heap; SURVEY §2.4 "pipeline parallelism").

`stream_blocks` is the engine; `bench_stream` measures blocks/s plus the
serial (unoverlapped) cost so the overlap win is visible in the output.
"""

from __future__ import annotations

import time

import numpy as np

from celestia_app_tpu.da import eds as eds_mod
from celestia_app_tpu.obs import xfer
from celestia_app_tpu.utils import telemetry


def _fetch_pending(pending):
    """Resolve one in-flight dispatch: ONE ``jax.device_get`` on the
    whole output tuple (a single transfer sync instead of per-leaf
    ``np.asarray`` round-trips), wall-clock routed through the telemetry
    timer so the overlap win is observable in /metrics, not just
    benchable. A fetch that arrives before the device finished counts
    ``streaming.overlap_stalls`` — the host outran the device, so the
    pipeline is device-bound there."""
    ready = getattr(pending[3], "is_ready", None)
    if ready is not None:
        try:
            if not ready():
                telemetry.incr("streaming.overlap_stalls")
        except Exception:
            # relay backends may not implement readiness probes; the
            # stall counter just degrades to "unknown" there
            telemetry.incr("streaming.readiness_unsupported")
    t0 = telemetry.start_timer()
    out = xfer.to_host(pending, "streaming.fetch")
    telemetry.measure_since("streaming.fetch", t0)
    return out


def stream_blocks(layout_fn, n_blocks: int, k: int, *, pipeline=None):
    """Run `n_blocks` through the device pipeline with one-deep overlap.

    ``layout_fn(i) -> (k, k, 512) uint8 ODS`` is the HOST work (square
    layout); the device computes block i while the host lays out block i+1.
    Returns the list of 32-byte data roots, in order.
    ``streaming.blocks_in_flight`` gauges the pipeline depth (1 while a
    dispatch is outstanding); see `_fetch_pending` for the fetch-side
    counters."""
    if n_blocks <= 0:
        return []
    run = pipeline if pipeline is not None else eds_mod.jitted_pipeline(k)
    roots: list[bytes] = []
    pending = None
    for i in range(n_blocks):
        ods = layout_fn(i)  # host: lay out block i
        # device: async dispatch (upload counted by the transfer ledger)
        out = run(xfer.to_device(ods, "streaming.dispatch"))
        telemetry.gauge("streaming.blocks_in_flight", 1)
        if pending is not None:
            roots.append(bytes(_fetch_pending(pending)[3]))  # block on i-1
        pending = out
    roots.append(bytes(_fetch_pending(pending)[3]))
    telemetry.gauge("streaming.blocks_in_flight", 0)
    return roots


def _synthetic_layout(k: int, seed: int) -> np.ndarray:
    """Stand-in host layout: generate + namespace-stamp a k×k ODS. Costs
    real host time (RNG + memory traffic) like share packing does."""
    rng = np.random.default_rng(seed)
    ods = rng.integers(0, 256, size=(k, k, 512), dtype=np.uint8)
    ods[..., :29] = 0
    ods[..., 28] = 7
    return ods


def _stream_batches(layout_fn, n_batches: int, run) -> list[bytes]:
    """THE one-deep batch-overlap loop shared by the mesh and the
    single-chip batched modes: host lays out batch i+1 while the device
    works on batch i; returns the flat list of 32-byte data roots."""
    if n_batches <= 0:
        return []
    roots: list[bytes] = []
    pending = None
    for i in range(n_batches):
        batch = layout_fn(i)  # host: lay out batch i
        out = run(batch)  # device/mesh: async dispatch
        telemetry.gauge("streaming.blocks_in_flight", batch.shape[0])
        if pending is not None:
            roots.extend(bytes(r) for r in _fetch_pending(pending)[3])
        pending = out
    roots.extend(bytes(r) for r in _fetch_pending(pending)[3])
    telemetry.gauge("streaming.blocks_in_flight", 0)
    return roots


def stream_blocks_mesh(layout_fn, n_batches: int, mesh, k: int, *,
                       pipeline=None):
    """Mesh-sharded streaming (BASELINE cfg 5): each unit is a BATCH of
    data-axis-many squares through the sharded pipeline
    (parallel/sharded_eds.py) — rows split over ``seq``, blocks over
    ``data`` — with the host laying out batch i+1 while the mesh extends
    and commits batch i. Returns the flat list of 32-byte data roots."""
    from celestia_app_tpu.parallel import sharded_eds

    run = (pipeline if pipeline is not None
           else sharded_eds.jitted_sharded_pipeline(mesh, k))
    return _stream_batches(layout_fn, n_batches, run)


def bench_stream_mesh(k: int | None = None, n_batches: int = 3,
                      n_devices: int = 8) -> dict:
    """Streamed blocks/s on an n-device mesh (BASELINE cfg 5's shape:
    256×256 streaming on 8 devices). On the TPU backend this is the real
    target; on CPU the virtual mesh demonstrates the same program."""
    import jax

    from celestia_app_tpu.parallel import mesh as mesh_mod

    devices = jax.devices()
    n_devices = min(n_devices, len(devices))
    backend = devices[0].platform
    if k is None:
        k = 256 if backend == "tpu" else 32
    mesh = mesh_mod.make_mesh(n_devices, k=k, devices=devices[:n_devices])
    batch = mesh.shape[mesh_mod.DATA_AXIS]

    from celestia_app_tpu.parallel import sharded_eds

    run = sharded_eds.jitted_sharded_pipeline(mesh, k)

    def layout(i: int):
        return np.stack(
            [_synthetic_layout(k, i * batch + j) for j in range(batch)]
        )

    warm = layout(0)
    # fetch: block_until_ready lies on the relay
    xfer.to_host(run(warm)[3], "streaming.warm")
    t0 = time.perf_counter()
    roots = stream_blocks_mesh(layout, n_batches, mesh, k, pipeline=run)
    dt = time.perf_counter() - t0
    n_blocks = n_batches * batch
    assert len(roots) == n_blocks and len(roots[0]) == 32
    return {
        "metric": f"stream_mesh_blocks_per_sec_k{k}",
        "value": round(n_blocks / dt, 3),
        "unit": "blocks/s",
        "backend": backend,
        "devices": n_devices,
        "mesh": dict(mesh.shape),
        "blocks": n_blocks,
        "elapsed_s": round(dt, 2),
    }


def bench_stream_batched(k: int | None = None, batch: int = 4,
                         n_batches: int = 3) -> dict:
    """Single-chip BATCHED streaming: one dispatch per batch of B squares
    (da/eds.jitted_pipeline_batched) with host layout overlapped — the
    one-device throughput mode (amortized launches, fuller MXU) the
    sharded mesh generalizes across chips."""
    import jax

    backend = jax.devices()[0].platform
    if k is None:
        k = 128 if backend == "tpu" else 16
    jitted = eds_mod.jitted_pipeline_batched(k)

    def run(batch_arr):
        return jitted(xfer.to_device(batch_arr, "streaming.dispatch"))

    def layout(i: int):
        return np.stack(
            [_synthetic_layout(k, i * batch + j) for j in range(batch)]
        )

    # warm the compile out of the measurement (fetch: see bench.py)
    xfer.to_host(run(layout(0))[3], "streaming.warm")
    t0 = time.perf_counter()
    roots = _stream_batches(layout, n_batches, run)
    dt = time.perf_counter() - t0
    n_blocks = batch * n_batches
    assert len(roots) == n_blocks and len(roots[0]) == 32
    return {
        "metric": f"stream_batched_blocks_per_sec_k{k}",
        "value": round(n_blocks / dt, 3),
        "unit": "blocks/s",
        "backend": backend,
        "batch": batch,
        "blocks": n_blocks,
        "elapsed_s": round(dt, 2),
    }


def bench_stream(k: int | None = None, n_blocks: int = 6) -> dict:
    """Measure streamed blocks/s vs the serial cost. ONE JSON-able dict."""
    import jax

    backend = jax.devices()[0].platform
    if k is None:
        # k=256 is the BASELINE cfg-5 target on TPU; virtual/CPU runs
        # demonstrate the overlap at a size the host can turn around
        k = 256 if backend == "tpu" else 32

    run = eds_mod.jitted_pipeline(k)
    # warm the compile out of the measurement (root FETCH, not
    # block_until_ready — the latter is a no-op on the axon relay)
    warm = _synthetic_layout(k, 0)
    xfer.to_host(run(xfer.to_device(warm, "streaming.dispatch"))[3],
                 "streaming.warm")

    # serial attribution: host layout cost, device cost
    t0 = time.perf_counter()
    layouts = [_synthetic_layout(k, i) for i in range(n_blocks)]
    host_ms = (time.perf_counter() - t0) * 1000 / n_blocks
    t0 = time.perf_counter()
    for ods in layouts:
        xfer.to_host(run(xfer.to_device(ods, "streaming.dispatch"))[3],
                     "streaming.fetch")
    device_ms = (time.perf_counter() - t0) * 1000 / n_blocks

    # streamed: layout of block i+1 overlaps device work on block i
    t0 = time.perf_counter()
    roots = stream_blocks(
        lambda i: _synthetic_layout(k, i), n_blocks, k, pipeline=run
    )
    streamed_ms = (time.perf_counter() - t0) * 1000 / n_blocks
    assert len(roots) == n_blocks and len(roots[0]) == 32

    return {
        "metric": f"stream_blocks_per_sec_k{k}",
        "value": round(1000.0 / streamed_ms, 2),
        "unit": "blocks/s",
        "backend": backend,
        "host_layout_ms": round(host_ms, 1),
        "device_ms": round(device_ms, 1),
        "serial_ms": round(host_ms + device_ms, 1),
        "streamed_ms": round(streamed_ms, 1),
    }
