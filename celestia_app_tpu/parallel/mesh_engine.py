"""The mesh plane: sharded EDS production as a first-class engine.

`parallel/sharded_eds.py` proved the program — row-sharded RS extension
with all-to-all column transposes over a (data, seq) ICI mesh, pinned
bit-identical to the single-device pipeline — but only bench/MULTICHIP
harnesses ever called it. This module is the production dispatch:

- **Engine selection.** ``edscache.compute_entry(engine="mesh")`` routes
  through here explicitly; under ``engine="auto"``/``"device"`` any
  square of ``k >= mesh_min_k()`` (env ``CELESTIA_MESH_MIN_K``, default
  256 — the SURVEY §2.4 streaming target) takes the mesh automatically
  when two or more devices exist. Lowering the knob (e.g. to 128, or to
  8 in the tier-1 tests' forced-host-device mesh) moves the boundary
  without touching the byte contract: the sharded program is pinned
  bit-identical to the single-device pipeline at every shared size
  (tests/test_sharded_eds.py, tests/test_mesh_plane.py).

- **Device-resident entries.** ``compute_entry_mesh`` returns a
  ``da/edscache.DeviceEntry``: the EDS (and, once warmed, the NMT level
  arrays) stay on the mesh; only the 90-byte axis roots and the 32-byte
  data root come back to host at construction (they ARE the commitment
  every protocol phase compares). Host bytes materialize lazily, only
  when a proof/serve path actually needs them, and every materialization
  counts ``edscache.host_crossings`` — the counter the --mesh bench pins
  at 0 per block on the warmed produce path.

- **Multi-block batched dispatch.** ``compute_entries_batched`` extends
  B squares in ONE dispatch — over the mesh's ``data`` axis when a mesh
  is active for the size, else through the single-chip vmapped program
  (da/eds.jitted_pipeline_batched) — and returns one device-resident
  entry per block. ``chain/producer.py`` feeds it the produce loop's
  speculative block plans.

- **Batch sharding for the repair/prover ops.** ``maybe_shard_batch``
  lets the pow2-bucketed batch runners (ops/rs._RepairAxesRunner,
  ops/nmt.eds_axis_roots) split their batch dimension over the flat
  device list when the mesh plane is active for the square size — the
  fused decode matmuls and the vmapped NMT reductions then run sharded
  with zero change to their programs (jit partitions by input sharding),
  so outputs stay bit-identical by construction.

Design in docs/DESIGN.md "The mesh plane"; knobs and counters in
docs/FORMATS.md §18.
"""

from __future__ import annotations

import functools
import os

import numpy as np

from celestia_app_tpu.obs import xfer
from celestia_app_tpu.utils import telemetry

# k=256 is the reference's streaming target (ROADMAP item 4 / SURVEY
# §2.4): squares at or above this size route through the mesh under
# auto/device engines. The env read is an engine-selection knob only —
# both engines are pinned bit-identical, so it can never change the
# bytes, only which silicon computes them (analyze.toml det-reach allow).
DEFAULT_MESH_MIN_K = 256


def mesh_min_k() -> int:
    """Smallest square size the auto/device engines hand to the mesh."""
    try:
        return max(1, int(os.environ.get("CELESTIA_MESH_MIN_K",
                                         str(DEFAULT_MESH_MIN_K))))
    except ValueError:
        return DEFAULT_MESH_MIN_K


def _max_devices() -> int:
    """Optional cap on how many devices the mesh plane claims
    (``CELESTIA_MESH_DEVICES``; 0/absent = all)."""
    try:
        return int(os.environ.get("CELESTIA_MESH_DEVICES", "0"))
    except ValueError:
        return 0


def _usable_device_count() -> int:
    """Devices the mesh plane may claim: jax's view, trimmed by the
    CELESTIA_MESH_DEVICES cap. 0 when no backend is usable (counted —
    the caller's engine fallback handles it)."""
    try:
        import jax

        n = len(jax.devices())
    except Exception:
        telemetry.incr("mesh.unavailable")
        return 0
    cap = _max_devices()
    if cap > 0:
        n = min(n, cap)
    return n


@functools.lru_cache(maxsize=None)
def _mesh_cached(k: int, n_devices: int):
    """(data, seq) mesh for square size k over n_devices (factoring via
    parallel/mesh.make_mesh: seq gets the largest pow2 divisor that still
    divides k). Cached per (k, n): jitted sharded programs key on the
    Mesh object, so it must be stable."""
    from celestia_app_tpu.parallel import mesh as mesh_mod

    return mesh_mod.make_mesh(n_devices, k=k)


def mesh_for(k: int):
    """The mesh the plane would run size-k squares on, or None when the
    square cannot shard across at least two devices (k=1, or a 1-device
    process — a 1-device "mesh" is just the single-chip pipeline with
    extra ceremony) or jax is unavailable. The device count is trimmed
    to the largest power of two whose ``seq`` extent divides k, so a
    single square always lands on a data=1 mesh (batch callers reuse
    the same mesh; its data axis stays 1 and batching rides vmap-style
    over the leading dim)."""
    n = _usable_device_count()
    # largest power-of-two device count <= n that divides k: the seq
    # axis then takes ALL of them (data=1), so any batch size shards
    seq = 1
    while seq * 2 <= n and k % (seq * 2) == 0:
        seq *= 2
    if seq < 2:
        return None
    return _mesh_cached(k, seq)


def mesh_for_batch(k: int, b: int):
    """Mesh for a B-block batched dispatch: the FULL device set when the
    batch divides its ``data`` extent (blocks split over ``data``, rows
    over ``seq`` — the two-axis shape the sharded pipeline was built
    for), else the seq-only single-square mesh."""
    n = _usable_device_count()
    # make_mesh keeps seq a pow2 divisor of k and puts the rest on data
    p = 1
    while p * 2 <= n:
        p *= 2
    if p >= 2:
        full = _mesh_cached(k, p)
        from celestia_app_tpu.parallel.mesh import DATA_AXIS

        if b % full.shape[DATA_AXIS] == 0:
            return full
    return mesh_for(k)


def mesh_active_for(k: int) -> bool:
    """True iff auto/device engines should route size-k squares (and
    their repair/prover batches) through the mesh."""
    return k >= mesh_min_k() and mesh_for(k) is not None


@functools.lru_cache(maxsize=None)
def _flat_mesh(n_devices: int):
    """1-D all-devices mesh for pure batch sharding (repair/root
    batches have no row dimension to split — only the batch)."""
    import jax
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()[:n_devices]), ("data",))


def maybe_shard_batch(batch: np.ndarray, k: int):
    """Shard a pow2-bucketed batch over the flat device list when the
    mesh plane is active for square size k and the batch divides evenly;
    otherwise return the input unchanged. The caller's jitted program is
    untouched — jit follows input shardings — so sharded and unsharded
    dispatches are bit-identical by construction."""
    if not mesh_active_for(k):
        return batch
    try:
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        n_dev = _usable_device_count()
        n = batch.shape[0]
        if n_dev < 2 or n < n_dev or n % n_dev != 0:
            return batch
        sharding = NamedSharding(
            _flat_mesh(n_dev), P("data", *([None] * (batch.ndim - 1)))
        )
        out = xfer.to_device(batch, "mesh.shard_batch",
                             placement=sharding)
        telemetry.incr("mesh.batch_shards")
        return out
    except Exception:
        # sharding is an optimization: any placement failure falls back
        # to the single-device dispatch, same bytes
        telemetry.incr("mesh.shard_fallbacks")
        return batch


# ---------------------------------------------------------------------------
# entry construction: the production ODS -> device-resident-entry dispatch
# ---------------------------------------------------------------------------


def _run_sharded(mesh, ods_batch: np.ndarray, k: int):
    """One sharded dispatch over a (B, k, k, 512) batch. Returns device
    (eds, row_roots, col_roots, data_roots) with the EDS left sharded.
    The upload is explicit (the pipeline's own input sharding) so the
    transfer ledger counts it instead of jit doing it silently."""
    from celestia_app_tpu.parallel import sharded_eds

    run = sharded_eds.jitted_sharded_pipeline(mesh, k)
    return run(xfer.to_device(
        ods_batch, "mesh.sharded_dispatch",
        placement=sharded_eds.input_sharding(mesh),
    ))


def compute_entry_mesh(ods: np.ndarray):
    """ODS -> device-resident entry through the sharded pipeline. The
    EDS stays on-mesh; roots/data-root (the commitment) come to host
    here — they are needed by every protocol phase and are tiny (4k x
    90 B + 32 B), so they are not host "crossings" in the counter's
    sense. Raises when no mesh is available (callers gate or catch)."""
    k = int(ods.shape[0])
    mesh = mesh_for(k)
    if mesh is None:
        raise RuntimeError("mesh engine needs >= 2 devices")
    eds_dev, rows, cols, roots = _run_sharded(mesh, ods[None], k)
    return _device_entry(eds_dev[0], rows[0], cols[0], roots[0])


def compute_entries_batched(ods_batch: np.ndarray,
                            engine: str = "auto") -> list:
    """The multi-block batched dispatch: (B, k, k, 512) -> B
    device-resident entries from ONE device program launch — the mesh's
    sharded pipeline when active for k (B rides the ``data`` axis), the
    single-chip vmapped pipeline otherwise. Counts ``da.extend_runs``
    once per block (the per-(node, height) accounting every tier-1 pin
    asserts on) plus one ``mesh.batched_dispatches``."""
    b, k = int(ods_batch.shape[0]), int(ods_batch.shape[1])
    mesh = mesh_for_batch(k, b)
    use_mesh = mesh is not None and (engine == "mesh"
                                     or mesh_active_for(k))
    t0 = telemetry.start_timer()
    if use_mesh:
        eds_dev, rows, cols, roots = _run_sharded(mesh, ods_batch, k)
    else:
        from celestia_app_tpu.da import eds as eds_mod

        eds_dev, rows, cols, roots = eds_mod.jitted_pipeline_batched(k)(
            xfer.to_device(ods_batch, "mesh.batched_dispatch")
        )
    # ONE small host fetch for the whole batch's commitments (B x 4k
    # roots + B x 32 data roots); the EDS slabs stay on device
    rows_h, cols_h, roots_h = xfer.to_host(
        (rows, cols, roots), "mesh.batched_commitments"
    )
    telemetry.incr("da.extend_runs", b)
    telemetry.incr("mesh.batched_dispatches")
    telemetry.incr("mesh.batched_blocks", b)
    telemetry.measure_since("mesh.batched_dispatch", t0)
    return [
        _device_entry(eds_dev[i], rows_h[i], cols_h[i], roots_h[i],
                      fetched=True)
        for i in range(b)
    ]


def _device_entry(eds_dev, rows, cols, root, fetched: bool = False):
    from celestia_app_tpu.da import edscache as edscache_mod
    from celestia_app_tpu.da.dah import DataAvailabilityHeader

    if not fetched:
        rows, cols, root = xfer.to_host(
            (rows, cols, root), "mesh.entry_commitments"
        )
    dah = DataAvailabilityHeader(
        row_roots=tuple(bytes(r) for r in rows),
        col_roots=tuple(bytes(c) for c in cols),
    )
    return edscache_mod.DeviceEntry(eds_dev, dah, bytes(root))
