"""Multi-host SPMD: the sharded block pipeline across OS-process hosts.

The reference scales validators per-process with no cross-node compute;
this framework's scale-out story is the opposite — ONE block pipeline
SPMD over a device mesh (parallel/sharded_eds.py). On a TPU pod the mesh
spans hosts: intra-host shards ride ICI, cross-host collectives ride DCN
(SURVEY §2.4/§5.8; the scaling-book recipe). Real multi-host hardware is
not available here, so this module proves the path the portable way:

  N OS processes x M virtual CPU devices each, joined into ONE global
  jax mesh via jax.distributed (Gloo collectives = the DCN stand-in),
  each process feeding only its LOCAL row shards of the ODS
  (multihost_utils.host_local_array_to_global_array) — the exact
  data-loading discipline a pod deployment uses: no host ever
  materializes another host's shard.

Entry points:
  worker_main(...)  — one host process (used by `multihost-dryrun`)
  spawn_dryrun(...) — driver: spawn N workers, compare every host's data
                      root against the single-host oracle, one JSON line.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time


def worker_main(process_id: int, num_processes: int, coordinator: str,
                k: int, batch: int, devices_per_host: int) -> dict:
    """Run inside a worker process AFTER env setup (JAX_PLATFORMS=cpu,
    xla_force_host_platform_device_count; axon env cleared): join the
    global mesh, feed local shards, run the pipeline, return the roots."""
    import jax

    jax.distributed.initialize(coordinator, num_processes=num_processes,
                               process_id=process_id)
    import numpy as np
    from jax.experimental import multihost_utils
    from jax.sharding import PartitionSpec as P

    from celestia_app_tpu.parallel import mesh as mesh_mod
    from celestia_app_tpu.parallel import sharded_eds
    from celestia_app_tpu.parallel.mesh import DATA_AXIS, SEQ_AXIS

    mesh = mesh_mod.make_mesh(k=k)
    n_data = mesh.shape[DATA_AXIS]
    n_seq = mesh.shape[SEQ_AXIS]
    if batch % n_data != 0:
        raise ValueError(f"batch {batch} must divide over data axis {n_data}")

    # deterministic global workload; each host slices out ONLY the shards
    # it owns (host-local view), then promotes them to a global array —
    # the pod-scale data-loading discipline (no full-array broadcast)
    rng = np.random.default_rng(1234)
    global_ods = rng.integers(
        0, 256, size=(batch, k, k, 512), dtype=np.uint8
    )
    local_rows = mesh.local_mesh.shape[SEQ_AXIS] * (k // n_seq)
    local_data = mesh.local_mesh.shape[DATA_AXIS] * (
        batch // n_data
    )
    # which global (data, seq) block this host owns: derive from the first
    # local device's coordinates in the global mesh grid
    first_local = jax.local_devices()[0]
    grid = np.asarray(mesh.devices)
    pos = np.argwhere(grid == first_local)
    d0, s0 = int(pos[0][0]), int(pos[0][1])
    b_lo = d0 * (batch // n_data)
    r_lo = s0 * (k // n_seq)
    host_local = global_ods[b_lo:b_lo + local_data,
                            r_lo:r_lo + local_rows]
    ods = multihost_utils.host_local_array_to_global_array(
        host_local, mesh,
        P(DATA_AXIS, SEQ_AXIS, None, None),
    )

    run = sharded_eds.jitted_sharded_pipeline(mesh, k)
    t0 = time.monotonic()
    _eds, _rr, _cc, data_roots = run(ods)
    roots_local = multihost_utils.process_allgather(data_roots, tiled=True)
    elapsed = time.monotonic() - t0

    # single-host oracle on block 0 (every host computes + compares)
    from celestia_app_tpu.utils import fast_host

    _, _, _, oracle_root = fast_host.pipeline_fast(global_ods[0])
    roots = np.asarray(roots_local).reshape(-1, 32)[:batch]
    ok = bytes(roots[0]) == bytes(oracle_root)
    return {
        "process_id": process_id,
        "num_processes": num_processes,
        "global_devices": len(jax.devices()),
        "local_devices": len(jax.local_devices()),
        "mesh": {"data": n_data, "seq": n_seq},
        "k": k,
        "batch": batch,
        "pipeline_s": round(elapsed, 3),
        "data_root_0": bytes(roots[0]).hex(),
        "matches_host_oracle": bool(ok),
    }


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _worker_env(devices_per_host: int) -> dict:
    import re

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flag = f"--xla_force_host_platform_device_count={devices_per_host}"
    prior = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" in prior:
        env["XLA_FLAGS"] = re.sub(
            r"--xla_force_host_platform_device_count=\d+", flag, prior
        )
    else:  # preserve any other flags the caller composed
        env["XLA_FLAGS"] = (prior + " " + flag).strip()
    env.pop("PALLAS_AXON_POOL_IPS", None)  # axon plugin hangs when its
    # relay is down, and sitecustomize registers it from the env at
    # interpreter start — clear it BEFORE the workers launch
    return env


def _run_workers(k: int, batch: int, num_processes: int,
                 devices_per_host: int, port: int,
                 timeout_s: float) -> list[dict]:
    import tempfile

    env = _worker_env(devices_per_host)
    procs, err_files = [], []
    for pid in range(num_processes):
        # stderr -> file, NOT a pipe: a later worker blocked on a full
        # stderr pipe would stop participating in collectives and wedge
        # the worker the driver is currently communicate()ing with
        ef = tempfile.NamedTemporaryFile(
            mode="w+", suffix=f".mh{pid}.err", delete=False
        )
        err_files.append(ef)
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "celestia_app_tpu", "multihost-worker",
             "--process-id", str(pid),
             "--num-processes", str(num_processes),
             "--coordinator", f"127.0.0.1:{port}",
             "--k", str(k), "--batch", str(batch),
             "--devices-per-host", str(devices_per_host)],
            stdout=subprocess.PIPE, stderr=ef, env=env, text=True,
        ))
    outs = []
    deadline = time.monotonic() + timeout_s
    try:
        for p, ef in zip(procs, err_files):
            left = max(5.0, deadline - time.monotonic())
            out, _ = p.communicate(timeout=left)
            if p.returncode != 0:
                ef.seek(0)
                raise RuntimeError(
                    f"worker failed rc={p.returncode}: {ef.read()[-800:]}"
                )
            outs.append(json.loads(out.strip().splitlines()[-1]))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for ef in err_files:
            try:
                ef.close()
                os.unlink(ef.name)
            except OSError:
                pass
    return outs


def spawn_dryrun(k: int = 16, batch: int = 2, num_processes: int = 2,
                 devices_per_host: int = 4, port: int = 0,
                 timeout_s: float = 600.0) -> dict:
    """Spawn the workers and aggregate their verdicts (the driver side of
    `python -m celestia_app_tpu multihost-dryrun`).

    The cross-host agreement claim is grounded in the oracle: EVERY host
    independently recomputes block 0's data root with the CPU reference
    pipeline and compares it to the root the global mesh handed it — all
    hosts matching the same deterministic oracle IS agreement, with no
    tautological self-comparison."""
    if num_processes < 1:
        raise ValueError("num_processes must be >= 1")
    last_err: Exception | None = None
    for _attempt in range(2):  # the free-port pick can race other jobs
        chosen = port or _free_port()
        try:
            outs = _run_workers(k, batch, num_processes, devices_per_host,
                                chosen, timeout_s)
            break
        except RuntimeError as e:
            last_err = e
            if port:  # caller pinned the port: don't mask the failure
                raise
    else:
        raise last_err  # both attempts failed
    return {
        "num_processes": num_processes,
        "devices_per_host": devices_per_host,
        "global_devices": outs[0]["global_devices"],
        "mesh": outs[0]["mesh"],
        "k": k,
        "batch": batch,
        "pipeline_s": max(o["pipeline_s"] for o in outs),
        "all_hosts_match_oracle": all(
            o["matches_host_oracle"] for o in outs
        ),
    }
