"""Device-mesh construction for the sharded DA pipeline.

The reference scales a validator with goroutine pools inside one process
(SURVEY.md §2.4); the TPU build scales over an ICI mesh instead. Two logical
axes:

- ``data``  — block-level data parallelism: independent squares (blocks /
  proposal candidates) land on different mesh slices.
- ``seq``   — the sequence-parallel analog: rows of one square are sharded
  across devices; the row↔column duality of the 2D RS code becomes an
  all-to-all transpose over this axis (SURVEY.md §5.7/§5.8).

``make_mesh`` factors the available devices into (data, seq), keeping the
``seq`` extent a power of two that divides the square size so every shard_map
block is shape-static.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
SEQ_AXIS = "seq"


def _largest_pow2_divisor(n: int) -> int:
    p = 1
    while n % (2 * p) == 0:
        p *= 2
    return p


def make_mesh(
    n_devices: int | None = None,
    *,
    k: int | None = None,
    devices=None,
) -> Mesh:
    """Build a (data, seq) mesh over ``n_devices`` (default: all devices).

    ``seq`` gets the largest power-of-two factor that still divides ``k``
    (when given) so row sharding of a k×k square is exact; the remainder goes
    to ``data``.
    """
    if devices is None:
        devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    devices = devices[:n_devices]
    seq = _largest_pow2_divisor(n_devices)
    if k is not None:
        while seq > 1 and k % seq != 0:
            seq //= 2
    data = n_devices // seq
    assert data * seq == n_devices  # seq is always a divisor of n_devices
    grid = np.array(devices).reshape(data, seq)
    return Mesh(grid, (DATA_AXIS, SEQ_AXIS))
