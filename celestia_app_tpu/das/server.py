"""DAS server plane: batched sample-proof serving from committed blocks.

The serving half of the celestia-node DASer story (PAPER §1, SURVEY §1):
millions of light clients hammer full nodes with cell-proof requests, so
the full-node side must answer them from *cached row trees*, never by
rehashing per request. Every height's trees come from the one batched
device pass `da/proof_device.BlockProver` already runs (ops/nmt.nmt_levels
— vmapped SHA-256 on device engines, the bit-identical fast_host SIMD
levels on host engines); each served proof is then pure index arithmetic.
Entries sit behind a bounded LRU keyed by height — the same discipline as
the DA service's square cache (service/da_service.DACore).

Routes (mounted on the node HTTP service and the standalone das-serve
sidecar; wire format in docs/FORMATS.md §7 and §17):

  GET  /das/head                        serving tip {"height": H}
  GET  /das/header?height=H             DAH (row+col roots) + data root
                                        (+ "pack" advertisement, §17.2)
  GET  /das/sample?height&row&col[&axis]   one cell + NMT proof
  POST /das/samples {height, cells, axis?} batched multi-cell variant
  POST /das/samples {groups: [{height, cells}...], axis?}
                                        multi-HEIGHT batched variant: one
                                        round-trip serves a whole catch-up
                                        window, entries resolved in one
                                        pass and dispatched per scheme/k
                                        bucket (§17.1)
  POST /das/headers {heights: [...]}    batched commitments docs
  GET  /das/pack?height=H               proof-pack manifest (§17.2)
  GET  /das/pack/chunk?height=H&index=I raw pack chunk bytes — static
                                        serving, no lock, no assembly
  GET  /das/availability?height=H       per-height serving record

`axis` selects which committed root the proof hangs under: "row" (the
sampler default) or "col" — orthogonal-axis proofs are exactly the
`ShareWithProof` members a bad-encoding fraud proof carries
(da/fraud.py), so an escalating DASer can assemble a BEFP from served
cells alone. Column trees are the row trees of the TRANSPOSED square
(the pkg/wrapper leaf-namespace rule is transpose-invariant: parity iff
outside Q0), so the col prover reuses the same batched device path with
zero new hashing code.

Serving-plane split (FORMATS §17.4): live assembly counts
``das.live_assembled``; static pack serving counts ``das.pack_hits`` /
``das.pack_misses`` — both split per height in the /das/availability
record, so operators can see how much of the fleet's demand the static
path absorbs. Pack routes never build an entry (and never extend): the
height's data root resolves from the serving cache or the block store.

Fault injection: `withhold(height, cells)` makes the server refuse those
cells — the adversarial fixture the DASer e2e uses to model a
withholding producer (tests/test_das.py). Note the withholding gate
models the LIVE path; a node serving static packs is the honest-server
shape (a real withholder simply does not publish packs).

Block-plane integration (PR 8): heights are backed by the app's
content-addressed EDS/DAH cache (da/edscache.py). `App.commit` hands each
committed entry here via `seed_cache_entry` (registered on
`app.da_seed_listeners`) from the warmer's background thread with its
provers pre-built, so the first sample after a commit never rebuilds or
re-extends; misses single-flight through `_entry` so concurrent samplers
of a fresh height pay one build between them.
"""

from __future__ import annotations

import collections
import threading

from celestia_app_tpu.da import codec as codec_mod
from celestia_app_tpu.da import edscache as edscache_mod
from celestia_app_tpu.da.dah import DataAvailabilityHeader, ExtendedDataSquare
from celestia_app_tpu.das import packs as packs_mod
from celestia_app_tpu.utils import telemetry


class SampleError(ValueError):
    """Client-side problem (bad coordinates, unknown height, withheld
    cell): transports map it to a 4xx, never a 500."""


class _Entry:
    """A served height: thin view over the block plane's EdsCacheEntry
    (da/edscache.py), which owns the EDS/DAH/roots and builds the row and
    col provers at most once — lazily under its own lock, or ahead of
    demand by the commit warmer that seeded it here."""

    def __init__(self, height: int, cache_entry: edscache_mod.EdsCacheEntry,
                 engine: str):
        self.height = height
        self.cache_entry = cache_entry
        self.engine = engine
        # resolved-prover memo: per-cell proving must not pay the cache
        # entry's lock per proof (benign race — get_prover is idempotent
        # and returns the one entry-owned instance)
        self._prover_view = None
        self._col_prover_view = None

    @property
    def dah(self):
        """The scheme's commitments object (a DataAvailabilityHeader
        under rs2d-nmt, a CmtCommitments under cmt-ldpc)."""
        return self.cache_entry.dah

    @property
    def scheme(self) -> str:
        return self.cache_entry.scheme

    @property
    def width(self) -> int:
        """Extended-square width (2k) — the geometry stat availability
        records carry for every scheme."""
        return 2 * self.cache_entry.k

    @property
    def root(self) -> bytes:
        return self.cache_entry.data_root

    @property
    def prover(self):
        if self._prover_view is None:
            self._prover_view = self.cache_entry.get_prover(self.engine)
        return self._prover_view

    @property
    def col_prover(self):
        if self._col_prover_view is None:
            self._col_prover_view = \
                self.cache_entry.get_col_prover(self.engine)
        return self._col_prover_view


def _b64(b: bytes) -> str:
    import base64

    return base64.b64encode(b).decode()


class SampleCore:
    """Per-height sample serving over an App's committed blocks.

    Thread-safe: HTTP handler threads call `sample`/`sample_many`
    concurrently; the entry cache and availability records are guarded.
    Proof generation itself is lock-free index arithmetic on immutable
    level arrays, so concurrent samplers never serialize on hashing."""

    def __init__(self, app, cache_heights: int = 4,
                 availability_keep: int = 256, app_lock=None,
                 pack_store: packs_mod.PackStore | None = None):
        self.app = app
        # writer lock of the process hosting the app (NodeService shares
        # its service lock): square REBUILDS take it so serving never
        # races a commit mid-store; cached-entry serving stays lock-free
        self.app_lock = app_lock
        # the static proof-pack store (das/packs.py): built at warm time
        # by the app's ProverWarmer, served here as raw bytes. Defaults
        # to the app's own (<home>/packs); None on pack-less processes.
        self.pack_store = (pack_store if pack_store is not None
                           else getattr(app, "pack_store", None))
        self._cache: collections.OrderedDict[int, _Entry] = \
            collections.OrderedDict()
        self._cache_heights = cache_heights
        self._availability_keep = availability_keep
        self._lock = threading.Lock()
        # height -> build in progress (single-flight: concurrent samplers
        # of a fresh height pay ONE square build between them)
        self._inflight: dict[int, threading.Event] = {}  # guarded-by: _lock
        # height -> serving record (exposed at /das/availability)
        self._availability: dict[int, dict] = {}
        self._withheld: dict[int, set[tuple[int, int]]] = {}
        self._max_seeded = 0  # seeded entries can sit above app.height

    # -- entries ---------------------------------------------------------

    def _engine(self) -> str:
        return getattr(self.app, "engine", "host")

    def _entry(self, height: int) -> _Entry:
        """Cached serving entry for a height; misses are single-flight.

        Two handler threads missing the same height used to both run the
        full square rebuild under the app lock; now the first registers
        an in-progress event and builds, later arrivals wait on it
        (counted ``das.entry_coalesced``) and re-read the cache. A failed
        build wakes the waiters, and whichever retries first becomes the
        next builder — an error never wedges the height."""
        while True:
            with self._lock:
                hit = self._cache.get(height)
                if hit is not None:
                    self._cache.move_to_end(height)
                    return hit
                ev = self._inflight.get(height)
                if ev is None:
                    ev = threading.Event()
                    self._inflight[height] = ev
                    break
            telemetry.incr("das.entry_coalesced")
            ev.wait()
        try:
            return self._build_entry(height)
        finally:
            with self._lock:
                self._inflight.pop(height, None)
            ev.set()

    def _build_entry(self, height: int) -> _Entry:
        import contextlib

        from celestia_app_tpu.chain.query import QueryError, \
            build_prover_entry

        t0 = telemetry.start_timer()
        guard = self.app_lock if self.app_lock is not None \
            else contextlib.nullcontext()
        try:
            with guard:
                _block, _square, cache_entry = \
                    build_prover_entry(self.app, height)
        except (QueryError, FileNotFoundError, KeyError, ValueError) as e:
            raise SampleError(f"no servable square at height {height}: {e}") \
                from None
        telemetry.incr("das.square_builds")
        telemetry.measure_since("das.square_build", t0)
        entry = _Entry(height, cache_entry, self._engine())
        self._remember(entry)
        return entry

    def seed_cache_entry(self, height: int,
                         cache_entry: edscache_mod.EdsCacheEntry) -> None:
        """The commit warmer's handoff (App.da_seed_listeners): serve the
        entry the lifecycle already computed — its provers are typically
        pre-built by the warmer, so the first sample after commit is pure
        index arithmetic, with no rebuild and no ``das.square_build``."""
        telemetry.incr("edscache.seeded")
        self._seed(height, cache_entry)

    def seed_entry(self, height: int,
                   eds: ExtendedDataSquare,
                   dah: DataAvailabilityHeader) -> None:
        """Serve a square already in memory (a block adopted via gossip /
        blocksync whose EDS never hit the tx store, or a test fixture) —
        bypasses the rebuild-from-txs path but NOT the proof path.
        Counted apart from the commit warmer's handoffs
        (``edscache.seeded_external`` vs ``edscache.seeded``) so /metrics
        distinguishes lifecycle seeding from gossip/fixture seeding."""
        telemetry.incr("edscache.seeded_external")
        self._seed(height, edscache_mod.EdsCacheEntry(eds, dah, dah.hash()))

    def seed_scheme_entry(self, height: int, cache_entry) -> None:
        """Scheme-generic twin of seed_entry: serve ANY codec-plane
        entry already in memory (e.g. a da/cmt.CmtEntry a test fixture
        or gossip handoff holds). Counted with the external seeds."""
        telemetry.incr("edscache.seeded_external")
        self._seed(height, cache_entry)

    def _seed(self, height: int,
              cache_entry: edscache_mod.EdsCacheEntry) -> None:
        self._remember(_Entry(height, cache_entry, self._engine()))
        with self._lock:
            self._max_seeded = max(self._max_seeded, height)

    def _remember(self, entry: _Entry) -> None:
        with self._lock:
            self._cache[entry.height] = entry
            self._cache.move_to_end(entry.height)
            while len(self._cache) > self._cache_heights:
                self._cache.popitem(last=False)

    def _col_prover(self, entry: _Entry):
        """Column-axis prover (BEFP escalation serving) — owned by the
        cache entry (da/edscache.EdsCacheEntry.get_col_prover), which
        builds it at most once under its own lock; the commit warmer
        usually pre-built it already."""
        return entry.col_prover

    # -- fault injection (tests / adversarial simulation) ----------------

    def withhold(self, height: int, cells) -> None:
        """Refuse to serve the given (row, col) cells of a height — the
        withholding-producer fixture. Idempotent; cumulative per height."""
        with self._lock:
            self._withheld.setdefault(height, set()).update(
                (int(r), int(c)) for r, c in cells
            )

    # -- serving ---------------------------------------------------------

    def head(self) -> dict:
        """The serving tip: the chain's committed height, or higher when
        a seeded square (gossip/blocksync handoff) sits above it."""
        return {"height": max(self.app.height, self._max_seeded)}

    def header(self, height: int) -> dict:
        """The scheme's commitments doc (+height): the old DAH shape
        (row/col roots) under rs2d-nmt — now with a "scheme" member old
        clients ignore — or the CMT parameter/root-hash doc (FORMATS
        §16.2). Either binds to the certified data root."""
        entry = self._entry(height)
        codec = codec_mod.get(entry.scheme)
        doc = {"height": height,
               **codec.commitments_doc(entry.cache_entry)}
        # pack advertisement (§17.2): zero-extra-round-trip discovery —
        # a sampler that just fetched commitments knows whether (and how)
        # this height is servable as static bytes. Old clients ignore it.
        pack = self._pack_advert(entry)
        if pack is not None:
            doc["pack"] = pack
        return doc

    def _one(self, entry: _Entry, row: int, col: int, axis: str) -> dict:
        if entry.scheme == codec_mod.RS2D_NAME:
            width = len(entry.dah.row_roots)
            if not (0 <= row < width and 0 <= col < width):
                raise SampleError(
                    f"cell ({row}, {col}) outside the {width}x{width} "
                    "square"
                )
        # ONE withholding/fault gate for every scheme: (row, col) is the
        # generic wire cell pair ((layer, index) for non-default codecs)
        held = self._withheld.get(entry.height)
        if held and (row, col) in held:
            self._note(entry, withheld=1)
            raise SampleError(f"cell ({row}, {col}) not served")
        # env/endpoint-armable twin of withhold(): a "drop"/"error" fault
        # at das.serve_sample makes THIS node a withholding producer for
        # matching cells without any in-process fixture access
        from celestia_app_tpu import faults

        if faults.fire("das.serve_sample", height=entry.height,
                       row=row, col=col) in ("drop", "error"):
            self._note(entry, withheld=1)
            raise SampleError(f"cell ({row}, {col}) not served")
        if entry.scheme != codec_mod.RS2D_NAME:
            return self._one_codec(entry, row, col)
        if axis == "row":
            # the shared doc builder (das/packs.live_cell_doc): the pack
            # builder runs the SAME function, so pack bytes ≡ live bytes
            # by construction (pinned in tests/test_serving.py)
            return packs_mod.live_cell_doc(
                entry.cache_entry, (row, col), prover=entry.prover)
        # transposed prover: cell (row, col) lives at (col, row) of
        # the transpose; its proof hangs under col_roots[col] and
        # covers leaf range [row, row+1)
        share, proof = self._col_prover(entry).prove_cell(col, row)
        return {
            "row": row,
            "col": col,
            "share": _b64(share),
            "proof": {
                "start": proof.start,
                "end": proof.end,
                "total": proof.total,
                "nodes": [_b64(n) for n in proof.nodes],
            },
        }

    def _one_codec(self, entry: _Entry, layer: int, index: int) -> dict:
        """Non-default-scheme cell: the wire (row, col) pair is the
        scheme's (layer, index) — FORMATS §16.3. The withholding fixture
        and the das.serve_sample fault point already gated in _one."""
        try:
            return packs_mod.live_cell_doc(entry.cache_entry,
                                           (layer, index))
        except codec_mod.CodecError as e:
            raise SampleError(str(e)) from None

    def sample(self, height: int, row: int, col: int,
               axis: str = "row") -> dict:
        out = self.sample_many(height, [(row, col)], axis=axis)
        one = out["samples"][0]
        if "error" in one:
            raise SampleError(one["error"])
        return {**out, "samples": [one]}

    def sample_many(self, height: int, cells, axis: str = "row") -> dict:
        """Batched multi-cell serving: one tree lookup, N index-arithmetic
        proofs. Per-cell failures (withheld, out of range) come back as
        {"row","col","error"} members so a partially-served batch still
        helps a reconstructing DASer."""
        cells = self._check_cells(cells, axis)
        entry = self._entry(height)
        return self._serve_group(entry, height, cells, axis)

    @staticmethod
    def _check_cells(cells, axis: str) -> list[tuple[int, int]]:
        if axis not in ("row", "col"):
            raise SampleError(f"axis must be 'row' or 'col', not {axis!r}")
        cells = [(int(r), int(c)) for r, c in cells]
        if not cells:
            raise SampleError("empty cell list")
        return cells

    def _serve_group(self, entry: _Entry, height: int,
                     cells: list[tuple[int, int]], axis: str) -> dict:
        """One height's batch against a resolved entry — THE one serving
        body behind both the single-height POST /das/samples and every
        group of the multi-height variant, so the two responses are
        byte-identical per height by construction (pinned in
        tests/test_serving.py)."""
        from celestia_app_tpu import obs

        # serve-side span of the DAS round-trip: the height's
        # deterministic trace id matches the sampling light node's, and
        # the incoming X-Celestia-Trace header (begin_request) makes the
        # sampler's fetch span this span's remote parent
        with obs.span(
            "das.serve_sample",
            traces=getattr(self.app, "traces", None),
            trace_id=obs.trace_id_for(
                getattr(self.app, "chain_id", ""), height
            ),
            height=height, cells=len(cells), axis=axis,
        ) as sp:
            t0 = telemetry.start_timer()
            samples = []
            served = 0
            for r, c in cells:
                try:
                    samples.append(self._one(entry, r, c, axis))
                    served += 1
                except SampleError as e:
                    samples.append({"row": r, "col": c, "error": str(e)})
            sp.set(served=served)
        telemetry.measure_since("das.sample_batch", t0)
        telemetry.incr("das.samples_served", served)
        # serving-plane accounting (FORMATS §17.4): these samples were
        # assembled live — the pack counters' counterpart
        telemetry.incr("das.live_assembled", served)
        telemetry.incr("das.sample_batches")
        self._note(entry, served=served, batches=1,
                   col_proofs=served if axis == "col" else 0,
                   live=served)
        return {
            "height": height,
            "data_root": entry.root.hex(),
            "scheme": entry.scheme,
            "axis": axis,
            "square_width": entry.width,
            "samples": samples,
        }

    def sample_groups(self, groups, axis: str = "row") -> dict:
        """Multi-height batched serving: one request resolves every
        group's height against the edscache in ONE pass, then serves the
        groups in (scheme, k) bucket order — heights sharing a codec
        dispatch shape run back to back, the batching the device path
        wants — while the response keeps the REQUEST order (each member
        byte-identical to the single-height response for that group).
        A height that cannot be resolved yields {"height", "error"} so
        the rest of the window still serves."""
        if not isinstance(groups, list) or not groups:
            raise SampleError("samples needs a non-empty 'groups' list")
        parsed: list[tuple[int, list[tuple[int, int]]]] = []
        for g in groups:
            try:
                height = int(g["height"])
            except (KeyError, TypeError, ValueError):
                raise SampleError(
                    "each group needs an integer 'height'") from None
            cells = g.get("cells")
            if not isinstance(cells, list):
                raise SampleError(
                    f"group for height {height} needs a 'cells' list")
            try:
                parsed.append((height, self._check_cells(cells, axis)))
            except SampleError:
                raise  # already the accurate message (empty list, axis)
            except (TypeError, ValueError):
                raise SampleError(
                    "each cell must be a [row, col] pair") from None
        # resolve every entry first (single-flight per height), bucketing
        # by (scheme, k) so same-shape heights serve consecutively
        resolved: dict[int, _Entry | SampleError] = {}
        for height, _cells in parsed:
            if height in resolved:
                continue
            try:
                resolved[height] = self._entry(height)
            except SampleError as e:
                resolved[height] = e
        order = sorted(
            range(len(parsed)),
            key=lambda i: (
                (resolved[parsed[i][0]].scheme,
                 resolved[parsed[i][0]].cache_entry.k)
                if isinstance(resolved[parsed[i][0]], _Entry)
                else ("", 0),
                i,
            ),
        )
        out: list[dict | None] = [None] * len(parsed)
        for i in order:
            height, cells = parsed[i]
            got = resolved[height]
            if isinstance(got, SampleError):
                out[i] = {"height": height, "error": str(got)}
                continue
            out[i] = self._serve_group(got, height, cells, axis)
        telemetry.incr("das.multi_height_batches")
        telemetry.incr("das.batch_heights", len(parsed))
        return {"axis": axis, "groups": out}

    def headers_many(self, heights) -> dict:
        """Batched commitments docs — the window sampler's one-round-trip
        header fetch. Per-height failures come back as {"height",
        "error"} members."""
        if not isinstance(heights, list) or not heights:
            raise SampleError("headers needs a non-empty 'heights' list")
        docs = []
        for h in heights:
            try:
                docs.append(self.header(int(h)))
            except SampleError as e:
                docs.append({"height": int(h), "error": str(e)})
            except (TypeError, ValueError):
                raise SampleError(
                    "each height must be an integer") from None
        return {"headers": docs}

    # -- proof packs (static serving; das/packs.py) ----------------------

    def _pack_root(self, height: int) -> bytes:
        """The height's data root WITHOUT building a square: cached
        serving entries first, then the durable block store. Raises
        SampleError when the height is unknown — pack routes must never
        trigger an extend."""
        with self._lock:
            hit = self._cache.get(height)
        if hit is not None:
            return hit.root
        db = getattr(self.app, "db", None)
        if db is not None:
            try:
                return db.load_block(height).header.data_hash
            except (OSError, KeyError, ValueError):
                pass
        # an unknown height is a pack miss too (global counter only: a
        # per-height record here would let an unauthenticated request
        # stream for arbitrary heights evict every genuine record from
        # the bounded availability map)
        telemetry.incr("das.pack_misses")
        raise SampleError(f"pack for height {height} not served")

    def pack_manifest(self, height: int) -> dict:
        """GET /das/pack: the height's pack manifest, or a 404-mapped
        refusal when no complete pack exists (counted das.pack_misses —
        the sampler falls back to live assembly)."""
        if self.pack_store is None:
            telemetry.incr("das.pack_misses")
            raise SampleError(f"pack for height {height} not served")
        m = self.pack_store.manifest(self._pack_root(height))
        if m is None:
            telemetry.incr("das.pack_misses")
            self._note_height(height, pack_misses=1)
            raise SampleError(f"pack for height {height} not served")
        return m

    def pack_chunk(self, height: int, index: int) -> bytes:
        """GET /das/pack/chunk: raw chunk bytes straight from disk — no
        lock, no assembly, no JSON; the CDN-shaped hot path. Counted
        das.pack_hits (misses das.pack_misses)."""
        if self.pack_store is None:
            telemetry.incr("das.pack_misses")
            raise SampleError(f"pack for height {height} not served")
        try:
            data = self.pack_store.chunk(self._pack_root(height), index)
        except packs_mod.PackError as e:
            telemetry.incr("das.pack_misses")
            self._note_height(height, pack_misses=1)
            raise SampleError(str(e)) from None
        telemetry.incr("das.pack_hits")
        self._note_height(height, pack_hits=1)
        return data

    def _pack_advert(self, entry: _Entry) -> dict | None:
        """The compact pack advertisement riding /das/header (§17.2), or
        None when this node serves no pack for the height."""
        if self.pack_store is None:
            return None
        m = self.pack_store.manifest(entry.root)
        if m is None:
            return None
        return packs_mod.advertised(m)

    # -- availability records -------------------------------------------

    _RECORD_ZEROS = (
        "samples_served", "batches", "withheld_refusals",
        "col_proofs_served", "pack_hits", "pack_misses", "live_assembled",
    )

    def _record_locked(self, height: int, data_root: str | None,
                       width: int | None) -> dict:
        rec = self._availability.get(height)
        if rec is None:
            rec = self._availability[height] = {
                "height": height,
                "data_root": data_root,
                "square_width": width,
                # mesh plane: where the height's square bytes live
                # ("device" until a proof materializes them — see
                # da/edscache.DeviceEntry; "host" for classic entries)
                "residency": None,
                **{k: 0 for k in self._RECORD_ZEROS},
            }
        elif rec["data_root"] is None and data_root is not None:
            # a pack-only record learns its identity when live serving
            # (or a later pack route) resolves the entry
            rec["data_root"] = data_root
            rec["square_width"] = width
        return rec

    def _note(self, entry: _Entry, served: int = 0, batches: int = 0,
              withheld: int = 0, col_proofs: int = 0,
              live: int = 0) -> None:
        residency = entry.cache_entry.residency() \
            if hasattr(entry.cache_entry, "residency") else "host"
        with self._lock:
            rec = self._record_locked(entry.height, entry.root.hex(),
                                      entry.width)
            rec["residency"] = residency
            rec["samples_served"] += served
            rec["batches"] += batches
            rec["withheld_refusals"] += withheld
            rec["col_proofs_served"] += col_proofs
            rec["live_assembled"] += live
            while len(self._availability) > self._availability_keep:
                self._availability.pop(min(self._availability))

    def _note_height(self, height: int, pack_hits: int = 0,
                     pack_misses: int = 0) -> None:
        """Pack-route bookkeeping: records pack serving for heights the
        live plane may never have resolved (static chunk serving builds
        no entry on purpose)."""
        with self._lock:
            rec = self._record_locked(height, None, None)
            rec["pack_hits"] += pack_hits
            rec["pack_misses"] += pack_misses
            while len(self._availability) > self._availability_keep:
                self._availability.pop(min(self._availability))

    def availability(self, height: int) -> dict:
        with self._lock:
            rec = self._availability.get(height)
            if rec is not None:
                return dict(rec)
        # never-served height: the same record shape with null identity
        # fields (FORMATS.md §7.1) so clients can read one schema
        return {"height": height, "data_root": None, "square_width": None,
                "residency": None, **{k: 0 for k in self._RECORD_ZEROS}}


# -- one router shared by every transport -----------------------------------


def route_das(core: SampleCore, method: str, path: str,
              query: dict, payload: dict | None = None):
    """Dispatch a /das/* request. `query` holds the GET params (strings);
    POST bodies arrive in `payload`. Raises SampleError for every
    malformed input (transports answer 4xx). Returns a JSON-able dict —
    or raw ``bytes`` for /das/pack/chunk, which the transports send as
    application/octet-stream (the chain/sync.route_sync convention)."""

    def _int(src: dict, key: str) -> int:
        try:
            v = src[key]
            return int(v[0] if isinstance(v, list) else v)
        except (KeyError, IndexError, TypeError, ValueError):
            raise SampleError(f"missing/invalid integer field {key!r}") \
                from None

    def _axis(src: dict) -> str:
        v = src.get("axis", "row")
        return v[0] if isinstance(v, list) else v

    if method == "GET":
        if path == "/das/head":
            return core.head()
        if path == "/das/header":
            return core.header(_int(query, "height"))
        if path == "/das/sample":
            return core.sample(_int(query, "height"), _int(query, "row"),
                               _int(query, "col"), axis=_axis(query))
        if path == "/das/availability":
            return core.availability(_int(query, "height"))
        if path == "/das/pack":
            return core.pack_manifest(_int(query, "height"))
        if path == "/das/pack/chunk":
            return core.pack_chunk(_int(query, "height"),
                                   _int(query, "index"))
    elif method == "POST" and path == "/das/samples":
        payload = payload or {}
        if "groups" in payload:
            # the multi-height window variant (§17.1): the legacy
            # single-height body stays exactly as it was
            return core.sample_groups(payload["groups"],
                                      axis=_axis(payload))
        cells = payload.get("cells")
        if not isinstance(cells, list):
            raise SampleError("samples needs a 'cells' list of [row, col]")
        try:
            pairs = [(int(r), int(c)) for r, c in cells]
        except (TypeError, ValueError):
            raise SampleError("each cell must be a [row, col] pair") \
                from None
        return core.sample_many(_int(payload, "height"), pairs,
                                axis=_axis(payload))
    elif method == "POST" and path == "/das/headers":
        payload = payload or {}
        return core.headers_many(payload.get("heights"))
    raise SampleError(f"no DAS route {method} {path}")


class SampleService:
    """Standalone HTTP server for the DAS routes — the das-serve sidecar:
    point it at a full node's home and it answers samplers with no chain
    process attached (blocks come from the durable store)."""

    def __init__(self, core: SampleCore, host: str = "127.0.0.1",
                 port: int = 26660):
        import json
        from http.server import (
            BaseHTTPRequestHandler,
            ThreadingHTTPServer,
        )
        from urllib.parse import parse_qs, urlparse

        service = self
        self.core = core

        class Handler(BaseHTTPRequestHandler):
            # keep-alive (HTTP/1.1): samplers hold persistent
            # connections; every response sets Content-Length
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _send(self, code: int, obj) -> None:
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _send_raw(self, code: int, body: bytes) -> None:
                # /das/pack/chunk serves raw bytes (octet-stream, NOT
                # base64) — the static CDN-shaped path
                self.send_response(code)
                self.send_header("Content-Type",
                                 "application/octet-stream")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _route(self, method: str, payload: dict | None) -> None:
                parsed = urlparse(self.path)
                try:
                    out = route_das(service.core, method, parsed.path,
                                    parse_qs(parsed.query), payload)
                    if isinstance(out, bytes):
                        self._send_raw(200, out)
                    else:
                        self._send(200, out)
                except SampleError as e:
                    self._send(404 if "not served" in str(e) else 400,
                               {"error": str(e)})
                except Exception as e:  # never kill the serving thread
                    telemetry.incr("das.server_errors")
                    self._send(500, {"error": f"{type(e).__name__}: {e}"})

            def do_GET(self):
                self._route("GET", None)

            def do_POST(self):
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    payload = json.loads(self.rfile.read(n) or b"{}")
                except ValueError:
                    self._send(400, {"error": "body must be JSON"})
                    return
                self._route("POST", payload)

        class Server(ThreadingHTTPServer):
            # sampler fleets connect in bursts; the stdlib default
            # listen backlog of 5 resets most of a burst on arrival
            request_queue_size = 1024

        self._httpd = Server((host, port), Handler)
        self.port = self._httpd.server_address[1]
        # GIL-pressure sampler for this serving plane (no-op unless
        # CELESTIA_OBS is on): gil.pressure{service="das"} in /metrics
        from celestia_app_tpu.obs import gil
        gil.start("das")

    def serve_background(self):
        t = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        t.start()
        return self

    def serve_forever(self):
        self._httpd.serve_forever()

    def shutdown(self):
        self._httpd.shutdown()
