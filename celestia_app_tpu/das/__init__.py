"""DAS plane: sample-proof serving (full nodes) + DASer daemon (light
nodes) — the celestia-node DASer analog over this framework's DA core.

Server plane: das/server.py (SampleCore + routes + standalone service)
with das/packs.py static proof packs behind /das/pack*.
Client plane: das/daser.py (DASer) over das/checkpoint.py persistence.
"""

from celestia_app_tpu.das.checkpoint import Checkpoint, CheckpointStore
from celestia_app_tpu.das.daser import DASer, DASerConfig, PeerSet
from celestia_app_tpu.das.packs import PackError, PackStore
from celestia_app_tpu.das.server import (
    SampleCore,
    SampleError,
    SampleService,
    route_das,
)

__all__ = [
    "Checkpoint",
    "CheckpointStore",
    "DASer",
    "DASerConfig",
    "PackError",
    "PackStore",
    "PeerSet",
    "SampleCore",
    "SampleError",
    "SampleService",
    "route_das",
]
