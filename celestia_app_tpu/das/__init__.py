"""DAS plane: sample-proof serving (full nodes) + DASer daemon (light
nodes) — the celestia-node DASer analog over this framework's DA core.

Server plane: das/server.py (SampleCore + routes + standalone service).
Client plane: das/daser.py (DASer) over das/checkpoint.py persistence.
"""

from celestia_app_tpu.das.checkpoint import Checkpoint, CheckpointStore
from celestia_app_tpu.das.daser import DASer, DASerConfig, PeerSet
from celestia_app_tpu.das.server import (
    SampleCore,
    SampleError,
    SampleService,
    route_das,
)

__all__ = [
    "Checkpoint",
    "CheckpointStore",
    "DASer",
    "DASerConfig",
    "PeerSet",
    "SampleCore",
    "SampleError",
    "SampleService",
    "route_das",
]
